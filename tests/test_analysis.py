"""paxlint self-tests: every rule family catches its seeded violation
class (and stays quiet on the clean twin), pragmas suppress, the
baseline round-trips, and the repo itself gates green.

Fixtures are tiny synthetic packages written to a tmp dir -- paxlint is
purely AST-based, so nothing is imported or executed.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap

from frankenpaxos_tpu.analysis import baseline as baseline_mod
from frankenpaxos_tpu.analysis.core import Project, run_rules


def project(tmp_path, files: dict) -> Project:
    """A throwaway project: {relative path under pkg/: source}."""
    for rel, source in files.items():
        path = tmp_path / "pkg" / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    return Project(str(tmp_path), package="pkg")


def rules_of(findings) -> set:
    return {f.rule for f in findings}


# --- PAX1xx: actor contract -------------------------------------------------

ACTOR_PREAMBLE = """\
    import threading
    import time

    class Actor:
        def receive(self, src, message): ...
        def on_drain(self): ...
        def timer(self, name, delay_s, f): ...
        def send(self, dst, message): ...
        def broadcast(self, dsts, message): ...
"""


def test_pax101_threading_in_handler(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def receive(self, src, message):
            threading.Thread(target=self.work).start()
    """}))
    assert "PAX101" in rules_of(findings)
    f = next(f for f in findings if f.rule == "PAX101")
    assert f.scope == "Bad.receive"


def test_pax101_reaches_self_call_closure(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def receive(self, src, message):
            self._helper()

        def _helper(self):
            threading.Event().wait()
    """}))
    assert any(f.rule == "PAX101" and f.scope == "Bad._helper"
               for f in findings)


def test_pax101_allows_construction_time_threads(tmp_path):
    """__init__ is not a handler: the ProxyLeader collector-thread
    pattern stays legal."""
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Fine(Actor):
        def __init__(self):
            threading.Thread(target=lambda: None, daemon=True).start()

        def receive(self, src, message):
            pass
    """}))
    assert "PAX101" not in rules_of(findings)


def test_pax102_lock_in_handler(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def receive(self, src, message):
            self.lock.acquire()
    """}))
    assert "PAX102" in rules_of(findings)


def test_pax103_sleep_in_handler(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def on_drain(self):
            time.sleep(0.1)
    """}))
    assert any(f.rule == "PAX103" and f.scope == "Bad.on_drain"
               for f in findings)


def test_pax103_sleep_in_timer_callback(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def receive(self, src, message):
            self.timer("t", 1.0, self._fire)

        def _fire(self):
            time.sleep(1)
    """}))
    assert any(f.rule == "PAX103" and f.scope == "Bad._fire"
               for f in findings)


def test_pax104_non_transport_timer(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def __init__(self, loop):
            threading.Timer(1.0, self._fire).start()
            loop.call_later(1.0, self._fire)

        def receive(self, src, message):
            pass

        def _fire(self):
            pass
    """}))
    assert sum(f.rule == "PAX104" for f in findings) == 2


def test_pax105_shared_module_state(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    SHARED = {}

    class A(Actor):
        def receive(self, src, message):
            SHARED[src] = message

    class B(Actor):
        def receive(self, src, message):
            return SHARED.get(src)
    """}))
    assert any(f.rule == "PAX105" and f.detail == "SHARED"
               for f in findings)


def test_pax105_single_class_use_is_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    CACHE = {}

    class A(Actor):
        def receive(self, src, message):
            CACHE[src] = message

    class B(Actor):
        def receive(self, src, message):
            pass
    """}))
    assert "PAX105" not in rules_of(findings)


def test_pax106_send_from_thread_target(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def __init__(self):
            threading.Thread(target=self._worker, daemon=True).start()

        def receive(self, src, message):
            pass

        def _worker(self):
            self.send(("h", 1), "result")
    """}))
    assert any(f.rule == "PAX106" and f.scope == "Bad._worker"
               for f in findings)


def test_pax110_acceptor_set_read_in_epoch_role(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def __init__(self, config):
            self.config = config
            self.epochs = object()

        def receive(self, src, message):
            group = self.config.acceptor_addresses[0]
            self.send(group[0], message)
    """}))
    assert any(f.rule == "PAX110" and f.scope == "Bad.receive"
               for f in findings)


def test_pax110_reaches_handler_closure_and_quorum_grid(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def __init__(self, config):
            self.config = config
            self.epochs = None

        def receive(self, src, message):
            self._fanout(message)

        def _fanout(self, message):
            grid = self.config.quorum_grid()
    """}))
    assert any(f.rule == "PAX110" and f.scope == "Bad._fanout"
               for f in findings)


def test_pax110_ignores_roles_without_epoch_store(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Frozen(Actor):
        def __init__(self, config):
            self.config = config

        def receive(self, src, message):
            group = self.config.acceptor_addresses[0]
    """}))
    assert "PAX110" not in rules_of(findings)


def test_pax110_init_reads_are_fine(tmp_path):
    # Construction-time reads seed the store itself.
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Good(Actor):
        def __init__(self, config):
            self.config = config
            self.epochs = list(config.acceptor_addresses[0])

        def receive(self, src, message):
            members = self.epochs
    """}))
    assert "PAX110" not in rules_of(findings)


def test_pax110_pragma_suppresses(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Pragmad(Actor):
        def __init__(self, config):
            self.config = config
            self.epochs = object()

        def receive(self, src, message):
            # paxlint: disable=PAX110
            group = self.config.acceptor_addresses[0]
    """}))
    assert "PAX110" not in rules_of(findings)


# --- PAX111: unbounded inbound buffers / sleep-retry loops (paxload) -------


def test_pax111_unbounded_list_inbox_in_handler(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def __init__(self):
            self.inbox = []

        def receive(self, src, message):
            self.inbox.append(message)
    """}))
    assert any(f.rule == "PAX111" and f.detail == "self.inbox"
               for f in findings)


def test_pax111_unbounded_deque_via_closure(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    import collections

    class Bad(Actor):
        def __init__(self):
            self.pending_frames = collections.deque()

        def receive(self, src, message):
            self._stash(message)

        def _stash(self, message):
            self.pending_frames.appendleft(message)
    """}))
    assert any(f.rule == "PAX111" and f.scope == "Bad._stash"
               for f in findings)


def test_pax111_maxlen_deque_and_len_guard_are_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    import collections

    class Capped(Actor):
        def __init__(self):
            self.inbox = collections.deque(maxlen=64)

        def receive(self, src, message):
            self.inbox.append(message)

    class Guarded(Actor):
        def __init__(self):
            self.queue = []

        def receive(self, src, message):
            if len(self.queue) < 64:
                self.queue.append(message)
    """}))
    assert "PAX111" not in rules_of(findings)


def test_pax111_inbox_full_admission_guard_is_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Admitted(Actor):
        def __init__(self, admission):
            self.admission = admission
            self.inbound = []

        def receive(self, src, message):
            if not self.admission.inbox_full(len(self.inbound)):
                self.inbound.append(message)
    """}))
    assert "PAX111" not in rules_of(findings)


def test_pax111_sleep_retry_loop_in_transport_code(tmp_path):
    findings = run_rules(project(tmp_path, {
        "runtime/conn.py": """
    import time

    def connect_with_retry(dial):
        while True:
            try:
                return dial()
            except OSError:
                time.sleep(0.5)
    """,
        # The same loop outside role/transport code is out of scope.
        "bench/poll.py": """
    import time

    def poll(ready):
        while not ready():
            time.sleep(0.5)
    """}))
    hits = [f for f in findings if f.rule == "PAX111"]
    assert [f.file for f in hits] == ["pkg/runtime/conn.py"]
    assert hits[0].detail == "time.sleep"


def test_pax111_nested_loops_report_one_finding_per_sleep(tmp_path):
    findings = run_rules(project(tmp_path, {"runtime/conn.py": """
    import time

    def connect_with_retry(dial):
        while True:
            for attempt in range(3):
                try:
                    return dial()
                except OSError:
                    time.sleep(0.5)
    """}))
    hits = [f for f in findings if f.rule == "PAX111"]
    assert len(hits) == 1


def test_pax111_sleep_in_function_defined_inside_loop_is_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"runtime/conn.py": """
    import time

    def make_delayers(delays):
        # The closures are DEFINED in a loop but run elsewhere (on a
        # transport timer, say): not a sleeping retry loop.
        out = []
        for delay in delays:
            def wait(delay=delay):
                time.sleep(delay)
            out.append(wait)
        return out
    """}))
    assert "PAX111" not in rules_of(findings)


def test_pax111_pragma_suppresses(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Pragmad(Actor):
        def __init__(self):
            self.inbox = []

        def receive(self, src, message):
            self.inbox.append(message)  # paxlint: disable=PAX111
    """}))
    assert "PAX111" not in rules_of(findings)


def test_pax106_call_soon_threadsafe_is_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Fine(Actor):
        def __init__(self, loop):
            self.loop = loop
            threading.Thread(target=self._worker, daemon=True).start()

        def receive(self, src, message):
            pass

        def _worker(self):
            self.loop.call_soon_threadsafe(self._emit, [1, 2])

        def _emit(self, results):
            self.send(("h", 1), results)
    """}))
    assert "PAX106" not in rules_of(findings)


# --- TPU2xx: hot-path rules -------------------------------------------------


def test_tpu201_block_until_ready_in_on_drain(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": """
    import jax

    class Tracker:
        def drain(self):
            jax.block_until_ready(self.board)

    class Role:
        def on_drain(self):
            self.tracker.drain()
    """}))
    assert any(f.rule == "TPU201" and f.scope == "Tracker.drain"
               for f in findings)


def test_tpu202_device_get_in_run_pipeline_handler(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": """
    import jax

    class Phase2aRun: ...

    class Role:
        def receive(self, src, message):
            if isinstance(message, Phase2aRun):
                self._handle_run(message)

        def _handle_run(self, run):
            return jax.device_get(run)
    """}))
    assert any(f.rule == "TPU202" and f.scope == "Role._handle_run"
               for f in findings)


def test_tpu203_blocking_fetch_of_async_dispatch(tmp_path):
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    import numpy as np

    def fetch(checker, block):
        mask = checker.check_block_async(block)
        return np.asarray(mask)
    """}))
    assert any(f.rule == "TPU203" for f in findings)


def test_tpu203_host_asarray_is_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    import numpy as np

    def pack(slots):
        return np.asarray(slots, dtype=np.int64)
    """}))
    assert "TPU203" not in rules_of(findings)


def test_tpu208_file_io_reachable_from_ops_kernel(tmp_path):
    """fsync / open reachable from ops/ kernel code is flagged -- WAL
    I/O must stay on the drain boundary, never inside a kernel."""
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    import os

    def persist(path, board):
        f = open(path, "ab")
        f.write(board.tobytes())
        os.fsync(f.fileno())
    """}))
    tpu208 = [f for f in findings if f.rule == "TPU208"]
    assert {f.detail for f in tpu208} >= {"open", "os.fsync"}


def test_tpu208_transitive_through_helper(tmp_path):
    findings = run_rules(project(tmp_path, {
        "ops/kernel.py": """
    from pkg.wal import sync_log

    def drain_kernel(block):
        sync_log()
    """,
        "wal.py": """
    import os

    def sync_log():
        os.fsync(3)
    """}))
    assert any(f.rule == "TPU208" and f.scope == "sync_log"
               for f in findings)


def test_tpu208_fsync_in_on_drain_is_fine(tmp_path):
    """The drain boundary is exactly where WAL I/O belongs: fsync in
    an actor's on_drain (outside ops/) is NOT flagged."""
    findings = run_rules(project(tmp_path, {"roles.py": """
    import os

    class Role:
        def on_drain(self):
            self.wal_file.flush()
            os.fsync(self.wal_file.fileno())
    """}))
    assert "TPU208" not in rules_of(findings)


def test_tpu209_clock_read_in_ops_kernel(tmp_path):
    """A host clock read inside ops/ kernel code is flagged -- span
    timing belongs to the transports/drain (obs/), never kernels."""
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    import time

    def check_block(board):
        t0 = time.perf_counter()
        result = board.sum()
        return result, time.perf_counter() - t0
    """}))
    tpu209 = [f for f in findings if f.rule == "TPU209"]
    assert {f.detail for f in tpu209} == {"time.perf_counter"}


def test_tpu209_trace_hook_reachable_from_ops_kernel(tmp_path):
    """Span-emitting hooks (trace_stage & friends) transitively
    reachable from a kernel are flagged at the reached site."""
    findings = run_rules(project(tmp_path, {
        "ops/kernel.py": """
    from pkg.helper import timed_step

    def record_and_check(board):
        return timed_step(board)
    """,
        "helper.py": """
    def timed_step(board):
        with board.owner.trace_stage("quorum-kernel"):
            return board.sum()
    """}))
    assert any(f.rule == "TPU209" and f.scope == "timed_step"
               and f.detail.endswith("trace_stage")
               for f in findings)


def test_tpu209_trace_hook_in_jitted_function(tmp_path):
    """Inside a jitted body the hook would run once at trace time and
    never again -- silently wrong, so it is flagged project-wide."""
    findings = run_rules(project(tmp_path, {"fast.py": """
    import time

    import jax

    @jax.jit
    def step(x):
        t0 = time.monotonic()
        return x + t0
    """}))
    assert any(f.rule == "TPU209" and f.scope == "step"
               for f in findings)


def test_tpu209_spans_in_on_drain_are_fine(tmp_path):
    """The drain path OUTSIDE kernels is exactly where stage spans
    belong: trace_stage/perf_counter in an actor's on_drain (not under
    ops/, not jitted) stays quiet."""
    findings = run_rules(project(tmp_path, {"roles.py": """
    import time

    class Role:
        def on_drain(self):
            with self.trace_stage("wal-fsync"):
                self.wal.sync()
            self.latency = time.perf_counter()
    """}))
    assert "TPU209" not in rules_of(findings)


def test_tpu209_summary_timer_not_a_clock_read(tmp_path):
    """``metrics.time()`` (the Summary timer) and bare ``time()`` are
    not clock reads; only the time-module entry points are."""
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    def check(board, metrics):
        with metrics.time():
            return board.sum()
    """}))
    assert "TPU209" not in rules_of(findings)


def test_tpu204_coercion_of_traced_value(tmp_path):
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    import jax

    @jax.jit
    def bad(x):
        return float(x)
    """}))
    assert any(f.rule == "TPU204" for f in findings)


def test_tpu205_python_if_on_traced_value(tmp_path):
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def bad(x):
        if x > 0:
            return x
        return -x
    """}))
    assert any(f.rule == "TPU205" for f in findings)


def test_tpu205_static_arg_if_is_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    import functools

    import jax

    @functools.partial(jax.jit, static_argnums=(1,))
    def fine(x, flag):
        if flag:
            return x
        return -x
    """}))
    assert "TPU205" not in rules_of(findings)


def test_tpu206_nested_jit(tmp_path):
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    import jax

    def hot(x):
        return jax.jit(lambda y: y + 1)(x)
    """}))
    assert any(f.rule == "TPU206" for f in findings)


def test_tpu207_loop_over_traced_shape(tmp_path):
    findings = run_rules(project(tmp_path, {"ops/kernel.py": """
    import jax

    @jax.jit
    def bad(x):
        total = 0
        for i in range(x.shape[0]):
            total = total + x[i]
        return total
    """}))
    assert any(f.rule == "TPU207" for f in findings)


# --- COD3xx: codec rules ----------------------------------------------------

CODEC_PREAMBLE = """\
    import dataclasses
    import struct

    class MessageCodec: ...

    def register_codec(codec): ...

    _I64 = struct.Struct("<q")
"""


def test_cod301_sent_message_without_codec(tmp_path):
    findings = run_rules(project(tmp_path, {
        "proto/messages.py": """
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class Hot:
        slot: int

    @dataclasses.dataclass(frozen=True)
    class Cold:
        round: int
    """,
        "proto/wire.py": CODEC_PREAMBLE + """
    from pkg.proto.messages import Hot

    class HotCodec(MessageCodec):
        message_type = Hot
        tag = 1

        def encode(self, out, message):
            out += _I64.pack(message.slot)

        def decode(self, buf, at):
            (slot,) = _I64.unpack_from(buf, at)
            return Hot(slot=slot), at + 8

    register_codec(HotCodec())
    """,
        "proto/role.py": """
    from pkg.proto.messages import Cold, Hot

    class Role:
        def receive(self, src, message):
            self.send(src, Hot(slot=1))
            self.send(src, Cold(round=2))
    """}))
    assert any(f.rule == "COD301" and f.detail == "Cold"
               for f in findings)
    assert not any(f.rule == "COD301" and f.detail == "Hot"
                   for f in findings)


def test_cod302_encode_decode_asymmetry(tmp_path):
    findings = run_rules(project(tmp_path, {
        "proto/messages.py": """
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class Msg:
        slot: int
        round: int
    """,
        "proto/wire.py": CODEC_PREAMBLE + """
    from pkg.proto.messages import Msg

    class MsgCodec(MessageCodec):
        message_type = Msg
        tag = 1

        def encode(self, out, message):
            out += _I64.pack(message.slot)  # forgets round

        def decode(self, buf, at):
            (slot,) = _I64.unpack_from(buf, at)
            return Msg(slot=slot, round=0), at + 8
    """}))
    assert any(f.rule == "COD302" and "round" in f.message
               for f in findings)


def test_cod302_symmetric_codec_is_clean(tmp_path):
    findings = run_rules(project(tmp_path, {
        "proto/messages.py": """
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class Msg:
        slot: int
    """,
        "proto/wire.py": CODEC_PREAMBLE + """
    from pkg.proto.messages import Msg

    class MsgCodec(MessageCodec):
        message_type = Msg
        tag = 1

        def encode(self, out, message):
            out += _I64.pack(message.slot)

        def decode(self, buf, at):
            (slot,) = _I64.unpack_from(buf, at)
            return Msg(slot=slot), at + 8
    """}))
    assert "COD302" not in rules_of(findings)


def test_cod302_same_named_messages_resolve_per_protocol(tmp_path):
    """Two protocols with same-named messages: each codec is checked
    against ITS protocol's dataclass, not a global name match."""
    findings = run_rules(project(tmp_path, {
        "p1/messages.py": """
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class Reply:
        a: int
    """,
        "p2/messages.py": """
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class Reply:
        b: int
    """,
        "p2/wire.py": CODEC_PREAMBLE + """
    from pkg.p2.messages import Reply

    class ReplyCodec(MessageCodec):
        message_type = Reply
        tag = 1

        def encode(self, out, message):
            out += _I64.pack(message.b)

        def decode(self, buf, at):
            (b,) = _I64.unpack_from(buf, at)
            return Reply(b=b), at + 8
    """}))
    assert "COD302" not in rules_of(findings)


# --- pragmas ----------------------------------------------------------------


def test_pragma_suppresses_on_same_line(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Curated(Actor):
        def on_drain(self):
            time.sleep(0.1)  # paxlint: disable=PAX103
    """}))
    assert "PAX103" not in rules_of(findings)


def test_pragma_on_preceding_comment_block(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Curated(Actor):
        def on_drain(self):
            # paxlint: disable=PAX103 -- justified: measured backoff
            # that the sim transport never executes.
            time.sleep(0.1)
    """}))
    assert "PAX103" not in rules_of(findings)


def test_pragma_on_def_line_scopes_whole_function(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Curated(Actor):
        def on_drain(self):  # paxlint: disable=PAX103
            time.sleep(0.1)
            time.sleep(0.2)
    """}))
    assert "PAX103" not in rules_of(findings)


def test_pragma_only_disables_named_rule(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Curated(Actor):
        def on_drain(self):
            time.sleep(0.1)  # paxlint: disable=PAX101
    """}))
    assert "PAX103" in rules_of(findings)


# --- baseline ---------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    proj = project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def on_drain(self):
            time.sleep(0.1)
    """})
    findings = run_rules(proj)
    assert findings
    path = str(tmp_path / "baseline.json")
    baseline_mod.write(path, findings)
    entries = baseline_mod.load(path)
    new, old, stale = baseline_mod.split(findings, entries)
    assert not new and not stale
    assert [f.key for f in old] == [f.key for f in findings]


def test_baseline_is_line_number_independent(tmp_path):
    proj = project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def on_drain(self):
            time.sleep(0.1)
    """})
    path = str(tmp_path / "baseline.json")
    baseline_mod.write(path, run_rules(proj))
    # Shift every line down: the finding must still match the baseline.
    src = (tmp_path / "pkg" / "a.py").read_text()
    (tmp_path / "pkg" / "a.py").write_text("# shifted\n# shifted\n" + src)
    shifted = run_rules(Project(str(tmp_path), package="pkg"))
    new, old, stale = baseline_mod.split(shifted,
                                         baseline_mod.load(path))
    assert not new and not stale and old


def test_new_finding_not_masked_by_baseline(tmp_path):
    proj = project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def on_drain(self):
            time.sleep(0.1)
    """})
    path = str(tmp_path / "baseline.json")
    baseline_mod.write(path, run_rules(proj))
    src = (tmp_path / "pkg" / "a.py").read_text()
    (tmp_path / "pkg" / "a.py").write_text(src + textwrap.dedent("""
    class Worse(Actor):
        def receive(self, src, message):
            time.sleep(1)
    """))
    findings = run_rules(Project(str(tmp_path), package="pkg"))
    new, old, stale = baseline_mod.split(findings,
                                         baseline_mod.load(path))
    assert any(f.scope == "Worse.receive" for f in new)
    assert all(f.scope != "Worse.receive" for f in old)


def test_stale_baseline_entries_reported(tmp_path):
    proj = project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def on_drain(self):
            time.sleep(0.1)
    """})
    path = str(tmp_path / "baseline.json")
    baseline_mod.write(path, run_rules(proj))
    (tmp_path / "pkg" / "a.py").write_text(
        textwrap.dedent(ACTOR_PREAMBLE))
    new, old, stale = baseline_mod.split(
        run_rules(Project(str(tmp_path), package="pkg")),
        baseline_mod.load(path))
    assert not new and not old and len(stale) == 1


# --- the repo itself gates green --------------------------------------------


def test_repo_passes_paxlint():
    """The acceptance gate: `python -m frankenpaxos_tpu.analysis` exits
    0 on this repository (everything fixed, pragma'd, or baselined)."""
    proc = subprocess.run(
        [sys.executable, "-m", "frankenpaxos_tpu.analysis"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new findings" in proc.stdout


def test_exit_code_gates_on_seeded_violation(tmp_path):
    """CLI exit 1 on a repo with a fresh (unbaselined) violation."""
    (tmp_path / "frankenpaxos_tpu").mkdir()
    (tmp_path / "frankenpaxos_tpu" / "bad.py").write_text(
        textwrap.dedent(ACTOR_PREAMBLE) + textwrap.dedent("""
    class Bad(Actor):
        def on_drain(self):
            time.sleep(0.5)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "frankenpaxos_tpu.analysis",
         "--root", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "PAX103" in proc.stdout


def test_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "frankenpaxos_tpu.analysis",
         "--list-rules"], capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0
    for rule in ("PAX101", "TPU201", "COD301", "COD302"):
        assert rule in proc.stdout


# --- FLOW4xx: message-topology contracts (paxflow) --------------------------

FLOW_PREAMBLE = """\
    import dataclasses

    class Actor:
        def receive(self, src, message): ...
        def on_drain(self): ...
        def timer(self, name, delay_s, f): ...
        def send(self, dst, message): ...
        def broadcast(self, dsts, message): ...
"""


def test_flow401_sent_but_unhandled(tmp_path):
    findings = run_rules(project(tmp_path, {
        "protocols/toy.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class Ping:
        n: int

    class Sender(Actor):
        def receive(self, src, message):
            self.send(src, Ping(n=1))
    """}))
    assert any(f.rule == "FLOW401" and f.scope == "Ping"
               for f in findings)


def test_flow401_quiet_when_handled_outside_protocols(tmp_path):
    """A handler in election/-style code outside the protocol tree
    still counts (the global handler scan)."""
    findings = run_rules(project(tmp_path, {
        "protocols/toy.py": FLOW_PREAMBLE + """
    from pkg.election import Ping

    class Sender(Actor):
        def receive(self, src, message):
            self.send(src, Ping(n=1))
    """,
        "election.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class Ping:
        n: int

    class Participant(Actor):
        def receive(self, src, message):
            if isinstance(message, Ping):
                pass
    """}))
    assert "FLOW401" not in rules_of(findings)


def test_flow401_payload_only_construction_is_not_a_send(tmp_path):
    """A message nested inside another sent message is wire payload,
    not an unhandled dispatch target."""
    findings = run_rules(project(tmp_path, {
        "protocols/toy.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class Inner:
        n: int

    @dataclasses.dataclass
    class Outer:
        inner: Inner

    class Sender(Actor):
        def receive(self, src, message):
            self.send(src, Outer(inner=Inner(n=1)))

    class Receiver(Actor):
        def receive(self, src, message):
            if isinstance(message, Outer):
                pass
    """}))
    assert all(not (f.rule == "FLOW401" and f.scope == "Inner")
               for f in findings)


def test_flow402_handled_but_never_sent(tmp_path):
    findings = run_rules(project(tmp_path, {
        "protocols/toy.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class Dead:
        n: int

    class Receiver(Actor):
        def receive(self, src, message):
            if isinstance(message, Dead):
                pass
    """}))
    assert any(f.rule == "FLOW402" and f.scope == "Dead"
               for f in findings)


def test_flow403_orphan_codec_tag(tmp_path):
    findings = run_rules(project(tmp_path, {
        "protocols/toy.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class Orphan:
        n: int

    class OrphanCodec:
        message_type = Orphan
        tag = 99

        def encode(self, out, message):
            out += bytes([message.n])

        def decode(self, buf, at):
            return Orphan(n=buf[at]), at + 1
    """}))
    assert any(f.rule == "FLOW403" and f.scope == "Orphan"
               for f in findings)


def test_flow404_request_without_reply_or_timer(tmp_path):
    findings = run_rules(project(tmp_path, {
        "protocols/toy.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class FetchRequest:
        n: int

    class Requester(Actor):
        def kick(self):
            self.send("server", FetchRequest(n=1))

        def receive(self, src, message):
            pass

    class Server(Actor):
        def receive(self, src, message):
            if isinstance(message, FetchRequest):
                pass
    """}))
    assert any(f.rule == "FLOW404" and f.scope == "FetchRequest"
               for f in findings)


def test_flow404_quiet_with_reply_path(tmp_path):
    findings = run_rules(project(tmp_path, {
        "protocols/toy.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class FetchRequest:
        n: int

    @dataclasses.dataclass
    class FetchReply:
        n: int

    class Requester(Actor):
        def kick(self):
            self.send("server", FetchRequest(n=1))

        def receive(self, src, message):
            if isinstance(message, FetchReply):
                pass

    class Server(Actor):
        def receive(self, src, message):
            if isinstance(message, FetchRequest):
                self.send(src, FetchReply(n=message.n))
    """}))
    assert "FLOW404" not in rules_of(findings)


def test_flow404_quiet_with_nested_def_resend_timer(tmp_path):
    """The ubiquitous client idiom: a nested ``def resend`` registered
    as a timer callback makes the request timer-resent."""
    findings = run_rules(project(tmp_path, {
        "protocols/toy.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class FetchRequest:
        n: int

    class Requester(Actor):
        def kick(self):
            request = FetchRequest(n=1)
            self.send("server", request)

            def resend():
                self.send("server", request)

            self.timer("resend", 1.0, resend).start()

        def receive(self, src, message):
            pass

    class Server(Actor):
        def receive(self, src, message):
            if isinstance(message, FetchRequest):
                pass
    """}))
    assert "FLOW404" not in rules_of(findings)


_LANES_FIXTURE = """\
    CLIENT_LANE_TYPE_NAMES = frozenset({
        "ClientRequest",
    })
"""


def test_flow405_lane_name_without_codec_tag(tmp_path):
    """A client-lane NAME whose message has no codec: the tag-based
    frame classifier can never shed it."""
    findings = run_rules(project(tmp_path, {
        "serve/lanes.py": _LANES_FIXTURE,
        "protocols/toy.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class ClientRequest:
        n: int

    @dataclasses.dataclass
    class Other:
        n: int

    class OtherCodec:
        message_type = Other
        tag = 98

        def encode(self, out, message):
            out += bytes([message.n])

        def decode(self, buf, at):
            return Other(n=buf[at]), at + 1

    class ToyClient(Actor):
        def kick(self):
            self.send("server", ClientRequest(n=1))
            self.send("server", Other(n=2))

        def receive(self, src, message):
            pass

    class Server(Actor):
        def receive(self, src, message):
            if isinstance(message, (ClientRequest, Other)):
                self.send(src, Other(n=0))
    """}))
    assert any(f.rule == "FLOW405"
               and f.detail == "untagged-lane:ClientRequest"
               for f in findings)


def test_flow405_unclassified_client_edge_message(tmp_path):
    """A codec-tagged *Request* sent only by client-edge roles but
    missing from CLIENT_LANE_TYPE_NAMES."""
    findings = run_rules(project(tmp_path, {
        "serve/lanes.py": _LANES_FIXTURE,
        "protocols/toy.py": FLOW_PREAMBLE + """
    @dataclasses.dataclass
    class FetchRequest:
        n: int

    class FetchRequestCodec:
        message_type = FetchRequest
        tag = 97

        def encode(self, out, message):
            out += bytes([message.n])

        def decode(self, buf, at):
            return FetchRequest(n=buf[at]), at + 1

    class ToyClient(Actor):
        def kick(self):
            request = FetchRequest(n=1)
            self.send("server", request)

            def resend():
                self.send("server", request)

            self.timer("resend", 1.0, resend).start()

        def receive(self, src, message):
            pass

    class Server(Actor):
        def receive(self, src, message):
            if isinstance(message, FetchRequest):
                pass
    """}))
    assert any(f.rule == "FLOW405"
               and f.detail == "unclassified:FetchRequest"
               for f in findings)


# --- DUR5xx: durability dataflow --------------------------------------------

DUR_PREAMBLE = """\
    import dataclasses

    class Actor:
        def receive(self, src, message): ...
        def on_drain(self): ...
        def timer(self, name, delay_s, f): ...
        def send(self, dst, message): ...
        def broadcast(self, dsts, message): ...

    class DurableRole:
        def _wal_init(self, wal): ...
        def _wal_send(self, dst, message): ...
        def _wal_drain(self): ...

    @dataclasses.dataclass
    class Record:
        n: int

    @dataclasses.dataclass
    class Ack:
        n: int

    @dataclasses.dataclass
    class Nack:
        n: int
"""


def test_dur501_direct_send_after_append(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": DUR_PREAMBLE + """
    class Bad(Actor, DurableRole):
        def receive(self, src, message):
            self.wal.append(Record(n=1))
            self.send(src, Ack(n=1))
    """}))
    assert any(f.rule == "DUR501" and f.detail == "send:Ack"
               for f in findings)


def test_dur501_quiet_for_wal_send(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": DUR_PREAMBLE + """
    class Good(Actor, DurableRole):
        def receive(self, src, message):
            self.wal.append(Record(n=1))
            self._wal_send(src, Ack(n=1))
    """}))
    assert "DUR501" not in rules_of(findings)


def test_dur501_nack_is_exempt(tmp_path):
    """A nack acknowledges nothing: the early-reject path may send it
    directly even in an appending handler."""
    findings = run_rules(project(tmp_path, {"a.py": DUR_PREAMBLE + """
    class Good(Actor, DurableRole):
        def receive(self, src, message):
            if message.n < 0:
                self.send(src, Nack(n=0))
                return
            self.wal.append(Record(n=1))
            self._wal_send(src, Ack(n=1))
    """}))
    assert "DUR501" not in rules_of(findings)


def test_dur502_wal_use_without_mixin(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": DUR_PREAMBLE + """
    class Bad(Actor):
        def receive(self, src, message):
            self.wal.append(Record(n=1))
    """}))
    assert any(f.rule == "DUR502" and f.scope == "Bad"
               for f in findings)


def test_dur502_quiet_with_mixin(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": DUR_PREAMBLE + """
    class Good(Actor, DurableRole):
        def receive(self, src, message):
            self.wal.append(Record(n=1))
            self._wal_send(src, Ack(n=1))

        def on_drain(self):
            self._wal_drain()
    """}))
    assert "DUR502" not in rules_of(findings)


def test_dur503_on_drain_without_wal_drain(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": DUR_PREAMBLE + """
    class Bad(Actor, DurableRole):
        def receive(self, src, message):
            self.wal.append(Record(n=1))
            self._wal_send(src, Ack(n=1))

        def on_drain(self):
            pass
    """}))
    assert any(f.rule == "DUR503" and f.scope == "Bad.on_drain"
               for f in findings)


def test_dur503_quiet_when_reached_through_helper(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": DUR_PREAMBLE + """
    class Good(Actor, DurableRole):
        def receive(self, src, message):
            self.wal.append(Record(n=1))
            self._wal_send(src, Ack(n=1))

        def on_drain(self):
            self._finish()

        def _finish(self):
            self._wal_drain()
    """}))
    assert "DUR503" not in rules_of(findings)


# --- SHAPE6xx: abstract shape/dtype interpretation --------------------------

SHAPE_PREAMBLE = """\
    import jax
    import jax.numpy as jnp
"""


def test_shape601_nonzero_without_size(tmp_path):
    findings = run_rules(project(tmp_path, {"k.py": SHAPE_PREAMBLE + """
    @jax.jit
    def kernel(x):
        return jnp.nonzero(x > 0)
    """}))
    assert any(f.rule == "SHAPE601" for f in findings)


def test_shape601_quiet_with_size(tmp_path):
    findings = run_rules(project(tmp_path, {"k.py": SHAPE_PREAMBLE + """
    @jax.jit
    def kernel(x):
        return jnp.nonzero(x > 0, size=8, fill_value=0)
    """}))
    assert "SHAPE601" not in rules_of(findings)


def test_shape601_one_arg_where(tmp_path):
    findings = run_rules(project(tmp_path, {"k.py": SHAPE_PREAMBLE + """
    @jax.jit
    def kernel(x):
        return jnp.where(x > 0)

    @jax.jit
    def fine(x):
        return jnp.where(x > 0, x, 0)
    """}))
    assert sum(f.rule == "SHAPE601" for f in findings) == 1


def test_shape602_builtin_astype(tmp_path):
    findings = run_rules(project(tmp_path, {"k.py": SHAPE_PREAMBLE + """
    @jax.jit
    def kernel(x):
        return x.astype(int)
    """}))
    assert any(f.rule == "SHAPE602" and f.detail == "astype:int"
               for f in findings)


def test_shape602_value_typed_arange(tmp_path):
    findings = run_rules(project(tmp_path, {"k.py": SHAPE_PREAMBLE + """
    @jax.jit
    def kernel(x):
        return jnp.arange(x.shape[0])

    @jax.jit
    def fine(x):
        return jnp.arange(x.shape[0], dtype=jnp.int32)
    """}))
    assert sum(f.rule == "SHAPE602" for f in findings) == 1


def test_shape602_jit_wrapped_module_level(tmp_path):
    """``kernel2 = jax.jit(kernel)`` marks ``kernel`` as jitted even
    without a decorator."""
    findings = run_rules(project(tmp_path, {"k.py": SHAPE_PREAMBLE + """
    def kernel(x):
        return x.astype(float)

    kernel2 = jax.jit(kernel)
    """}))
    assert any(f.rule == "SHAPE602" and f.detail == "astype:float"
               for f in findings)


def test_shape603_undeclared_axis_name(tmp_path):
    findings = run_rules(project(tmp_path, {"k.py": SHAPE_PREAMBLE + """
    from jax import lax
    from jax.sharding import Mesh

    def make(devices):
        return Mesh(devices, ("group", "slot"))

    @jax.jit
    def kernel(x):
        return lax.psum(x, axis_name="grp")
    """}))
    assert any(f.rule == "SHAPE603" and f.detail == "psum:grp"
               for f in findings)


def test_shape603_quiet_when_declared(tmp_path):
    findings = run_rules(project(tmp_path, {"k.py": SHAPE_PREAMBLE + """
    from jax import lax
    from jax.sharding import Mesh

    def make(devices):
        return Mesh(devices, ("group", "slot"))

    @jax.jit
    def kernel(x):
        return lax.psum(x, axis_name="group")
    """}))
    assert "SHAPE603" not in rules_of(findings)


# --- paxflow graph artifacts ------------------------------------------------


def test_flowgraph_covers_every_protocol_unit():
    """Registry completeness: every protocol package yields a
    non-empty flow graph (roles, messages, and at least one edge)."""
    from frankenpaxos_tpu.analysis import flowgraph

    proj = Project(".")
    graphs = flowgraph.build_all(proj)
    units = set(flowgraph.unit_modules(proj))
    assert units == set(graphs)
    assert len(graphs) >= 20
    for unit, graph in graphs.items():
        assert graph.roles, unit
        assert graph.messages, unit
        assert graph.edges(), unit


def test_flowgraph_golden_multipaxos_mencius():
    """The committed docs/flowgraphs artifacts for the two run-pipeline
    protocols match a fresh build byte-for-byte, and a second
    independent build is bit-identical (deterministic, diff-stable)."""
    from frankenpaxos_tpu.analysis import flowgraph

    first = flowgraph.render(Project("."))
    second = flowgraph.render(Project("."))
    assert first == second
    for unit in ("multipaxos", "mencius"):
        for ext in ("json", "dot"):
            with open(f"docs/flowgraphs/{unit}.{ext}",
                      encoding="utf-8") as f:
                assert f.read() == first[f"{unit}.{ext}"], (
                    f"{unit}.{ext} is stale: regenerate with "
                    f"python -m frankenpaxos_tpu.analysis "
                    f"--write-flowgraphs")


def test_flowgraph_topology_golden_epaxos_simplebpaxos():
    """The paxruns port contract, mechanically checked: coalescing
    PreAcceptOk/DependencyReply into DepRun frames must leave the
    epaxos and simplebpaxos role x message topology EXACTLY as it was
    (runs/wire.py codecs are transport_layer; receivers re-expand to
    the original messages). A topology diff here means a run message
    leaked into a protocol's role graph -- update tests/golden/ only
    with a deliberate protocol change, never for a transport one."""
    import json

    from frankenpaxos_tpu.analysis import flowgraph

    graphs = flowgraph.build_all(Project("."))
    for unit in ("epaxos", "simplebpaxos"):
        d = flowgraph.to_json(graphs[unit])
        live = {
            "protocol": unit,
            "edges": sorted(
                d["edges"],
                key=lambda e: (e["message"], e["from"], e["to"],
                               e["kind"])),
            "roles": {role: {"handles": sorted(v["handles"]),
                             "sends": sorted(v["sends"])}
                      for role, v in d["roles"].items()},
        }
        with open(f"tests/golden/flow_topology_{unit}.json",
                  encoding="utf-8") as f:
            golden = json.load(f)
        assert live == golden, (
            f"{unit} role x message topology changed -- the run-layer "
            f"port must be topology-neutral")


# --- import_sort: the tooled import-order pass ------------------------------


def test_import_sort_sections_and_members():
    from frankenpaxos_tpu.analysis.import_sort import sort_source

    src = textwrap.dedent("""\
    \"\"\"doc.\"\"\"

    from frankenpaxos_tpu.utils import BufferMap
    import sys
    from typing import Optional
    import jax
    from frankenpaxos_tpu.runtime import Logger, Actor
    """)
    out = sort_source(src)
    want = textwrap.dedent("""\
    \"\"\"doc.\"\"\"

    import sys
    from typing import Optional

    import jax

    from frankenpaxos_tpu.runtime import Actor, Logger
    from frankenpaxos_tpu.utils import BufferMap
    """)
    assert out == want
    assert sort_source(out) == out  # idempotent


def test_import_sort_preserves_noqa_and_interior_comments():
    from frankenpaxos_tpu.analysis.import_sort import sort_source

    src = textwrap.dedent("""\
    from frankenpaxos_tpu.wal.log import (  # noqa: F401
        Wal,
        MemStorage,
    )
    from frankenpaxos_tpu.obs import (
        Tracer,
        # the flight recorder survives kill -9
        FlightRecorder,
    )
    """)
    out = sort_source(src)
    assert "# noqa: F401" in out
    # The interior-comment statement is kept verbatim (unsorted names
    # and all) -- only its position may change.
    assert "# the flight recorder survives kill -9" in out
    assert out.index("frankenpaxos_tpu.obs") < out.index(
        "frankenpaxos_tpu.wal")


def test_import_sort_repo_gate():
    """The CI gate: the repo's import order is check-clean."""
    proc = subprocess.run(
        [sys.executable, "-m",
         "frankenpaxos_tpu.analysis.import_sort", "--check"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --- NET7xx: paxwire transport contract -------------------------------------


def test_net701_flushing_send_loop_in_on_drain(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def on_drain(self):
            for reply in self.staged:
                self.send(self.leader, reply)
    """}))
    assert "NET701" in rules_of(findings)
    f = next(f for f in findings if f.rule == "NET701")
    assert f.scope == "Bad.on_drain"


def test_net701_reaches_drain_helper_closure(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def on_drain(self):
            self._release()

        def _release(self):
            for ack in self.acks:
                self.send(self.proxy, ack)
    """}))
    assert "NET701" in rules_of(findings)
    assert any(f.rule == "NET701" and f.scope == "Bad._release"
               for f in findings)


def test_net701_chan_send_on_loop_invariant_channel(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Bad(Actor):
        def on_drain(self):
            chan = self.chan(self.leader)
            for reply in self.staged:
                chan.send(reply)
    """}))
    assert "NET701" in rules_of(findings)


def test_net701_per_destination_fanout_is_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Good(Actor):
        def on_drain(self):
            for client, reply in self.staged.items():
                self.send(client, reply)
    """}))
    assert "NET701" not in rules_of(findings)


def test_net701_send_no_flush_plus_flush_is_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Good(Actor):
        def send_no_flush(self, dst, message): ...
        def flush(self, dst): ...
        def on_drain(self):
            for reply in self.staged:
                self.send_no_flush(self.leader, reply)
            self.flush(self.leader)
    """}))
    assert "NET701" not in rules_of(findings)


def test_net701_receive_loops_not_flagged(tmp_path):
    """Only DRAIN-granular handlers are in scope: a receive() handling
    one inbound message sends per message by definition."""
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Good(Actor):
        def receive(self, src, message):
            for dst in range(3):
                self.send(self.leader, message)
    """}))
    assert "NET701" not in rules_of(findings)


def test_net701_pragma_suppresses(tmp_path):
    findings = run_rules(project(tmp_path, {"a.py": ACTOR_PREAMBLE + """
    class Tolerated(Actor):
        def on_drain(self):
            for reply in self.staged:
                self.send(self.leader, reply)  # paxlint: disable=NET701
    """}))
    assert "NET701" not in rules_of(findings)


def test_flow403_transport_layer_codec_excluded(tmp_path):
    """A codec marked ``transport_layer = True`` (paxwire batch
    envelopes: encoded by the transport's flush planner, never by a
    role) is not an orphan tag; the unmarked twin still is."""
    files = {
        "serve/lanes.py": "CLIENT_LANE_TYPE_NAMES = frozenset()\n",
        "wire.py": """
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class Envelope:
        segments: tuple

    @dataclasses.dataclass(frozen=True)
    class Orphan:
        segments: tuple

    class MessageCodec: ...

    class EnvelopeCodec(MessageCodec):
        message_type = Envelope
        tag = 150
        transport_layer = True

    class OrphanCodec(MessageCodec):
        message_type = Orphan
        tag = 151
    """}
    findings = run_rules(project(tmp_path, files))
    flow403 = {f.scope for f in findings if f.rule == "FLOW403"}
    assert "Orphan" in flow403
    assert "Envelope" not in flow403


# --- GEO8xx: paxgeo determinism contract ------------------------------------


def test_geo801_wall_clock_in_geo_layer(tmp_path):
    findings = run_rules(project(tmp_path, {"geo/topology.py": """
    import time

    def sample_delay(src, dst):
        return time.time() * 0.001
    """}))
    assert "GEO801" in rules_of(findings)
    f = next(f for f in findings if f.rule == "GEO801")
    assert "time.time" in f.detail


def test_geo801_unseeded_random_in_geo_layer(tmp_path):
    findings = run_rules(project(tmp_path, {"geo/jitter.py": """
    import random

    def jitter():
        return random.random()
    """}))
    assert "GEO801" in rules_of(findings)


def test_geo801_os_entropy_in_geo_layer(tmp_path):
    findings = run_rules(project(tmp_path, {"geo/seed.py": """
    import os

    def fresh():
        return os.urandom(8)
    """}))
    assert "GEO801" in rules_of(findings)


def test_geo801_seeded_random_is_fine(tmp_path):
    findings = run_rules(project(tmp_path, {"geo/topology.py": """
    import random

    def sample_delay(seed, src, dst, frame_id):
        return random.Random(f"{seed}|{src}|{dst}|{frame_id}").random()
    """}))
    assert "GEO801" not in rules_of(findings)


def test_geo801_scoped_to_geo_tree(tmp_path):
    # The same construct OUTSIDE geo/ (a bench's wall-clock timing) is
    # not this rule's business.
    findings = run_rules(project(tmp_path, {"bench/geo_lt.py": """
    import time

    def measure():
        return time.time()
    """}))
    assert "GEO801" not in rules_of(findings)


def test_geo801_repo_is_clean():
    from frankenpaxos_tpu.analysis.core import Project as _P
    from frankenpaxos_tpu.analysis.geo_rules import check as _geo_check

    import frankenpaxos_tpu
    import os as _os

    root = _os.path.dirname(_os.path.dirname(
        frankenpaxos_tpu.__file__))
    findings = list(_geo_check(_P(root, package="frankenpaxos_tpu")))
    assert findings == []


# --- SAFE9xx: Paxos safety disciplines (paxsafe) ----------------------------

ROLE_PREAMBLE = """\
    class Actor:
        def receive(self, src, message): ...
        def on_drain(self): ...
        def timer(self, name, delay_s, f): ...
        def send(self, dst, message): ...
        def broadcast(self, dsts, message): ...
"""


def role_project(tmp_path, source: str) -> "Project":
    """A throwaway project whose one module lives under protocols/
    (the SAFE9xx/ALIAS10xx self-scope)."""
    return project(tmp_path, {"protocols/a.py": ROLE_PREAMBLE + source})


def test_safe901_unguarded_round_adoption(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            self.round = message.round
            self.send(src, message)
    """))
    assert any(f.rule == "SAFE901" and f.detail == "self.round"
               for f in findings)


def test_safe901_compare_guard_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            if message.round < self.round:
                return
            self.round = message.round
    """))
    assert "SAFE901" not in rules_of(findings)


def test_safe901_guard_in_caller_clears_helper(tmp_path):
    """Cross-method: the round compare in the dispatching handler
    clears the adoption inside the helper it calls."""
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            if message.round < self.round:
                return
            self._adopt(message)

        def _adopt(self, message):
            self.round = message.round
    """))
    assert "SAFE901" not in rules_of(findings)


def test_safe901_helper_without_any_guard_flagged(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            self._adopt(message)

        def _adopt(self, message):
            self.ballot = message.ballot
    """))
    assert any(f.rule == "SAFE901" and f.scope == "Bad._adopt"
               for f in findings)


def test_safe901_max_and_bump_are_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            self.round = max(self.round, message.round)
            self.ballot += 1
    """))
    assert "SAFE901" not in rules_of(findings)


def test_safe901_pragma_suppresses(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Odd(Actor):
        def receive(self, src, message):
            # the round space is partitioned per proposer: no two
            # proposers share a round, so adoption cannot regress.
            # paxlint: disable=SAFE901
            self.round = message.round
    """))
    assert "SAFE901" not in rules_of(findings)


def test_safe901_out_of_scope_module_is_ignored(tmp_path):
    findings = run_rules(project(tmp_path, {"runtime/a.py": """\
    class Actor:
        def receive(self, src, message): ...
        def send(self, dst, message): ...

    class Elsewhere(Actor):
        def receive(self, src, message):
            self.round = message.round
    """}))
    assert "SAFE901" not in rules_of(findings)


def test_safe902_vote_overwrite_without_check(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            self.votes[message.slot] = (message.round, message.value)
    """))
    assert any(f.rule == "SAFE902" and f.detail == "self.votes"
               for f in findings)


def test_safe902_round_compare_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            if message.round < self.round:
                return
            self.round = message.round
            self.votes[message.slot] = (message.round, message.value)
    """))
    assert "SAFE902" not in rules_of(findings)


def test_safe902_existing_entry_get_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            existing = self.votes.get(message.slot)
            if existing is None:
                self.votes[message.slot] = (message.round, message.value)
    """))
    assert "SAFE902" not in rules_of(findings)


def test_safe902_guard_in_caller_clears_helper(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            if message.round < self.round:
                return
            self._store(message)

        def _store(self, message):
            self.vote_value = message.value
    """))
    assert "SAFE902" not in rules_of(findings)


def test_safe902_pragma_suppresses(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Odd(Actor):
        def receive(self, src, message):
            # single-proposer unit: one value per slot by construction.
            # paxlint: disable=SAFE902
            self.votes[message.slot] = message.value
    """))
    assert "SAFE902" not in rules_of(findings)


def test_safe903_unclamped_next_slot(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            max_slot = max(v.slot for v in message.votes)
            self.next_slot = max_slot + 1
    """))
    assert any(f.rule == "SAFE903" and f.detail == "self.next_slot"
               for f in findings)


def test_safe903_watermark_clamp_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            max_slot = max(v.slot for v in message.votes)
            self.next_slot = max(max_slot + 1, self.chosen_watermark)
    """))
    assert "SAFE903" not in rules_of(findings)


def test_safe903_flags_unclamped_helper_call_site(tmp_path):
    """Cross-method: the cursor is written in a helper; the voted-max
    flows in at the call site, which is where the clamp is missing."""
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            max_slot = max(v.slot for v in message.votes)
            start = max_slot + 1
            self._set_slots(start)

        def _set_slots(self, start_slot):
            self.next_slot = start_slot
    """))
    assert any(f.rule == "SAFE903" and f.scope == "Bad.receive"
               for f in findings)


def test_safe903_clamped_helper_call_site_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            max_slot = max(v.slot for v in message.votes)
            start = max(max_slot + 1, self.chosen_watermark)
            self._set_slots(start)

        def _set_slots(self, start_slot):
            self.next_slot = start_slot
    """))
    assert "SAFE903" not in rules_of(findings)


def test_safe903_monotone_guard_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            max_slot = max(v.slot for v in message.votes)
            if max_slot + 1 > self.next_slot:
                self.next_slot = max_slot + 1
    """))
    assert "SAFE903" not in rules_of(findings)


def test_safe903_pragma_suppresses(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Odd(Actor):
        def receive(self, src, message):
            max_slot = max(v.slot for v in message.votes)
            # the cursor trails the watermark by construction here.
            # paxlint: disable=SAFE903
            self.next_slot = max_slot + 1
    """))
    assert "SAFE903" not in rules_of(findings)


def test_safe904_plain_watermark_assignment(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            self.chosen_watermark = message.slot
    """))
    assert any(f.rule == "SAFE904"
               and f.detail == "self.chosen_watermark"
               for f in findings)


def test_safe904_max_update_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            self.chosen_watermark = max(self.chosen_watermark,
                                        message.slot)
    """))
    assert "SAFE904" not in rules_of(findings)


def test_safe904_guard_compare_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            if message.slot > self.chosen_watermark:
                self.chosen_watermark = message.slot
    """))
    assert "SAFE904" not in rules_of(findings)


def test_safe904_walked_forward_copy_is_clean(tmp_path):
    """The wm = self.W; while ...: wm += 1; self.W = wm walk reads the
    field first: monotone by construction."""
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            wm = self.chosen_watermark
            while wm in self.log:
                wm += 1
            self.chosen_watermark = wm
    """))
    assert "SAFE904" not in rules_of(findings)


def test_safe904_pragma_suppresses(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Odd(Actor):
        def receive(self, src, message):
            # snapshots install a complete replacement state.
            # paxlint: disable=SAFE904
            self.chosen_watermark = message.slot
    """))
    assert "SAFE904" not in rules_of(findings)


def test_safe905_promise_mutated_after_phase1b_send(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Phase1b:
        pass

    class Bad(Actor):
        def receive(self, src, message):
            if message.round < self.round:
                return
            self.send(src, Phase1b(round=message.round))
            self.round = message.round
    """))
    assert any(f.rule == "SAFE905" and f.detail == "self.round"
               for f in findings)


def test_safe905_update_then_send_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Phase1b:
        pass

    class Fine(Actor):
        def receive(self, src, message):
            if message.round < self.round:
                return
            self.round = message.round
            self.send(src, Phase1b(round=self.round))
    """))
    assert "SAFE905" not in rules_of(findings)


def test_safe905_sibling_branch_is_not_post_send(tmp_path):
    """A Phase2a elif branch below the Phase1a branch's send is NOT
    control-flow-after it (the caspaxos shape)."""
    findings = run_rules(role_project(tmp_path, """
    class Phase1b:
        pass

    class Fine(Actor):
        def receive(self, src, message):
            if message.kind == 1:
                if message.round < self.round:
                    return
                self.round = message.round
                self.send(src, Phase1b(round=self.round))
            elif message.kind == 2:
                if message.round < self.round:
                    return
                self.round = message.round
                self.vote_round = message.round
    """))
    assert "SAFE905" not in rules_of(findings)


def test_safe905_nack_is_not_a_promise(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Phase1bNack:
        pass

    class Fine(Actor):
        def receive(self, src, message):
            if message.round <= self.round:
                self.send(src, Phase1bNack(round=self.round))
                return
            self.round = message.round
    """))
    assert "SAFE905" not in rules_of(findings)


def test_safe905_local_alias_send_flagged(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Phase1b:
        pass

    class Bad(Actor):
        def receive(self, src, message):
            if message.round < self.round:
                return
            reply = Phase1b(round=message.round)
            self.send(src, reply)
            self.round = message.round
    """))
    assert "SAFE905" in rules_of(findings)


def test_safe905_pragma_suppresses(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Phase1b:
        pass

    class Odd(Actor):
        def receive(self, src, message):
            if message.round < self.round:
                return
            self.send(src, Phase1b(round=message.round))
            # the transport serializes at send in BOTH arms here.
            # paxlint: disable=SAFE905
            self.round = message.round
    """))
    assert "SAFE905" not in rules_of(findings)


# --- ALIAS10xx: sim-vs-deployed mutable aliasing (paxsafe) ------------------


def test_alias1001_live_list_in_message(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Batch:
        pass

    class Bad(Actor):
        def __init__(self):
            self.pending = []

        def receive(self, src, message):
            self.pending.append(message)
            self.send(src, Batch(values=self.pending))
    """))
    assert any(f.rule == "ALIAS1001" and f.detail == "self.pending"
               for f in findings)


def test_alias1001_tuple_copy_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Batch:
        pass

    class Fine(Actor):
        def __init__(self):
            self.pending = []

        def receive(self, src, message):
            self.pending.append(message)
            self.send(src, Batch(values=tuple(self.pending)))
            self.pending.clear()
    """))
    assert "ALIAS1001" not in rules_of(findings)


def test_alias1001_unmutated_field_is_clean(tmp_path):
    """A mutable field no handler mutates cannot race the send."""
    findings = run_rules(role_project(tmp_path, """
    class Batch:
        pass

    class Fine(Actor):
        def __init__(self):
            self.static_config = {}

        def receive(self, src, message):
            self.send(src, Batch(values=self.static_config))
    """))
    assert "ALIAS1001" not in rules_of(findings)


def test_alias1001_resolves_sender_helper(tmp_path):
    """The alias leaks at the call site of a sender helper whose
    parameter flows into the message construction."""
    findings = run_rules(role_project(tmp_path, """
    class Batch:
        pass

    class Bad(Actor):
        def __init__(self):
            self.pending = []

        def receive(self, src, message):
            self.pending.append(message)
            self._reply(src, self.pending)

        def _reply(self, dst, values):
            self.send(dst, Batch(values=values))
    """))
    assert any(f.rule == "ALIAS1001" and f.scope == "Bad.receive"
               for f in findings)


def test_alias1001_locally_constructed_message(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Batch:
        pass

    class Bad(Actor):
        def __init__(self):
            self.pending = []

        def on_drain(self):
            batch = Batch(values=self.pending)
            self.send("dst", batch)

        def receive(self, src, message):
            self.pending.append(message)
    """))
    assert "ALIAS1001" in rules_of(findings)


def test_alias1001_pragma_suppresses(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Batch:
        pass

    class Odd(Actor):
        def __init__(self):
            self.pending = []

        def receive(self, src, message):
            self.pending.append(message)
            # ownership transfer: the field is rebound, never
            # mutated, after this send.
            # paxlint: disable=ALIAS1001
            self.send(src, Batch(values=self.pending))
    """))
    assert "ALIAS1001" not in rules_of(findings)


def test_alias1002_mutates_received_message(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            message.values.append(1)
    """))
    assert any(f.rule == "ALIAS1002"
               and f.detail == "message.values.append"
               for f in findings)


def test_alias1002_attribute_assignment_flagged(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            message.round = 7
    """))
    assert "ALIAS1002" in rules_of(findings)


def test_alias1002_taint_reaches_dispatch_helper(tmp_path):
    """Cross-method: receive's dispatch passes the message into a
    _handle_* helper, whose mutation is the same race."""
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            self._handle_write(src, message)

        def _handle_write(self, src, write):
            write.entries.pop()
    """))
    assert any(f.rule == "ALIAS1002"
               and f.scope == "Bad._handle_write"
               for f in findings)


def test_alias1002_local_alias_of_message_state(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            values = message.values
            values.append(1)
    """))
    assert "ALIAS1002" in rules_of(findings)


def test_alias1002_copy_before_mutate_is_clean(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Fine(Actor):
        def receive(self, src, message):
            values = list(message.values)
            values.append(1)
            self.send(src, values)
    """))
    assert "ALIAS1002" not in rules_of(findings)


def test_alias1002_pragma_suppresses(tmp_path):
    findings = run_rules(role_project(tmp_path, """
    class Odd(Actor):
        def receive(self, src, message):
            # the sender constructs a fresh message per destination.
            # paxlint: disable=ALIAS1002
            message.values.append(1)
    """))
    assert "ALIAS1002" not in rules_of(findings)


def test_safe_alias_repo_is_clean_or_justified():
    """The repo gate: SAFE9xx/ALIAS10xx produce zero unsuppressed
    findings, and every suppressing pragma carries a justification
    comment (the safety argument), not a bare disable."""
    import os as _os
    import re as _re

    import frankenpaxos_tpu
    from frankenpaxos_tpu.analysis.alias_rules import (
        check as _alias_check,
    )
    from frankenpaxos_tpu.analysis.core import (
        _suppressed,
        Project as _P,
    )
    from frankenpaxos_tpu.analysis.safety_rules import (
        check as _safety_check,
    )

    root = _os.path.dirname(_os.path.dirname(frankenpaxos_tpu.__file__))
    proj = _P(root, package="frankenpaxos_tpu")
    findings = list(_safety_check(proj)) + list(_alias_check(proj))
    live = [f for f in findings if not _suppressed(proj, f)]
    assert live == [], [f.render() for f in live]
    # Every SAFE/ALIAS pragma line must sit in a comment block with
    # more to say than the directive itself.
    pragma_re = _re.compile(r"#\s*paxlint:\s*disable=((?:SAFE|ALIAS)[0-9]+)")
    for mod in proj:
        for i, line in enumerate(mod.lines):
            m = pragma_re.search(line)
            if not m:
                continue
            # Justification: comment text beyond the directive on this
            # line, or a comment line directly above.
            before = line[:m.start()].strip()
            after = line[m.end():].strip(" -#")
            above = mod.lines[i - 1].strip() if i > 0 else ""
            justified = (before.startswith("#") and len(before) > 5) \
                or len(after) > 5 or above.startswith("#")
            assert justified, (
                f"{mod.path}:{i + 1}: bare {m.group(1)} pragma without "
                f"a justification comment")


def test_paxlint_runtime_budget():
    """The full project run stays under the CI budget. PR 7 cut the
    run from 124s to 15s with project-level caches; the paxsafe
    interprocedural passes (SAFE9xx guard closures, ALIAS10xx taint)
    must stay inside that cached-namespace/callgraph infrastructure
    rather than re-walking the tree per rule. The paxown families
    (OWN11xx escape fixpoint, DEV12xx transfer discipline) ride the
    same memoized callgraph and are included in this budget; the
    diff-aware (<10s) twin lives in tests/test_analysis_cli.py."""
    import os as _os
    import time as _time

    import frankenpaxos_tpu

    root = _os.path.dirname(_os.path.dirname(frankenpaxos_tpu.__file__))
    start = _time.monotonic()
    proj = Project(root, package="frankenpaxos_tpu")
    run_rules(proj)
    elapsed = _time.monotonic() - start
    assert elapsed < 30.0, (
        f"paxlint full-project run took {elapsed:.1f}s; the CI budget "
        f"is 30s (docs/ANALYSIS.md)")


def test_format_json_emits_finding_records(tmp_path):
    """--format=json: one JSON document of file/line/rule/scope/
    detail/message/baselined records, exit code still gating; --output
    writes the same document to a file while stdout keeps the human
    report."""
    import json as _json

    (tmp_path / "frankenpaxos_tpu").mkdir()
    (tmp_path / "frankenpaxos_tpu" / "bad.py").write_text(
        textwrap.dedent(ACTOR_PREAMBLE) + textwrap.dedent("""
    class Bad(Actor):
        def on_drain(self):
            time.sleep(0.5)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "frankenpaxos_tpu.analysis",
         "--root", str(tmp_path), "--format", "json"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    document = _json.loads(proc.stdout)
    assert document["new"] == 1
    (record,) = document["findings"]
    assert record["rule"] == "PAX103"
    assert record["file"] == "frankenpaxos_tpu/bad.py"
    assert record["scope"] == "Bad.on_drain"
    assert record["baselined"] is False
    assert record["line"] > 0 and record["message"]
    # --output keeps the human report on stdout and writes the file.
    out = tmp_path / "paxlint.json"
    proc = subprocess.run(
        [sys.executable, "-m", "frankenpaxos_tpu.analysis",
         "--root", str(tmp_path), "--output", str(out)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1
    assert "PAX103" in proc.stdout  # human text
    on_disk = _json.loads(out.read_text())
    assert on_disk["findings"] == document["findings"]


def test_list_rules_includes_paxsafe_families():
    proc = subprocess.run(
        [sys.executable, "-m", "frankenpaxos_tpu.analysis",
         "--list-rules"], capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0
    for rule in ("SAFE901", "SAFE902", "SAFE903", "SAFE904", "SAFE905",
                 "ALIAS1001", "ALIAS1002"):
        assert rule in proc.stdout


def test_safe905_nested_resend_def_is_not_post_send(tmp_path):
    """The repo's resend-timer idiom: a Phase1b send inside a nested
    ``def resend()`` has no post-send region in the ENCLOSING handler
    (the outer statements run before the timer ever fires)."""
    findings = run_rules(role_project(tmp_path, """
    class Phase1b:
        pass

    class Fine(Actor):
        def receive(self, src, message):
            if message.round < self.round:
                return
            def resend():
                self.send(src, Phase1b(round=self.round))
            self.timer("resend", 1.0, resend)
            self.round = message.round
            self.send(src, Phase1b(round=self.round))
    """))
    assert "SAFE905" not in rules_of(findings)


def test_safe901_tuple_unpacking_write_is_visible(tmp_path):
    """``self.round, self.vote_round = m.round, m.round`` is the same
    unguarded adoption as the plain assignment."""
    findings = run_rules(role_project(tmp_path, """
    class Bad(Actor):
        def receive(self, src, message):
            self.round, self.vote_round = message.round, message.round
    """))
    assert any(f.rule == "SAFE901" and f.detail == "self.round"
               for f in findings)
    assert any(f.rule == "SAFE902" and f.detail == "self.vote_round"
               for f in findings)
