"""Message serialization.

Reference behavior: Serializer.scala:5-10 / ProtoSerializer.scala:3-11 --
every inbound message type has a serializer with ``to_bytes`` /
``from_bytes`` plus a debug ``to_pretty_string``.

Protocol messages here are plain dataclasses; the default wire format is
pickle (simple, complete). The framing layer (tcp_transport / the C++
codec) is format-agnostic, so a fixed-layout binary codec can replace
pickle per-message-type without touching protocol code.

SECURITY: the no-code-execution-on-decode property holds ONLY for
messages carried by a registered ``MessageCodec`` (wire tags 1..255;
128+ ride the 0x00-prefixed extended page).
Unregistered message types -- and a handful of escape hatches inside
binary codecs, e.g. exotic sim addresses -- fall back to pickle, and
``pickle.loads`` on attacker-controlled bytes executes arbitrary code.
The reference avoids this wholesale by using protobuf everywhere
(ProtoSerializer.scala:3-11). Deployments whose transport crosses a
trust boundary must call ``set_pickle_fallback(False)``: decoding then
hard-errors on any pickle frame (first byte >= 0x80) instead of
evaluating it, and encoding an unregistered type raises at the sender.
"""

from __future__ import annotations

import abc
import pickle
import struct
from typing import Generic, TypeVar

M = TypeVar("M")


class Serializer(abc.ABC, Generic[M]):
    @abc.abstractmethod
    def to_bytes(self, message: M) -> bytes:
        ...

    @abc.abstractmethod
    def from_bytes(self, data: bytes) -> M:
        ...

    def to_pretty_string(self, message: M) -> str:
        return repr(message)


class PickleSerializer(Serializer[M]):
    """Default serializer for dataclass messages."""

    def to_bytes(self, message: M) -> bytes:
        return pickle.dumps(message, protocol=pickle.HIGHEST_PROTOCOL)

    def from_bytes(self, data: bytes) -> M:
        return pickle.loads(data)


class MessageCodec(abc.ABC):
    """A fixed-layout binary codec for ONE message type (the
    ProtoSerializer.scala:3-11 analog: schema'd, language-agnostic, no
    arbitrary code execution on decode)."""

    #: The message class this codec handles.
    message_type: type
    #: Wire tag. 1..127 encode as a single leading byte (pickle streams
    #: start with 0x80, so one byte discriminates binary-coded from
    #: pickled messages). Tags 128..255 live on the EXTENDED PAGE:
    #: byte 0x00 -- never a primary tag, never a pickle opcode -- is the
    #: escape prefix, and the second byte carries ``tag - 128``. The
    #: primary page filled up at PR 4 (every protocol family carries
    #: codecs); new subsystems allocate from the extended page.
    tag: int

    @abc.abstractmethod
    def encode(self, out: bytearray, message) -> None:
        ...

    @abc.abstractmethod
    def decode(self, buf: bytes, at: int) -> tuple:
        """-> (message, next_offset)."""


_CODECS_BY_TYPE: dict[type, MessageCodec] = {}
_CODECS_BY_TAG: dict[int, MessageCodec] = {}

#: Whether HybridSerializer (and codec escape hatches) may pickle.
#: Default True: sims and single-trust-domain deployments keep the
#: complete-coverage fallback. See the module docstring for the
#: security trade-off.
_PICKLE_FALLBACK = True


def set_pickle_fallback(enabled: bool) -> None:
    """Globally allow/forbid the pickle wire fallback. With it disabled,
    decode raises on pickle frames instead of executing them, and encode
    raises on message types without a registered codec."""
    global _PICKLE_FALLBACK
    _PICKLE_FALLBACK = enabled


def pickle_fallback_enabled() -> bool:
    return _PICKLE_FALLBACK


def guarded_pickle_loads(raw: bytes, what: str):
    """The ONE entry point for pickle escape hatches inside binary
    codecs (exotic addresses/values/commands): every hatch must decode
    through here so ``set_pickle_fallback(False)`` covers it."""
    if not _PICKLE_FALLBACK:
        raise ValueError(
            f"pickle fallback disabled: refusing pickled {what} inside "
            f"binary frame")
    try:
        return pickle.loads(raw)
    except Exception as e:
        # A corrupt frame can route arbitrary bytes into this hatch (a
        # flipped address-kind byte -- found by the registry-wide
        # containment fuzz), and pickle.loads raises open-ended
        # exception types on garbage. Normalize to the ValueError
        # containment channel like every other decode failure.
        raise ValueError(f"corrupt pickled {what}: {e!r}") from e


def guarded_pickle_dumps(obj, what: str) -> bytes:
    """Encode-side twin of :func:`guarded_pickle_loads`: fail at the
    sender instead of emitting a frame the receiver must refuse."""
    if not _PICKLE_FALLBACK:
        raise ValueError(
            f"pickle fallback disabled: cannot encode {what} {obj!r} in "
            f"a binary frame")
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def register_codec(codec: MessageCodec) -> None:
    """Install a binary codec for its message type (process-global: the
    codec IS the wire schema, so every actor must agree on it)."""
    if not 1 <= codec.tag <= 255:
        raise ValueError(f"tag {codec.tag} outside 1..255")
    existing = _CODECS_BY_TAG.get(codec.tag)
    if existing is not None and type(existing) is not type(codec):
        raise ValueError(f"tag {codec.tag} already taken by {existing}")
    _CODECS_BY_TYPE[codec.message_type] = codec
    _CODECS_BY_TAG[codec.tag] = codec


class HybridSerializer(Serializer[M]):
    """Binary fixed-layout encoding for registered hot message types
    (Phase2a/Phase2b/Chosen/ClientRequest...); pickle for the long tail.

    The first byte discriminates: 1..127 selects a registered codec,
    0x00 escapes to the extended tag page (the second byte selects tag
    ``128 + byte``), and 0x80+ is a pickle stream (every pickle
    protocol >= 2 starts with the PROTO opcode 0x80). Senders and
    receivers therefore interoperate in any mix of
    registered/unregistered types.
    """

    def to_bytes(self, message: M) -> bytes:
        codec = _CODECS_BY_TYPE.get(type(message))
        if codec is None:
            if not _PICKLE_FALLBACK:
                raise ValueError(
                    f"pickle fallback disabled and no codec registered "
                    f"for {type(message).__name__}")
            return pickle.dumps(message,
                                protocol=pickle.HIGHEST_PROTOCOL)
        if codec.tag > 127:
            out = bytearray((0, codec.tag - 128))
        else:
            out = bytearray((codec.tag,))
        codec.encode(out, message)
        return bytes(out)

    def from_bytes(self, data: bytes) -> M:
        tag = data[0]
        if tag >= 128:
            if not _PICKLE_FALLBACK:
                raise ValueError(
                    "pickle fallback disabled: refusing to decode a "
                    "pickle frame (first byte >= 0x80)")
            return pickle.loads(data)
        at = 1
        if tag == 0:
            # Extended page: 0x00 escape + one tag byte. A bare 0x00
            # frame is corruption, not a message.
            if len(data) < 2:
                raise ValueError("truncated extended-tag frame")
            tag = 128 + data[1]
            at = 2
        codec = _CODECS_BY_TAG.get(tag)
        if codec is None:
            raise ValueError(f"no codec registered for wire tag {tag}")
        try:
            message, _ = codec.decode(data, at)
        except ValueError:
            raise
        except (struct.error, IndexError, KeyError, UnicodeDecodeError,
                OverflowError, MemoryError) as e:
            # THE CONTAINMENT CONTRACT (fuzzed over the whole codec
            # registry in tests/test_wire_codecs.py): a corrupt binary
            # frame decodes to garbage or raises ValueError -- never an
            # uncontrolled exception type. The transport's
            # corrupt-frame guard logs-and-drops on any Exception, but
            # OTHER decode sites (WAL replay, tests, tools) rely on
            # ValueError being the one failure channel.
            raise ValueError(
                f"corrupt frame for wire tag {tag}: {e!r}") from e
        return message


#: Shared default: one instance so registrations apply everywhere.
DEFAULT_SERIALIZER: HybridSerializer = HybridSerializer()
