"""Message serialization.

Reference behavior: Serializer.scala:5-10 / ProtoSerializer.scala:3-11 --
every inbound message type has a serializer with ``to_bytes`` /
``from_bytes`` plus a debug ``to_pretty_string``.

Protocol messages here are plain dataclasses; the default wire format is
pickle (simple, complete). The framing layer (tcp_transport / the C++
codec) is format-agnostic, so a fixed-layout binary codec can replace
pickle per-message-type without touching protocol code.
"""

from __future__ import annotations

import abc
import pickle
from typing import Generic, TypeVar

M = TypeVar("M")


class Serializer(abc.ABC, Generic[M]):
    @abc.abstractmethod
    def to_bytes(self, message: M) -> bytes:
        ...

    @abc.abstractmethod
    def from_bytes(self, data: bytes) -> M:
        ...

    def to_pretty_string(self, message: M) -> str:
        return repr(message)


class PickleSerializer(Serializer[M]):
    """Default serializer for dataclass messages."""

    def to_bytes(self, message: M) -> bytes:
        return pickle.dumps(message, protocol=pickle.HIGHEST_PROTOCOL)

    def from_bytes(self, data: bytes) -> M:
        return pickle.loads(data)


class MessageCodec(abc.ABC):
    """A fixed-layout binary codec for ONE message type (the
    ProtoSerializer.scala:3-11 analog: schema'd, language-agnostic, no
    arbitrary code execution on decode)."""

    #: The message class this codec handles.
    message_type: type
    #: Wire tag, 1..127 (pickle streams start with 0x80, so one leading
    #: byte discriminates binary-coded from pickled messages).
    tag: int

    @abc.abstractmethod
    def encode(self, out: bytearray, message) -> None:
        ...

    @abc.abstractmethod
    def decode(self, buf: bytes, at: int) -> tuple:
        """-> (message, next_offset)."""


_CODECS_BY_TYPE: dict[type, MessageCodec] = {}
_CODECS_BY_TAG: dict[int, MessageCodec] = {}


def register_codec(codec: MessageCodec) -> None:
    """Install a binary codec for its message type (process-global: the
    codec IS the wire schema, so every actor must agree on it)."""
    if not 1 <= codec.tag <= 127:
        raise ValueError(f"tag {codec.tag} outside 1..127")
    existing = _CODECS_BY_TAG.get(codec.tag)
    if existing is not None and type(existing) is not type(codec):
        raise ValueError(f"tag {codec.tag} already taken by {existing}")
    _CODECS_BY_TYPE[codec.message_type] = codec
    _CODECS_BY_TAG[codec.tag] = codec


class HybridSerializer(Serializer[M]):
    """Binary fixed-layout encoding for registered hot message types
    (Phase2a/Phase2b/Chosen/ClientRequest...); pickle for the long tail.

    The first byte discriminates: 1..127 selects a registered codec,
    0x80+ is a pickle stream (every pickle protocol >= 2 starts with
    the PROTO opcode 0x80). Senders and receivers therefore
    interoperate in any mix of registered/unregistered types.
    """

    def to_bytes(self, message: M) -> bytes:
        codec = _CODECS_BY_TYPE.get(type(message))
        if codec is None:
            return pickle.dumps(message,
                                protocol=pickle.HIGHEST_PROTOCOL)
        out = bytearray((codec.tag,))
        codec.encode(out, message)
        return bytes(out)

    def from_bytes(self, data: bytes) -> M:
        tag = data[0]
        if tag >= 128:
            return pickle.loads(data)
        codec = _CODECS_BY_TAG.get(tag)
        if codec is None:
            raise ValueError(f"no codec registered for wire tag {tag}")
        message, _ = codec.decode(data, 1)
        return message


#: Shared default: one instance so registrations apply everywhere.
DEFAULT_SERIALIZER: HybridSerializer = HybridSerializer()
