"""Message serialization.

Reference behavior: Serializer.scala:5-10 / ProtoSerializer.scala:3-11 --
every inbound message type has a serializer with ``to_bytes`` /
``from_bytes`` plus a debug ``to_pretty_string``.

Protocol messages here are plain dataclasses; the default wire format is
pickle (simple, complete). The framing layer (tcp_transport / the C++
codec) is format-agnostic, so a fixed-layout binary codec can replace
pickle per-message-type without touching protocol code.
"""

from __future__ import annotations

import abc
import pickle
from typing import Generic, TypeVar

M = TypeVar("M")


class Serializer(abc.ABC, Generic[M]):
    @abc.abstractmethod
    def to_bytes(self, message: M) -> bytes:
        ...

    @abc.abstractmethod
    def from_bytes(self, data: bytes) -> M:
        ...

    def to_pretty_string(self, message: M) -> str:
        return repr(message)


class PickleSerializer(Serializer[M]):
    """Default serializer for dataclass messages."""

    def to_bytes(self, message: M) -> bytes:
        return pickle.dumps(message, protocol=pickle.HIGHEST_PROTOCOL)

    def from_bytes(self, data: bytes) -> M:
        return pickle.loads(data)
