"""paxwire: drain-granular batched wire frames for the native transport.

The TPU kernels commit ~1.6B cmds/s while the deployed TCP path does
thousands -- the per-message Python frame layer (one header f-string,
one codec dispatch, one ``writer.write`` PER MESSAGE) is the deployed
bottleneck. paxwire is the batch layer underneath TcpTransport:

  * **Batch frames.** A drain's same-type messages to one peer coalesce
    into ONE wire frame whose payload is an ordinary extended-page
    codec message::

        [0x00][tag-128][u32le count][count * u32le seg_len][segments]

    The segments are the messages' unmodified wire payloads, copied
    raw -- a Phase2aRun/ClientReplyArray whose value bytes are
    ``LazyValueArray`` segments batches without re-materializing a
    value. Because the batch leads with a REGISTERED wire tag,
    ``serve/lanes.py``'s one-byte frame classifier (and the bounded
    inbox shedding built on it) works on batch frames without decode:
    client-request batches ride :data:`CLIENT_BATCH_TAG` (shedable),
    everything else :data:`CONTROL_BATCH_TAG` (never shed).

  * **Coalescers.** A protocol can register a per-tag coalescer that
    understands its message layout and merges a run of payloads into
    something DENSER than concatenation -- the ack coalescing path:
    ``protocols/multipaxos/wire.py`` folds a drain's Phase2b stream to
    one peer into run-granular ack ranges (see
    :func:`register_coalescer`).

  * **Flush plans.** :func:`plan_flush` turns a connection's pending
    ``(header, payload, lane)`` entries into the scatter/gather segment
    list one ``socket.sendmsg`` (writev) pushes out -- tiny header
    prefixes interleaved with the original payload ``bytes`` objects,
    never a per-frame join.

Receivers EXPAND batch frames back into the original messages (same
``src``, same frame-header TraceContext) before delivery, so protocol
handlers and per-message admission are untouched: batching changes the
syscall and dispatch count, never the semantics. Expansion rides the
``__wire_expand__`` protocol: any decoded message exposing
``__wire_expand__(serializer) -> iterable`` is flattened by the
transport (coalesced ack batches use it to surface as the
Phase2b/Phase2bRange messages the proxy leaders already handle).

Wire format details and the A/B artifact: docs/TRANSPORT.md.
"""

from __future__ import annotations

import struct
from typing import Callable, Optional

from frankenpaxos_tpu import native

#: Extended-page wire tags for the two batch envelopes. Control is the
#: conservative default; the client tag exists ONLY so the frame-layer
#: classifier can shed a batch of client requests like it sheds the
#: requests themselves.
CONTROL_BATCH_TAG = 150
CLIENT_BATCH_TAG = 151

#: Coalesce a run only when it actually merges something.
MIN_BATCH = 2

_LEN = struct.Struct(">I")
MAX_FRAME = 10 * 1024 * 1024  # TcpTransport's frame cap


class FrameBatch:
    """A decoded control-lane batch frame: opaque wire segments, each
    one complete message payload. The transport expands it; actors
    never see one."""

    __slots__ = ("segments",)

    def __init__(self, segments):
        self.segments = tuple(segments)

    def __wire_expand__(self, serializer):
        return [serializer.from_bytes(bytes(s)) for s in self.segments]

    def __eq__(self, other):
        if isinstance(other, FrameBatch):
            return (type(self) is type(other)
                    and self.segments == other.segments)
        return NotImplemented

    def __repr__(self) -> str:
        return f"{type(self).__name__}(n={len(self.segments)})"


class ClientFrameBatch(FrameBatch):
    """The client-lane twin (named in serve/lanes.py's client lane so
    both the tag-level and type-level classifiers shed it)."""


def _register_batch_codecs() -> None:
    # Deferred: serializer imports nothing from here, but keeping the
    # MessageCodec subclasses inside a function avoids importing the
    # registry at module scope before test monkeypatching can happen.
    from frankenpaxos_tpu.runtime.serializer import (
        MessageCodec,
        register_codec,
    )

    class _BatchCodec(MessageCodec):
        # Encoded and decoded by the TRANSPORT's flush/scan paths, not
        # by any protocol role (paxflow FLOW403 skips transport_layer
        # codecs -- there is deliberately no role send site).
        transport_layer = True

        def encode(self, out, message):
            segments = message.segments
            out += native.batch_header(self.tag,
                                       [len(s) for s in segments])[2:]
            for segment in segments:
                out += segment

        def decode(self, buf, at):
            offsets = native.scan_batch(buf, at)
            return self.message_type(
                tuple(bytes(buf[s:e]) for s, e in offsets)), len(buf)

    class FrameBatchCodec(_BatchCodec):
        message_type = FrameBatch
        tag = CONTROL_BATCH_TAG

    class ClientFrameBatchCodec(_BatchCodec):
        message_type = ClientFrameBatch
        tag = CLIENT_BATCH_TAG

    register_codec(FrameBatchCodec())
    register_codec(ClientFrameBatchCodec())


_register_batch_codecs()


# --- coalescers --------------------------------------------------------------

#: wire tag -> fn(list of payload bytes) -> denser single payload, or
#: None to decline (fall back to the generic batch envelope).
_COALESCERS: dict[int, Callable[[list], Optional[bytes]]] = {}


def register_coalescer(tag: int,
                       fn: Callable[[list], Optional[bytes]]) -> None:
    """Install ``fn`` as the coalescer for runs of ``tag`` payloads on
    one connection within one flush. The function receives the raw wire
    payloads (tag byte included) in send order and returns ONE payload
    that decodes to a message expanding back to equivalent deliveries
    (``__wire_expand__``), or None to decline."""
    _COALESCERS[tag] = fn


def leading_tag(payload) -> Optional[int]:
    """The wire tag a payload leads with: 1..127 primary, 128..255
    extended, -1 for a pickle stream, None when undecidable."""
    if not payload:
        return None
    b0 = payload[0]
    if b0 == 0:
        return 128 + payload[1] if len(payload) > 1 else None
    if b0 >= 128:
        return -1
    return b0


def is_batch_payload(data) -> bool:
    """Is this frame payload a batch envelope? One-or-two byte check,
    run on every inbound frame."""
    return (len(data) > 1 and data[0] == 0
            and data[1] + 128 in (CONTROL_BATCH_TAG, CLIENT_BATCH_TAG))


def split_batch(data) -> list[bytes]:
    """A batch frame payload -> its message payload segments (raises
    ValueError on a torn/corrupt table, the transport's corrupt-frame
    containment channel)."""
    return [bytes(data[s:e]) for s, e in native.scan_batch(data, 2)]


# --- flush planning ----------------------------------------------------------


class FlushPlan:
    """One connection flush: the writev segment list plus its stats."""

    __slots__ = ("segments", "frames", "messages", "nbytes",
                 "coalesced_acks")

    def __init__(self):
        self.segments: list = []
        self.frames = 0
        self.messages = 0
        self.nbytes = 0
        self.coalesced_acks = 0

    def _add_frame(self, header: bytes, payload_parts: list,
                   inner_payload_len: int) -> None:
        inner = 4 + len(header) + inner_payload_len
        prefix = _LEN.pack(inner) + _LEN.pack(len(header)) + header
        self.segments.append(prefix)
        self.segments.extend(payload_parts)
        self.frames += 1
        self.nbytes += 4 + inner


def _client_tags() -> frozenset:
    from frankenpaxos_tpu.serve.lanes import client_lane_tags

    return client_lane_tags()


def plan_flush(entries: list) -> FlushPlan:
    """``entries`` is a connection's pending list in send order; each
    entry is indexable with the frame header at ``[0]`` and the message
    payload at ``[1]``. Consecutive same-header entries with the same
    leading wire tag become one batch frame (or one coalesced frame
    when the tag has a registered coalescer); singletons stay plain
    frames. Send order is preserved throughout -- only ADJACENT
    same-type messages merge."""
    plan = FlushPlan()
    plan.messages = len(entries)
    i, n = 0, len(entries)
    client_tags = None
    while i < n:
        header, payload = entries[i][0], entries[i][1]
        tag = leading_tag(payload)
        j = i + 1
        while j < n and entries[j][0] == header \
                and leading_tag(entries[j][1]) == tag:
            j += 1
        run = [e[1] for e in entries[i:j]]
        if len(run) < MIN_BATCH or tag is None:
            for payload in run:
                plan._add_frame(header, [payload], len(payload))
            i = j
            continue
        coalescer = _COALESCERS.get(tag) if tag is not None else None
        if coalescer is not None:
            try:
                merged = coalescer(run)
            except Exception:
                # The decline contract is "return None", but a raising
                # coalescer must not lose the flush's already-popped
                # entries (or abort the rest of the flush pass):
                # contain it and fall back to the generic batch.
                merged = None
            if merged is not None and \
                    4 + len(header) + len(merged) <= MAX_FRAME:
                plan.coalesced_acks += len(run)
                plan._add_frame(header, [merged], len(merged))
                i = j
                continue
        if client_tags is None:
            client_tags = _client_tags()
        batch_tag = (CLIENT_BATCH_TAG if tag in client_tags
                     else CONTROL_BATCH_TAG)
        # Split the run so no batch frame exceeds the 10 MiB cap (the
        # per-entry cap was enforced at send time, so every chunk makes
        # progress).
        k = 0
        while k < len(run):
            chunk: list = []
            chunk_bytes = 0
            while k < len(run):
                seg = run[k]
                add = 4 + len(seg)
                if chunk and (10 + len(header) + chunk_bytes + add
                              > MAX_FRAME):
                    break
                chunk.append(seg)
                chunk_bytes += add
                k += 1
            if len(chunk) == 1:
                plan._add_frame(header, chunk, len(chunk[0]))
                continue
            bh = native.batch_header(batch_tag,
                                     [len(s) for s in chunk])
            plan._add_frame(header, [bh] + chunk,
                            len(bh) + chunk_bytes - 4 * len(chunk))
        i = j
    return plan
