"""Leveled logging with checked assertions.

Reference behavior: Logger.scala:1-118 (five levels; ``check*`` helpers;
``fatal`` raises), PrintLogger/FileLogger/FakeLogger variants.

Messages are passed lazily (callables or strings) so debug logging is
free when filtered, matching the reference's by-name parameters
(Logger.scala:26-60).
"""

from __future__ import annotations

import enum
import sys
import time
from typing import Any, Callable, Union

LazyMessage = Union[str, Callable[[], str]]


class LogLevel(enum.IntEnum):
    DEBUG = 0
    INFO = 1
    WARN = 2
    ERROR = 3
    FATAL = 4


def _force(message: LazyMessage) -> str:
    return message() if callable(message) else message


class FatalError(RuntimeError):
    """Raised by Logger.fatal (the analog of fatal returning Nothing)."""


class Logger:
    def __init__(self, log_level: LogLevel = LogLevel.DEBUG):
        self.log_level = log_level

    # --- backend hook -----------------------------------------------------
    def emit(self, level: LogLevel, message: str) -> None:
        raise NotImplementedError

    # --- leveled logging --------------------------------------------------
    def _log(self, level: LogLevel, message: LazyMessage) -> None:
        if level >= self.log_level:
            self.emit(level, _force(message))

    def debug(self, message: LazyMessage) -> None:
        self._log(LogLevel.DEBUG, message)

    def info(self, message: LazyMessage) -> None:
        self._log(LogLevel.INFO, message)

    def warn(self, message: LazyMessage) -> None:
        self._log(LogLevel.WARN, message)

    def error(self, message: LazyMessage) -> None:
        self._log(LogLevel.ERROR, message)

    def fatal(self, message: LazyMessage) -> "NoReturn":  # type: ignore[name-defined]  # noqa: F821
        text = _force(message)
        self.emit(LogLevel.FATAL, text)
        raise FatalError(text)

    # --- checked assertions (Logger.scala:62-117) -------------------------
    def check(self, condition: bool, message: LazyMessage = "check failed"):
        if not condition:
            self.fatal(message)

    def check_eq(self, lhs: Any, rhs: Any) -> None:
        if lhs != rhs:
            self.fatal(f"check_eq failed: {lhs!r} != {rhs!r}")

    def check_ne(self, lhs: Any, rhs: Any) -> None:
        if lhs == rhs:
            self.fatal(f"check_ne failed: {lhs!r} == {rhs!r}")

    def check_lt(self, lhs: Any, rhs: Any) -> None:
        if not lhs < rhs:
            self.fatal(f"check_lt failed: {lhs!r} >= {rhs!r}")

    def check_le(self, lhs: Any, rhs: Any) -> None:
        if not lhs <= rhs:
            self.fatal(f"check_le failed: {lhs!r} > {rhs!r}")

    def check_gt(self, lhs: Any, rhs: Any) -> None:
        if not lhs > rhs:
            self.fatal(f"check_gt failed: {lhs!r} <= {rhs!r}")

    def check_ge(self, lhs: Any, rhs: Any) -> None:
        if not lhs >= rhs:
            self.fatal(f"check_ge failed: {lhs!r} < {rhs!r}")


class PrintLogger(Logger):
    def emit(self, level: LogLevel, message: str) -> None:
        stream = sys.stderr if level >= LogLevel.WARN else sys.stdout
        print(f"[{level.name:5s}] {time.strftime('%H:%M:%S')} {message}",
              file=stream, flush=True)


class FileLogger(Logger):
    def __init__(self, path: str, log_level: LogLevel = LogLevel.DEBUG,
                 flush: bool = True):
        super().__init__(log_level)
        self._file = open(path, "a")
        self._flush = flush

    def emit(self, level: LogLevel, message: str) -> None:
        self._file.write(
            f"[{level.name:5s}] {time.strftime('%H:%M:%S')} {message}\n")
        if self._flush:
            self._file.flush()


class FakeLogger(Logger):
    """Captures log records for tests (FakeLogger.scala)."""

    def __init__(self, log_level: LogLevel = LogLevel.DEBUG):
        super().__init__(log_level)
        self.records: list[tuple[LogLevel, str]] = []

    def emit(self, level: LogLevel, message: str) -> None:
        self.records.append((level, message))
