"""Frozen PRE-paxsim simulator delivery machinery (the legacy core).

PR "paxsim" rebuilt the simulator core around batched SoA delivery
waves (``sim_transport._run_wave``); this module pins the replaced
per-message machinery VERBATIM -- ``list.remove``-by-equality buffer
consumption, per-message partition/link checks, the duplicated
``deliver_all``/``deliver_all_coalesced`` drain loops, and the geo
event loop's per-message heap pops. It exists for two reasons:

1. **A/B truth**: ``bench/sim_core_ab.py`` measures the vectorized
   core against THIS arm (the same discipline as paxwire's
   ``batching=False`` legacy transport arm) -- the committed
   ``bench_results/sim_core_ab.json`` speedups are meaningless unless
   the baseline is the real pre-refactor code, not a degraded shim.
2. **Schedule equivalence**: ``tests/test_sim_core.py`` replays fixed
   seeds through both cores and asserts byte-identical delivery
   orders, which is what lets the chaos soaks and the geo goldens
   trust the new core without re-blessing every artifact.

Do not "improve" these bodies; they are a reference, not a code path.
"""

from __future__ import annotations

import heapq
from typing import Optional

from frankenpaxos_tpu.geo.transport import GeoSimTransport
from frankenpaxos_tpu.runtime.actor import Actor
from frankenpaxos_tpu.runtime.sim_transport import (
    DeliverMessage,
    SimMessage,
    SimTransport,
)


def _legacy_plain_deliver(self, message: SimMessage) -> Optional[Actor]:
    """Verbatim pre-paxsim ``SimTransport._deliver``: consume via
    ``list.remove`` (dataclass ``__eq__`` scan), then the per-message
    partition check / inbox bookkeeping / decode / receive."""
    try:
        self.messages.remove(message)
    except ValueError:
        self.logger.warn(f"delivering unbuffered message {message}")
        return None
    if self._inbox_policies and message.dst in self._inbox_policies:
        from frankenpaxos_tpu.serve.lanes import LANE_CLIENT, frame_lane

        if frame_lane(message.data) == LANE_CLIENT:
            self._inbox_depth[message.dst] = max(
                0, self._inbox_depth.get(message.dst, 0) - 1)
            pending = self._client_inbox.get(message.dst)
            if pending:
                try:
                    pending.remove(message)
                except ValueError:
                    pass
    if (message.dst in self.partitioned
            or message.src in self.partitioned):
        return None
    self.history.append(DeliverMessage(message))
    actor = self.actors.get(message.dst)
    if actor is None:
        self.logger.warn(f"no actor registered at {message.dst}")
        return None
    tracer = self.tracer
    if tracer is None:
        actor.receive(message.src,
                      actor.serializer.from_bytes(message.data))
        return actor
    span = tracer.receive_span(str(message.dst), "?", message.trace)
    with span:
        with tracer.stage("decode"):
            decoded = actor.serializer.from_bytes(message.data)
        span.name = (f"receive:{type(decoded).__name__}"
                     f"@{message.dst}")
        with tracer.stage("handler"):
            actor.receive(message.src, decoded)
    return actor


class LegacySimTransport(SimTransport):
    """Pre-paxsim :class:`SimTransport`: per-message Python dispatch."""

    def _deliver(self, message: SimMessage) -> Optional[Actor]:
        return _legacy_plain_deliver(self, message)

    def deliver_all(self, max_steps: int = 100000) -> int:
        steps = 0
        while self.messages and steps < max_steps:
            self.deliver_message(self.messages[0])
            steps += 1
        return steps

    def deliver_all_coalesced(self, max_steps: int = 100000) -> int:
        steps = 0
        while self.messages and steps < max_steps:
            wave = list(self.messages[:max_steps - steps])
            touched: list[Actor] = []
            seen: set[int] = set()
            for message in wave:
                actor = self._deliver(message)
                steps += 1
                if actor is not None and id(actor) not in seen:
                    seen.add(id(actor))
                    touched.append(actor)
            for actor in touched:
                self._drain(actor)
        return steps


class LegacyGeoSimTransport(GeoSimTransport):
    """Pre-paxsim :class:`GeoSimTransport`: per-message heap pops and
    link checks, ``list.remove`` buffer consumption."""

    def _deliver(self, message: SimMessage):
        self.arrivals.pop(message.id, None)
        self._by_id.pop(message.id, None)
        if not self.topology.link_up(message.src, message.dst):
            try:
                self.messages.remove(message)
            except ValueError:
                self.logger.warn(
                    f"dropping unbuffered message {message}")
            return None
        return _legacy_plain_deliver(self, message)

    def run_until(self, t_end: float, max_steps: int = 1_000_000) -> int:
        steps = 0
        while steps < max_steps:
            t = self.next_event_time()
            if t is None or t > t_end:
                break
            self.now = t
            touched: list = []
            seen: set[int] = set()
            for message in self._pop_due_messages(t):
                actor = self._deliver(message)
                steps += 1
                if actor is not None and id(actor) not in seen:
                    seen.add(id(actor))
                    touched.append(actor)
            for actor in touched:
                self._drain(actor)
            while self._deadline_heap:
                deadline, timer_id = self._deadline_heap[0]
                if deadline > t:
                    break
                heapq.heappop(self._deadline_heap)
                if self._deadlines.get(timer_id) == deadline:
                    self.trigger_timer(timer_id)
                    steps += 1
        self.now = max(self.now, t_end)
        return steps

    def run_until_quiescent(self, max_steps: int = 1_000_000,
                            horizon_s: float = 3600.0) -> int:
        steps = 0
        t_end = self.now + horizon_s
        while steps < max_steps:
            t = self._peek(self._arrival_heap, self.arrivals)
            if t is None or t > t_end:
                break
            self.now = max(self.now, t)
            _, message_id = heapq.heappop(self._arrival_heap)
            message = self._by_id.get(message_id)
            if message is None:
                continue
            actor = self._deliver(message)
            steps += 1
            if actor is not None:
                self._drain(actor)
        return steps
