"""Transport and Timer contracts.

Reference behavior: Transport.scala:44-99 (associated Address/Timer types;
register/send/sendNoFlush/flush/timer) and Timer.scala:23-42
(name/start/stop/reset; names are non-unique, purely for debugging).

THE CONTRACT (Transport.scala:37-40): a transport is a single-threaded
event loop. ``Actor.receive`` and timer callbacks run serially on one
logical thread; protocol code never needs locks and stays deterministic.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Hashable, TYPE_CHECKING

if TYPE_CHECKING:
    from frankenpaxos_tpu.runtime.actor import Actor

# Addresses are opaque hashable values; each transport documents its
# concrete address type (host:port tuples for TCP, strings for sim).
Address = Hashable


class Timer(abc.ABC):
    """A restartable one-shot timer owned by an actor's event loop."""

    @property
    @abc.abstractmethod
    def name(self) -> str:
        ...

    @abc.abstractmethod
    def start(self) -> None:
        ...

    @abc.abstractmethod
    def stop(self) -> None:
        ...

    def reset(self) -> None:
        self.stop()
        self.start()

    def set_delay(self, delay_s: float) -> None:
        """Update the delay used by the NEXT start(); a running
        countdown is unaffected. Transports whose timers support
        retuning override this -- it is how RTT-adaptive timeouts
        (geo.RttEstimator: heartbeat fail periods, election no-ping
        deadlines) retune without reconstructing timers."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support set_delay")


class Transport(abc.ABC):
    """Asynchronous, unordered, at-most-once message delivery between
    registered actors, plus timers -- all on one event loop."""

    # True for transports that run a real event-loop thread (TcpTransport):
    # actors may then offload blocking work to worker threads and post
    # results back with call_soon_threadsafe. SimTransport runs inline on
    # the caller's thread, so everything must stay synchronous.
    threaded: bool = False

    # paxtrace (obs/): an attached obs.Tracer makes the transport emit
    # receive/timer/drain spans and propagate trace contexts at the
    # frame layer; an attached obs.RuntimeMetrics feeds the
    # drain-granular runtime metrics (stage histograms, queue depth).
    # None (the default) keeps every hook to one attribute load + an
    # ``is None`` test -- the <3% tracing-off budget
    # (bench_results/trace_overhead.json).
    tracer = None
    runtime_metrics = None

    @abc.abstractmethod
    def register(self, address: Address, actor: "Actor") -> None:
        """Register ``actor`` to receive messages addressed to ``address``.
        At most one actor per address (Transport.scala:58-63)."""

    @abc.abstractmethod
    def send(self, src: Address, dst: Address, data: bytes) -> None:
        ...

    @abc.abstractmethod
    def send_no_flush(self, src: Address, dst: Address, data: bytes) -> None:
        """Queue without flushing; enables write batching
        (NettyTcpTransport.scala:455-495)."""

    @abc.abstractmethod
    def flush(self, src: Address, dst: Address) -> None:
        ...

    def send_batch(self, src: Address, dst: Address, datas) -> None:
        """Queue a drain's already-encoded messages to one destination
        and flush ONCE (paxwire): on TcpTransport the whole batch rides
        one writev and adjacent same-type payloads coalesce into batch
        frames; the default is the portable send_no_flush/flush
        spelling, so SimTransport and custom transports need no
        batching support."""
        for data in datas:
            self.send_no_flush(src, dst, data)
        self.flush(src, dst)

    @abc.abstractmethod
    def timer(self, address: Address, name: str, delay_s: float,
              f: Callable[[], None]) -> Timer:
        """Create a stopped timer on ``address``'s event loop firing ``f``
        after ``delay_s`` once started."""

    def stage(self) -> Any:
        """Optional hook: transports that batch device work override this."""
        return None

    def note_admission(self, address: Address, actor: "Actor") -> None:
        """paxload (serve/): a role that attaches an
        ``AdmissionController`` AFTER construction-time registration
        calls this so the transport can arm per-destination state (the
        sim's bounded inbox). Default: nothing -- TcpTransport reads
        ``actor.admission`` at delivery time."""
