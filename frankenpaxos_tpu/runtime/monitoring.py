"""Prometheus-shaped metrics facade with real and fake backends.

Reference behavior: monitoring/ (Collectors.scala:6-14, Counter.scala,
Gauge.scala, Summary.scala, PrometheusCollectors.scala:3-11,
FakeCollectors.scala:3-11). Protocol code builds metrics through the
facade and is identical in production (prometheus_client), tests, and
simulation (fakes).
"""

from __future__ import annotations

import abc
import dataclasses
import time
from typing import Sequence


class Counter(abc.ABC):
    @abc.abstractmethod
    def labels(self, *values: str) -> "Counter":
        ...

    @abc.abstractmethod
    def inc(self, amount: float = 1.0) -> None:
        ...

    @abc.abstractmethod
    def get(self) -> float:
        ...


class Gauge(abc.ABC):
    @abc.abstractmethod
    def labels(self, *values: str) -> "Gauge":
        ...

    @abc.abstractmethod
    def set(self, value: float) -> None:
        ...

    @abc.abstractmethod
    def inc(self, amount: float = 1.0) -> None:
        ...

    @abc.abstractmethod
    def dec(self, amount: float = 1.0) -> None:
        ...

    @abc.abstractmethod
    def get(self) -> float:
        ...


class Summary(abc.ABC):
    @abc.abstractmethod
    def labels(self, *values: str) -> "Summary":
        ...

    @abc.abstractmethod
    def observe(self, value: float) -> None:
        ...

    def time(self):
        """Context manager observing elapsed seconds (the ``timed`` handler
        pattern, multipaxos/Leader.scala:281-293)."""
        return _SummaryTimer(self)

    @abc.abstractmethod
    def get_count(self) -> float:
        ...

    @abc.abstractmethod
    def get_sum(self) -> float:
        ...


class _SummaryTimer:
    def __init__(self, summary: Summary):
        self.summary = summary

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.summary.observe(time.perf_counter() - self._t0)
        return False


class Histogram(abc.ABC):
    """A bucketed distribution (drain-stage latencies, WAL fsyncs):
    the Prometheus exposition carries ``_bucket{le=...}``/``_sum``/
    ``_count`` samples, which promdb keeps queryable by those suffixed
    names."""

    @abc.abstractmethod
    def labels(self, *values: str) -> "Histogram":
        ...

    @abc.abstractmethod
    def observe(self, value: float) -> None:
        ...

    @abc.abstractmethod
    def get_count(self) -> float:
        ...

    @abc.abstractmethod
    def get_sum(self) -> float:
        ...


#: Event-loop-scale latency buckets (seconds): the prometheus_client
#: defaults start at 5ms -- useless for µs drain stages; these cover
#: 1µs..1s, the span between a fused kernel pass and a stalled fsync.
LATENCY_BUCKETS = (
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0)


class Collectors(abc.ABC):
    """Metric builders (Collectors.scala:6-14)."""

    @abc.abstractmethod
    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        ...

    @abc.abstractmethod
    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        ...

    @abc.abstractmethod
    def summary(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Summary:
        ...

    @abc.abstractmethod
    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = LATENCY_BUCKETS
                  ) -> Histogram:
        ...


# --- Fake backend (FakeCollectors.scala) ----------------------------------


@dataclasses.dataclass
class _FakeChild:
    value: float = 0.0
    count: float = 0.0


class FakeCounter(Counter):
    def __init__(self):
        self._children: dict[tuple, _FakeChild] = {}
        self._root = _FakeChild()

    def labels(self, *values: str) -> "FakeCounter":
        child = FakeCounter()
        child._root = self._children.setdefault(values, _FakeChild())
        return child

    def inc(self, amount: float = 1.0) -> None:
        self._root.value += amount

    def get(self) -> float:
        return self._root.value


class FakeGauge(Gauge):
    def __init__(self):
        self._children: dict[tuple, _FakeChild] = {}
        self._root = _FakeChild()

    def labels(self, *values: str) -> "FakeGauge":
        child = FakeGauge()
        child._root = self._children.setdefault(values, _FakeChild())
        return child

    def set(self, value: float) -> None:
        self._root.value = value

    def inc(self, amount: float = 1.0) -> None:
        self._root.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self._root.value -= amount

    def get(self) -> float:
        return self._root.value


class FakeSummary(Summary):
    def __init__(self):
        self._children: dict[tuple, _FakeChild] = {}
        self._root = _FakeChild()

    def labels(self, *values: str) -> "FakeSummary":
        child = FakeSummary()
        child._root = self._children.setdefault(values, _FakeChild())
        return child

    def observe(self, value: float) -> None:
        self._root.value += value
        self._root.count += 1

    def get_count(self) -> float:
        return self._root.count

    def get_sum(self) -> float:
        return self._root.value


class FakeHistogram(Histogram):
    def __init__(self, buckets: Sequence[float] = LATENCY_BUCKETS):
        self.buckets = tuple(buckets)
        self._children: dict[tuple, "FakeHistogram"] = {}
        self._root = _FakeChild()
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf last

    def labels(self, *values: str) -> "FakeHistogram":
        # Label aliasing contract (same as the other fakes): repeated
        # labels() calls with equal values share ONE child's state.
        child = self._children.get(values)
        if child is None:
            child = FakeHistogram(self.buckets)
            self._children[values] = child
        return child

    def observe(self, value: float) -> None:
        self._root.value += value
        self._root.count += 1
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                self.bucket_counts[i] += 1
                return
        self.bucket_counts[-1] += 1

    def get_count(self) -> float:
        return self._root.count

    def get_sum(self) -> float:
        return self._root.value


class FakeCollectors(Collectors):
    def __init__(self):
        self.metrics: dict[str, object] = {}

    def counter(self, name, help="", labels=()):
        return self.metrics.setdefault(name, FakeCounter())

    def gauge(self, name, help="", labels=()):
        return self.metrics.setdefault(name, FakeGauge())

    def summary(self, name, help="", labels=()):
        return self.metrics.setdefault(name, FakeSummary())

    def histogram(self, name, help="", labels=(),
                  buckets=LATENCY_BUCKETS):
        return self.metrics.setdefault(name, FakeHistogram(buckets))


# --- Prometheus backend (PrometheusCollectors.scala) -----------------------


class PrometheusCollectors(Collectors):
    """Thin adapter over prometheus_client; import is deferred so sim/test
    environments never need it."""

    def __init__(self, registry=None):
        import prometheus_client  # noqa: deferred import

        self._pc = prometheus_client
        self._registry = registry or prometheus_client.REGISTRY
        self._cache: dict[str, object] = {}

    def _make(self, cls, name, help, labels):
        if name not in self._cache:
            self._cache[name] = cls(name, help or name, list(labels),
                                    registry=self._registry)
        return self._cache[name]

    def counter(self, name, help="", labels=()):
        return _PromCounter(self._make(self._pc.Counter, name, help, labels))

    def gauge(self, name, help="", labels=()):
        return _PromGauge(self._make(self._pc.Gauge, name, help, labels))

    def summary(self, name, help="", labels=()):
        return _PromSummary(self._make(self._pc.Summary, name, help, labels))

    def histogram(self, name, help="", labels=(),
                  buckets=LATENCY_BUCKETS):
        if name not in self._cache:
            self._cache[name] = self._pc.Histogram(
                name, help or name, list(labels),
                buckets=list(buckets), registry=self._registry)
        return _PromHistogram(self._cache[name])


class _PromCounter(Counter):
    def __init__(self, metric):
        self._m = metric

    def labels(self, *values):
        return _PromCounter(self._m.labels(*values))

    def inc(self, amount: float = 1.0) -> None:
        self._m.inc(amount)

    def get(self) -> float:
        return self._m._value.get()


class _PromGauge(Gauge):
    def __init__(self, metric):
        self._m = metric

    def labels(self, *values):
        return _PromGauge(self._m.labels(*values))

    def set(self, value: float) -> None:
        self._m.set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._m.inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._m.dec(amount)

    def get(self) -> float:
        return self._m._value.get()


class _PromSummary(Summary):
    def __init__(self, metric):
        self._m = metric

    def labels(self, *values):
        return _PromSummary(self._m.labels(*values))

    def observe(self, value: float) -> None:
        self._m.observe(value)

    def get_count(self) -> float:
        return self._m._count.get()

    def get_sum(self) -> float:
        return self._m._sum.get()


class _PromHistogram(Histogram):
    def __init__(self, metric):
        self._m = metric

    def labels(self, *values):
        return _PromHistogram(self._m.labels(*values))

    def observe(self, value: float) -> None:
        self._m.observe(value)

    def get_count(self) -> float:
        return sum(b.get() for b in self._m._buckets) \
            if hasattr(self._m, "_buckets") else 0.0

    def get_sum(self) -> float:
        return self._m._sum.get()


def instrument_actor(actor, collectors: Collectors, protocol: str,
                     role: str) -> bool:
    """Wrap ``actor.receive`` with the standard inbound metrics every
    reference role exports (``<proto>_<role>_requests_total{type=...}``
    and ``..._requests_latency_seconds``; e.g. Leader.scala:281-293):
    uniform observability for roles that don't hand-register their own
    collectors. Roles that DO (multipaxos) are left untouched; returns
    False in that case.
    """
    prefix = f"{protocol}_{role}"
    # Memoized per collectors instance so colocated roles of the same
    # kind (supernode mode) share one metric family. A role that
    # hand-registered its own request metrics at construction (all
    # multipaxos roles) must NOT be wrapped on top -- that would double
    # every count -- and PrometheusCollectors returns cached metrics
    # rather than raising on re-registration, so detect prior
    # registration via its name cache explicitly.
    cache = getattr(collectors, "_instrument_cache", None)
    if cache is None:
        cache = {}
        collectors._instrument_cache = cache
    if prefix not in cache:
        already = getattr(collectors, "_cache", {})
        if (f"{prefix}_requests_total" in already
                or f"{prefix}_requests_latency_seconds" in already):
            cache[prefix] = None  # the role registers its own metrics
        else:
            cache[prefix] = (
                collectors.counter(
                    f"{prefix}_requests_total",
                    help=f"Total {role} inbound messages",
                    labels=("type",)),
                collectors.summary(
                    f"{prefix}_requests_latency_seconds",
                    help=f"{role} handler latency", labels=("type",)))
    if cache[prefix] is None:
        return False
    requests, latency = cache[prefix]

    original = actor.receive

    def receive(src, message):
        name = type(message).__name__
        with latency.labels(name).time():
            original(src, message)
        requests.labels(name).inc()

    actor.receive = receive
    return True
