"""SimTransport: the deterministic in-memory transport for testing.

Reference behavior: FakeTransport.scala:64-230. Messages accumulate in a
buffer instead of being delivered; tests (and the property-based
simulator, sim/) explicitly deliver any buffered message or trigger any
running timer, in any order. That explores reordering, duplication (via
protocol resends), and loss (never delivering). Everything executes
inline on the caller's thread (FakeTransport.scala:127-140), keeping runs
perfectly deterministic for a given command sequence.

Also supports actor partitioning (JsTransport.scala:77): messages to or
from a partitioned actor are dropped at delivery time.

paxsim (docs/SIMULATION.md): the NON-adversarial delivery paths --
``deliver_all``/``deliver_all_coalesced`` here and the geo transport's
virtual-clock event loop -- share one wave engine, ``_run_wave``: the
batch of frames consumed in one step is spliced out of the buffer as a
unit (never ``list.remove`` per message), drop decisions evaluate as a
vectorized mask over the wave's SoA columns (ops/simwave.py) above
``WAVE_VECTOR_MIN``, and consecutive same-destination frames deliver
through ``Actor.receive_batch`` when the actor opts in. The
adversarial API (``deliver_message`` of ANY buffered frame,
``generate_command``, partition/crash controls) is unchanged, and the
engine steps aside -- falling back to the per-message compat loop --
whenever delivery is intercepted (viz instance wraps, the overhead
benches' class patches, runtime/sim_legacy.py).
"""

from __future__ import annotations

from collections import deque
import dataclasses
import itertools
from typing import Callable, Optional, Union

import numpy as np

from frankenpaxos_tpu.ops import simwave
from frankenpaxos_tpu.runtime.actor import Actor
from frankenpaxos_tpu.runtime.logger import Logger, PrintLogger
from frankenpaxos_tpu.runtime.transport import Address, Timer, Transport


@dataclasses.dataclass(frozen=True)
class SimMessage:
    id: int
    src: Address
    dst: Address
    data: bytes
    # paxtrace: the frame-layer trace context (obs.TraceContext) --
    # the sim analog of the TCP frame header's ``|ctx`` suffix. None
    # whenever no tracer is attached or no context was active at send.
    trace: object = None


class SimTimer(Timer):
    """A timer that only fires when the test triggers it
    (FakeTransport.scala:9-62)."""

    def __init__(self, transport: "SimTransport", timer_id: int,
                 address: Address, name: str, delay_s: float,
                 f: Callable[[], None]):
        self._transport = transport
        self._id = timer_id
        self.address = address
        self._name = name
        self.delay_s = delay_s
        self._f = f
        self.running = False
        # Bumped on every start(): reused timer objects (clients keep
        # one resend timer per pseudonym) need restarts distinguishable
        # from still-running, or a virtual-time pump keeps the OLD
        # operation's deadline for the new one (serve/loadgen.py).
        self.starts = 0

    @property
    def name(self) -> str:
        return self._name

    @property
    def id(self) -> int:
        return self._id

    def start(self) -> None:
        self.running = True
        self.starts += 1
        # The transport's registry holds RUNNING timers only: clients
        # create a fresh timer per resend/backoff, so registering for
        # the timer object's lifetime would leak the dict (and the
        # per-tick running_timers() scan) without bound under
        # sustained load (serve/loadgen.py pumps millions).
        self._transport.timers[self._id] = self

    def stop(self) -> None:
        self.running = False
        self._transport.timers.pop(self._id, None)

    def set_delay(self, delay_s: float) -> None:
        self.delay_s = delay_s

    def run(self) -> None:
        """Fire the timer (one-shot: stops first, like
        FakeTransport.scala:40-46)."""
        if self.running:
            self.stop()
            self._f()


# Commands the simulator replays against a SimTransport (the bridge to
# property-based testing, FakeTransport.scala:196-230).
@dataclasses.dataclass(frozen=True)
class DeliverMessage:
    message: SimMessage


@dataclasses.dataclass(frozen=True)
class TriggerTimer:
    address: Address
    name: str
    timer_id: int


SimCommand = Union[DeliverMessage, TriggerTimer]


class SimTransport(Transport):
    """Addresses are arbitrary hashables (conventionally strings)."""

    def __init__(self, logger: Optional[Logger] = None):
        self.logger = logger or PrintLogger()
        self.actors: dict[Address, Actor] = {}
        self.messages: list[SimMessage] = []
        self.timers: dict[int, SimTimer] = {}
        self.partitioned: set[Address] = set()
        self.history: list[SimCommand] = []
        self._ids = itertools.count()
        # paxload (serve/): destinations with a bounded client-lane
        # inbox -- address -> that actor's AdmissionController -- the
        # per-destination count of buffered client-lane frames, and
        # those frames themselves in arrival order (so drop-oldest is
        # an O(capacity) deque pop, not a frame_lane scan of the whole
        # buffer, which goes quadratic exactly when shedding must be
        # cheap). All three dicts stay empty unless a registered actor
        # carries an admission controller with an inbox capacity, so
        # the admission-off hot path pays one falsy-dict test per send.
        self._inbox_policies: dict[Address, object] = {}
        self._inbox_depth: dict[Address, int] = {}
        self._client_inbox: dict[Address, deque] = {}
        # paxsim wave engine state. ``_consumed`` tombstones message
        # ids the geo wave path has delivered but not yet compacted out
        # of ``messages`` (the public buffer list stays a plain list
        # for the adversarial API; splicing it per delivery is the
        # legacy quadratic the wave engine exists to kill). Non-empty
        # ONLY inside a wave loop -- every public entry point compacts
        # first. ``_addr_ids`` interns addresses to ints for the
        # vectorized drop masks (ops/simwave.py).
        self._consumed: set[int] = set()
        self._addr_ids: dict[Address, int] = {}
        # Frames shed by drop-oldest while they sat in an in-flight
        # wave (already spliced from ``messages``): the wave engine
        # must not deliver them -- legacy delivery would have found
        # them unbuffered. Only ever populated when an admission
        # policy is armed.
        self._wave_shed: set[int] = set()
        #: Record delivered/triggered events into ``history``. The
        #: default matches the reference; schedule-scale harnesses
        #: (bench/sim_core_ab.py million-event runs) disable it --
        #: history is an append-only list of per-event dataclasses,
        #: which at 1M+ events is hundreds of MB of bookkeeping no
        #: oracle reads.
        self.record_history: bool = True

    # --- Transport API ----------------------------------------------------
    def register(self, address: Address, actor: Actor) -> None:
        if address in self.actors:
            raise ValueError(f"an actor is already registered at {address}")
        self.actors[address] = actor
        if actor.admission is not None:
            self.note_admission(address, actor)

    def note_admission(self, address: Address, actor: Actor) -> None:
        """Arm the bounded client-lane inbox for ``address``. Called
        from register() when the controller predates registration, and
        by roles that attach one AFTER ``Actor.__init__`` registered
        them (the usual order: options are parsed in the subclass
        constructor)."""
        admission = actor.admission
        if admission is not None and admission.options.inbox_capacity:
            from frankenpaxos_tpu.serve.lanes import LANE_CLIENT, frame_lane

            if self._consumed:
                self._compact_messages()
            self._inbox_policies[address] = admission
            # Recompute rather than trust stale state: a crash ->
            # restart leaves the dead incarnation's frames buffered
            # (the network does not know about the crash) and they
            # deliver to whatever re-registers here.
            self._client_inbox[address] = deque(
                m for m in self.messages
                if m.dst == address and frame_lane(m.data) == LANE_CLIENT)
            self._inbox_depth[address] = len(self._client_inbox[address])

    def send(self, src: Address, dst: Address, data: bytes) -> None:
        tracked = False
        if self._inbox_policies:
            verdict = self._admit_to_inbox(src, dst, data)
            if not verdict:
                return
            tracked = verdict == "track"
        tracer = self.tracer
        trace = tracer.current if tracer is not None else None
        message = SimMessage(next(self._ids), src, dst, data, trace)
        self.messages.append(message)
        if tracked:
            self._client_inbox.setdefault(dst, deque()).append(message)

    def _admit_to_inbox(self, src: Address, dst: Address,
                        data: bytes) -> Optional[str]:
        """Bounded-inbox enforcement for ``dst`` (serve/admission.py).
        Only CLIENT-lane frames count against (or are ever shed from)
        the bound; control-plane frames always buffer. Returns None
        when the frame must NOT be buffered (reject-newest) -- the
        ONLY falsy verdict, chaos tests hook this to assert control
        frames are never refused -- "buffer" for frames outside the
        bound, or "track" for client-lane frames counted against it
        (mirrored in ``_client_inbox``)."""
        admission = self._inbox_policies.get(dst)
        if admission is None:
            return "buffer"
        from frankenpaxos_tpu.serve.lanes import LANE_CLIENT, frame_lane

        if frame_lane(data) != LANE_CLIENT:
            return "buffer"
        depth = self._inbox_depth.get(dst, 0)
        if admission.inbox_full(depth):
            if admission.options.inbox_policy == "drop":
                # Drop-oldest: shed the longest-waiting client frame
                # (it has aged the most; the newest arrival has the
                # best chance of completing inside its deadline).
                # _client_inbox mirrors the buffered client-lane
                # frames in arrival order, so this is O(capacity).
                pending = self._client_inbox.get(dst)
                while pending:
                    oldest = pending.popleft()
                    if self._remove_buffered(oldest):
                        break
                    # Not buffered: the frame either sits in an
                    # in-flight wave (spliced out ahead of delivery --
                    # mark it shed so the wave engine skips it, else a
                    # frame the admission controller counted as
                    # dropped would still reach its handler; ids are
                    # never reused, so a stale mark is inert) or was
                    # removed out-of-band (live.py drop; same marking,
                    # same inertness).
                    self._wave_shed.add(oldest.id)
                    break
                admission.note_shed("drop-oldest")
                depth -= 1
            else:
                # Reject-newest: never buffered, and the client hears
                # about it NOW -- synthesize the Rejected wire replies
                # (extended tag page) from the would-be receiver.
                admission.note_shed("reject-newest")
                self._send_reject_replies(dst, data)
                return None
        self._inbox_depth[dst] = depth + 1
        admission.note_inbox_depth(depth + 1)
        return "track"

    def _send_reject_replies(self, dst: Address, data: bytes) -> None:
        from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER
        from frankenpaxos_tpu.serve.admission import reject_replies_for
        from frankenpaxos_tpu.serve.messages import REASON_QUEUE

        admission = self._inbox_policies[dst]
        try:
            message = DEFAULT_SERIALIZER.from_bytes(data)
        except ValueError:
            return  # corrupt frame: nothing to reject, just shed
        for client, reply in reject_replies_for(
                message, admission.retry_after_ms(), REASON_QUEUE):
            self.messages.append(SimMessage(
                next(self._ids), dst, client,
                DEFAULT_SERIALIZER.to_bytes(reply), None))

    def send_no_flush(self, src: Address, dst: Address, data: bytes) -> None:
        self.send(src, dst, data)

    def flush(self, src: Address, dst: Address) -> None:
        pass

    def timer(self, address: Address, name: str, delay_s: float,
              f: Callable[[], None]) -> SimTimer:
        # Registration happens in SimTimer.start(): self.timers holds
        # running timers only (see SimTimer.start).
        return SimTimer(self, next(self._ids), address, name, delay_s, f)

    # --- test / simulator API (FakeTransport.scala:142-230) ---------------
    def running_timers(self) -> list[SimTimer]:
        return [t for t in self.timers.values() if t.running]

    def deliver_message(self, message: SimMessage) -> None:
        """Remove ``message`` from the buffer and run the destination's
        ``receive`` inline. Unknown/partitioned destinations drop."""
        actor = self._deliver(message)
        if actor is not None:
            self._drain(actor)

    def _drain(self, actor: Actor) -> None:
        tracer = self.tracer
        if tracer is None:
            actor.on_drain()
            return
        with tracer.drain_span(str(actor.address)):
            actor.on_drain()

    # --- the paxsim buffer bookkeeping ------------------------------------
    def _remove_buffered(self, message: SimMessage) -> bool:
        """Consume ``message`` from the buffer: scan by id (an integer
        compare per probe, where the legacy ``list.remove`` paid a
        field-tuple ``__eq__`` per probe -- 70%+ of the geo event
        loop), then verify FULL equality on the hit. The equality
        check is load-bearing: minimization replays
        (sim/simulator.py) deliver messages recorded from a DIFFERENT
        execution, and a same-id frame with different bytes must read
        as "no longer applies" exactly like the legacy
        remove-by-equality did. Ids are unique in the buffer, so one
        probe decides."""
        if self._consumed:
            self._compact_messages()
        mid = message.id
        messages = self.messages
        for i, m in enumerate(messages):
            if m.id == mid:
                if m == message:
                    del messages[i]
                    return True
                return False
        return False

    def _compact_messages(self) -> None:
        """Apply pending wave tombstones to the public buffer list."""
        if self._consumed:
            consumed = self._consumed
            self.messages[:] = [m for m in self.messages
                                if m.id not in consumed]
            consumed.clear()

    def _consume_buffered(self, wave) -> None:
        """Tombstone a delivered wave; compact once the dead fraction
        dominates (amortized O(1) per message -- each compaction
        removes at least half the list)."""
        consumed = self._consumed
        for message in wave:
            consumed.add(message.id)
        if (len(consumed) > 1024
                and 2 * len(consumed) >= len(self.messages)):
            self._compact_messages()

    def _deliver(self, message: SimMessage) -> Optional[Actor]:
        """Deliver without draining; returns the receiving actor (None if
        the message was dropped) so callers control drain granularity."""
        if not self._remove_buffered(message):
            self.logger.warn(f"delivering unbuffered message {message}")
            return None
        if self._inbox_policies and message.dst in self._inbox_policies:
            from frankenpaxos_tpu.serve.lanes import LANE_CLIENT, frame_lane

            if frame_lane(message.data) == LANE_CLIENT:
                self._inbox_depth[message.dst] = max(
                    0, self._inbox_depth.get(message.dst, 0) - 1)
                pending = self._client_inbox.get(message.dst)
                if pending:
                    # Usually the leftmost (FIFO delivery); adversarial
                    # sims deliver out of order, but the deque is
                    # capacity-bounded so remove() stays O(capacity).
                    try:
                        pending.remove(message)
                    except ValueError:
                        pass
        if (message.dst in self.partitioned
                or message.src in self.partitioned):
            # Dropped at the partition: not part of the delivered history
            # (the trace viewer renders history entries as deliveries).
            return None
        if self.record_history:
            self.history.append(DeliverMessage(message))
        actor = self.actors.get(message.dst)
        if actor is None:
            self.logger.warn(f"no actor registered at {message.dst}")
            return None
        tracer = self.tracer
        if tracer is None:
            actor.receive(message.src,
                          actor.serializer.from_bytes(message.data))
            return actor
        # Traced delivery: decode and handler run as drain-stage
        # sub-spans of the per-message receive span, which is parented
        # by the frame's propagated context (message.trace).
        span = tracer.receive_span(str(message.dst), "?", message.trace)
        with span:
            with tracer.stage("decode"):
                decoded = actor.serializer.from_bytes(message.data)
            span.name = (f"receive:{type(decoded).__name__}"
                         f"@{message.dst}")
            with tracer.stage("handler"):
                actor.receive(message.src, decoded)
        return actor

    def trigger_timer(self, timer_id: int) -> None:
        timer = self.timers.get(timer_id)
        if timer is None or not timer.running:
            return
        if timer.address in self.partitioned:
            timer.stop()
            return
        if self.record_history:
            self.history.append(
                TriggerTimer(timer.address, timer.name, timer_id))
        tracer = self.tracer
        if tracer is None:
            timer.run()
            return
        with tracer.timer_span(str(timer.address), timer.name):
            timer.run()

    def run_command(self, command: SimCommand) -> None:
        if isinstance(command, DeliverMessage):
            self.deliver_message(command.message)
        else:
            self.trigger_timer(command.timer_id)

    def possible_commands(self) -> list[SimCommand]:
        """Everything that could happen next (FakeTransport.scala:196-220)."""
        if self._consumed:
            self._compact_messages()
        commands: list[SimCommand] = [DeliverMessage(m)
                                      for m in self.messages]
        commands.extend(TriggerTimer(t.address, t.name, t.id)
                        for t in self.running_timers())
        return commands

    def generate_command(self, rng) -> Optional[SimCommand]:
        """Pick a random next step, weighting deliveries vs. timers by
        availability (the spirit of FakeTransport.generateCommand)."""
        if self._consumed:
            self._compact_messages()
        n_msgs = len(self.messages)
        running = self.running_timers()
        total = n_msgs + len(running)
        if total == 0:
            return None
        i = rng.randrange(total)
        if i < n_msgs:
            return DeliverMessage(self.messages[i])
        return TriggerTimer(running[i - n_msgs].address,
                            running[i - n_msgs].name,
                            running[i - n_msgs].id)

    def deliver_all(self, max_steps: int = 100000) -> int:
        """FIFO-deliver until no messages remain (no timers), draining
        after EVERY message. Convenience for non-adversarial
        integration tests."""
        return self._deliver_fifo(max_steps, coalesce=False)

    def deliver_all_coalesced(self, max_steps: int = 100000) -> int:
        """FIFO-deliver in WAVES, draining each touched actor once per
        wave -- the delivery semantics of the real event loop
        (TcpTransport defers ``on_drain`` to the end of a loop pass, so
        a burst of frames lands in one drain). A wave is the set of
        messages buffered when it starts; sends made during the wave
        join the next one. This is the right mode for benchmarking
        batch-amortized actors over SimTransport; adversarial sims keep
        per-message drains (``deliver_message``)."""
        return self._deliver_fifo(max_steps, coalesce=True)

    # --- the paxsim wave engine -------------------------------------------
    def _wave_fast_path_ok(self) -> bool:
        """Whether the wave engine may splice the buffer and dispatch
        waves directly. False when delivery is intercepted -- a viz
        recorder wrapped this instance's ``deliver_message``, or an
        overhead bench / sim_legacy pinned a different ``_deliver`` on
        the class -- so every delivered frame still flows through the
        interceptor via the per-message compat loop."""
        return ("deliver_message" not in self.__dict__
                and type(self)._deliver in WAVE_SAFE_DELIVERS)

    def _deliver_fifo(self, max_steps: int, coalesce: bool) -> int:
        """The ONE parameterized FIFO drain loop (both public modes
        differ only in drain granularity). Waves are buffer-prefix
        snapshots: sends made by handlers append behind the snapshot
        and join the next wave, which reproduces the legacy loops'
        strict send-order delivery exactly."""
        if not self._wave_fast_path_ok():
            return self._deliver_fifo_compat(max_steps, coalesce)
        steps = 0
        messages = self.messages
        while messages and steps < max_steps:
            wave = messages[:max_steps - steps]
            del messages[:len(wave)]
            self._drop_schedule_stamps(wave)
            steps += len(wave)
            self._run_wave(wave, coalesce)
        return steps

    def _drop_schedule_stamps(self, wave) -> None:
        """Scheduler-policy hook: consume any per-frame scheduling
        state for frames leaving the buffer outside the policy's own
        loop (the geo transport pops arrival stamps here, so a FIFO
        drain can never leave a stale stamp for ``run_until`` to
        double-deliver)."""

    def _deliver_fifo_compat(self, max_steps: int, coalesce: bool) -> int:
        """Per-message fallback: identical delivery order and drain
        granularity, routed through ``deliver_message``/``_deliver`` so
        interceptors observe every step."""
        steps = 0
        while self.messages and steps < max_steps:
            if not coalesce:
                self.deliver_message(self.messages[0])
                steps += 1
                continue
            wave = list(self.messages[:max_steps - steps])
            touched: list[Actor] = []
            seen: set[int] = set()
            for message in wave:
                actor = self._deliver(message)
                steps += 1
                if actor is not None and id(actor) not in seen:
                    seen.add(id(actor))
                    touched.append(actor)
            for actor in touched:
                self._drain(actor)
        return steps

    def _wave_keep_mask(self, wave) -> Optional[np.ndarray]:
        """Vectorized drop mask over a wave (True = deliver), or None
        to decide per message via ``_per_message_check`` -- small waves
        skip array staging entirely (ops/simwave.WAVE_VECTOR_MIN)."""
        if not self.partitioned or len(wave) < simwave.WAVE_VECTOR_MIN:
            return None
        n = len(wave)
        intern = self._intern
        src = np.fromiter((intern(m.src) for m in wave), np.int64, n)
        dst = np.fromiter((intern(m.dst) for m in wave), np.int64, n)
        blocked = np.fromiter((intern(a) for a in self.partitioned),
                              np.int64, len(self.partitioned))
        return simwave.keep_mask(src, dst, blocked)

    def _per_message_check(self) -> Optional[Callable]:
        """Scalar drop check used when ``_wave_keep_mask`` returned
        None; None means nothing can drop (no partitions)."""
        part = self.partitioned
        if not part:
            return None
        return lambda m: m.src not in part and m.dst not in part

    def _intern(self, address) -> int:
        ids = self._addr_ids
        aid = ids.get(address)
        if aid is None:
            aid = ids[address] = len(ids)
        return aid

    def _run_wave(self, wave, coalesce: bool) -> int:
        """Deliver one wave. PRECONDITION: the wave's frames are
        already consumed from the buffer (prefix splice or tombstones).
        Returns the number of frames that reached an actor.

        Delivery order is exactly per-message FIFO; the only batching
        is that consecutive frames to one destination hand off through
        ``Actor.receive_batch`` when (a) drains are coalesced and (b)
        the actor OVERRIDES it -- the default body replays decode +
        ``receive`` in order, so grouping is order-equivalent by
        construction."""
        keep = self._wave_keep_mask(wave)
        check = self._per_message_check() if keep is None else None
        actors = self.actors
        record = self.record_history
        history = self.history
        tracer = self.tracer
        inbox = bool(self._inbox_policies)
        shed = self._wave_shed if inbox or self._wave_shed else None
        touched: dict[int, Actor] = {}
        delivered = 0
        n = len(wave)
        i = 0
        while i < n:
            message = wave[i]
            if shed and message.id in shed:
                # Drop-oldest shed this frame out of the in-flight wave
                # (a handler's send overflowed the bounded inbox
                # mid-wave); legacy delivery would have found it
                # unbuffered and skipped it -- before any inbox
                # accounting.
                shed.discard(message.id)
                i += 1
                continue
            if inbox:
                # BEFORE the drop mask: legacy _deliver decrements the
                # bounded-inbox depth even for frames a partition then
                # drops (the frame left the buffer either way). Geo
                # link drops differ in legacy (no admission is ever
                # armed on geo harnesses), so the wave engine applies
                # the plain-transport rule uniformly.
                self._note_wave_delivery(message)
            if (keep is not None and not keep[i]) or \
                    (check is not None and not check(message)):
                # Dropped at a partition (or, in the geo subclass, a
                # downed link): consumed, no history entry, no drain.
                i += 1
                continue
            dst = message.dst
            actor = actors.get(dst)
            if record:
                history.append(DeliverMessage(message))
            if actor is None:
                self.logger.warn(f"no actor registered at {dst}")
                i += 1
                continue
            if tracer is not None:
                self._traced_receive(actor, message)
                delivered += 1
                i += 1
            elif (coalesce and type(actor).receive_batch
                    is not Actor.receive_batch):
                j = i + 1
                while (j < n and wave[j].dst == dst
                       and (keep[j] if keep is not None
                            else check is None or check(wave[j]))
                       and not (shed and wave[j].id in shed)):
                    j += 1
                run = wave[i:j]
                for m in run[1:]:
                    if inbox:
                        self._note_wave_delivery(m)
                    if record:
                        history.append(DeliverMessage(m))
                actor.receive_batch([(m.src, m.data) for m in run])
                delivered += j - i
                i = j
            else:
                actor.receive(message.src,
                              actor.serializer.from_bytes(message.data))
                delivered += 1
                i += 1
            if coalesce:
                if id(actor) not in touched:
                    touched[id(actor)] = actor
            else:
                self._drain(actor)
        if coalesce:
            for actor in touched.values():
                self._drain(actor)
        return delivered

    def _traced_receive(self, actor: Actor, message: SimMessage) -> None:
        """Per-message traced delivery (paxtrace): the wave engine
        never groups under a tracer, so span structure matches the
        per-message path byte for byte."""
        tracer = self.tracer
        span = tracer.receive_span(str(message.dst), "?", message.trace)
        with span:
            with tracer.stage("decode"):
                decoded = actor.serializer.from_bytes(message.data)
            span.name = (f"receive:{type(decoded).__name__}"
                         f"@{message.dst}")
            with tracer.stage("handler"):
                actor.receive(message.src, decoded)

    def _note_wave_delivery(self, message: SimMessage) -> None:
        """Bounded-inbox accounting for one delivered frame (the wave
        twin of the block in ``_deliver``)."""
        if message.dst not in self._inbox_policies:
            return
        from frankenpaxos_tpu.serve.lanes import LANE_CLIENT, frame_lane

        if frame_lane(message.data) != LANE_CLIENT:
            return
        self._inbox_depth[message.dst] = max(
            0, self._inbox_depth.get(message.dst, 0) - 1)
        pending = self._client_inbox.get(message.dst)
        if pending:
            if pending[0] is message:
                pending.popleft()
            else:
                try:
                    pending.remove(message)
                except ValueError:
                    pass

    def partition(self, address: Address) -> None:
        self.partitioned.add(address)

    def heal(self, address: Address) -> None:
        self.partitioned.discard(address)

    def crash(self, address: Address) -> None:
        """Process crash (``kill -9``) for the actor at ``address``:
        deregister it and destroy its timers -- every piece of volatile
        state dies with the object, including anything it staged for a
        group commit that never happened. In-flight messages to the
        address stay buffered (the network does not know about the
        crash): they deliver to whatever re-registers there -- the
        restarted actor, whose durable state must make that safe -- or
        drop as 'no actor registered' if nothing does. The restart is
        the harness's job: construct a fresh actor at the same address
        over the surviving WAL storage."""
        if self.tracer is not None:
            self.tracer.event(f"crash {address}")
        self.actors.pop(address, None)
        # The bounded-inbox policy dies with its controller; the
        # restarted actor's register() re-attaches (and recomputes the
        # buffered depth) if it carries admission again.
        self._inbox_policies.pop(address, None)
        self._inbox_depth.pop(address, None)
        self._client_inbox.pop(address, None)
        for timer_id in [tid for tid, t in self.timers.items()
                         if t.address == address]:
            del self.timers[timer_id]


#: ``_deliver`` implementations the wave engine is allowed to bypass:
#: the base transport's, plus wave-aware subclasses that register here
#: (geo/transport.py). Any OTHER ``_deliver`` on the class -- an
#: overhead bench's no-hooks patch, sim_legacy's frozen bodies --
#: disables the fast path so per-message interception keeps working.
WAVE_SAFE_DELIVERS: set = {SimTransport._deliver}
