"""Actor and Chan.

Reference behavior: Actor.scala:7-51 (address/transport/logger; declares
InboundMessage + serializer + receive; registers itself at construction;
chan/send/sendNoFlush/flush helpers; timer factory) and Chan.scala:3-17
(typed channel serializing the *destination's* inbound type).
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Generic, TypeVar

from frankenpaxos_tpu.obs.trace import stage_scope
from frankenpaxos_tpu.runtime.logger import Logger
from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER, Serializer
from frankenpaxos_tpu.runtime.transport import Address, Timer, Transport

M = TypeVar("M")


class Chan(Generic[M]):
    """A typed channel from a source actor to a destination address
    (Chan.scala:3-17)."""

    def __init__(self, transport: Transport, src: Address, dst: Address,
                 serializer: Serializer[M]):
        self.transport = transport
        self.src = src
        self.dst = dst
        self.serializer = serializer

    def send(self, message: M) -> None:
        self.transport.send(self.src, self.dst,
                            self.serializer.to_bytes(message))

    def send_no_flush(self, message: M) -> None:
        self.transport.send_no_flush(self.src, self.dst,
                                     self.serializer.to_bytes(message))

    def flush(self) -> None:
        self.transport.flush(self.src, self.dst)

    def send_batch(self, messages) -> None:
        """A drain's worth of messages in one transport batch: encoded
        per message (the codecs are per-type) but flushed ONCE --
        paxwire turns adjacent same-type payloads into one batch frame
        and the whole call into one writev."""
        self.transport.send_batch(
            self.src, self.dst,
            [self.serializer.to_bytes(m) for m in messages])


class Actor(abc.ABC):
    """A single-threaded protocol role.

    Subclasses set ``serializer`` (for their own inbound messages) and
    implement ``receive``. Like the reference (Actor.scala:19-20), an
    actor registers with its transport at construction.
    """

    # The hybrid default encodes registered hot message types with
    # their fixed-layout binary codecs and pickles the long tail; a
    # subclass can still pin its own serializer.
    serializer: Serializer = DEFAULT_SERIALIZER

    # paxload (serve/): an attached serve.AdmissionController makes the
    # transports enforce this actor's bounded client-lane inbox and
    # CoDel drain-delay shedding, and lets the role's own handlers
    # admit/reject client commands. None (the default) keeps every
    # hook to one attribute load + an ``is None`` test -- the <3%
    # disabled-path budget (bench_results/overload_lt.json).
    admission = None

    # paxingest (ingest/): the zero-object wire-sink fast path. None
    # (the default) keeps delivery untouched. An opted-in actor sets a
    # ``{leading wire tag: (parser, handler)}`` mapping: when a frame's
    # payload leads with a mapped tag, TcpTransport calls
    # ``parser(payload)`` under its corrupt-frame guard (ValueError =
    # torn/corrupt, log-and-drop; None = unsupported shape, fall back
    # to ordinary per-message decode+deliver) and, on success, hands
    # the parsed descriptor to ``handler(src, parsed)`` with normal
    # handler semantics -- no per-message objects in between. The
    # parsed object must expose ``count`` (messages represented) for
    # drain bookkeeping. Sinks are bypassed whenever a tracer is
    # attached (per-message span semantics win) -- and role-level
    # admission is the SINK's job: the transport's client-lane inbox
    # shed does not see sink frames.
    wire_sinks = None

    def __init__(self, address: Address, transport: Transport,
                 logger: Logger):
        self.address = address
        self.transport = transport
        self.logger = logger
        transport.register(address, self)

    @abc.abstractmethod
    def receive(self, src: Address, message: Any) -> None:
        ...

    def receive_batch(self, batch: list) -> None:
        """paxsim: a consecutive same-destination run of one delivery
        wave, as raw ``(src, frame_bytes)`` pairs in arrival order.
        This default decodes and feeds ``receive`` one frame at a time
        -- bit-identical to per-message delivery, which is why the sim
        wave engine may group through it. SoA-native actors (bench
        sinks, loadgen-style drivers) override it to consume the run
        as arrays with no per-message Python; the engine only routes
        through an OVERRIDE (sim_transport._run_wave), so this body is
        the contract, not a hot path. Overrides MUST process frames in
        order for the determinism contract to hold."""
        serializer = self.serializer
        receive = self.receive
        for src, data in batch:
            receive(src, serializer.from_bytes(data))

    def on_drain(self) -> None:
        """Called by the transport after it finishes delivering a batch of
        inbound messages. Actors that stage work for batched device kernels
        (e.g. ProxyLeader vote collection onto the TpuQuorumChecker) flush
        it here -- the host-side analog of "one jitted step per event-loop
        drain" (SURVEY.md section 7)."""

    # --- helpers (Actor.scala:26-50) --------------------------------------
    def chan(self, dst: Address,
             serializer: Serializer | None = None) -> Chan:
        return Chan(self.transport, self.address, dst,
                    serializer or DEFAULT_SERIALIZER)

    def send(self, dst: Address, message: Any,
             serializer: Serializer | None = None) -> None:
        self.chan(dst, serializer).send(message)

    def send_no_flush(self, dst: Address, message: Any,
                      serializer: Serializer | None = None) -> None:
        self.chan(dst, serializer).send_no_flush(message)

    def broadcast(self, dsts, message: Any,
                  serializer: Serializer | None = None) -> None:
        """Send one message to many destinations, serializing it ONCE.
        The per-destination Chan.send path re-encodes identical bytes N
        times -- measurable when the message carries a whole drain's
        values (Phase2aRun to a write quorum, ChosenRun to every
        replica)."""
        data = (serializer or DEFAULT_SERIALIZER).to_bytes(message)
        for dst in dsts:
            self.transport.send(self.address, dst, data)

    def send_batch(self, dst: Address, messages,
                   serializer: Serializer | None = None) -> None:
        """Drain hook (paxwire): a handler that produced many messages
        for ONE destination ships them as a single transport batch --
        one flush, one writev, adjacent same-type payloads coalesced
        into a batch frame. The paxlint NET701 rule points per-message
        ``send`` loops here."""
        ser = serializer or DEFAULT_SERIALIZER
        self.transport.send_batch(
            self.address, dst, [ser.to_bytes(m) for m in messages])

    def flush(self, dst: Address) -> None:
        self.transport.flush(self.address, dst)

    def trace_stage(self, name: str):
        """A drain-stage scope (paxtrace, obs/): times ``name`` as a
        sub-span of the current trace and/or an observation into the
        runtime drain-stage histogram, whichever sinks are attached to
        the transport; a shared no-op otherwise. The canonical stages
        are decode, handler, quorum-kernel, wal-fsync, send-release."""
        transport = self.transport
        return stage_scope(transport.tracer, transport.runtime_metrics,
                           name)

    def timer(self, name: str, delay_s: float,
              f: Callable[[], None]) -> Timer:
        return self.transport.timer(self.address, name, delay_s, f)
