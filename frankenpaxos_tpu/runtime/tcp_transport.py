"""TcpTransport: the production transport (asyncio).

Reference behavior: NettyTcpTransport.scala:124-505 --

  * one event-loop thread for everything (``NioEventLoopGroup(1)``,
    NettyTcpTransport.scala:240) -> here: one asyncio loop; ``receive``
    and timer callbacks run serially on it, preserving the single-thread
    contract;
  * 4-byte length-prefixed frames, 10 MiB max
    (``LengthFieldBasedFrameDecoder(10485760, 0, 4, 0, 4)``,
    NettyTcpTransport.scala:353,417);
  * lazy connection establishment with pending-message buffering
    (NettyTcpTransport.scala:377-445), channel map keyed
    ``(local_actor_address, remote_address)``
    (NettyTcpTransport.scala:268-271);
  * ``send_no_flush`` + ``flush`` write coalescing
    (NettyTcpTransport.scala:455-495);
  * timers scheduled on the same loop (NettyTcpTransport.scala:78-122).

Addresses are ``(host, port)`` tuples. Each frame is prefixed by the
sender's address (so the receiving actor sees a meaningful ``src``),
mirroring the reference where inbound connections learn the remote actor
address from the channel.

paxwire (docs/TRANSPORT.md): with ``batching=True`` (the default) the
send path is DRAIN-GRANULAR -- ``send`` queues ``(header, payload)``
entries and one flush per event-loop pass turns a connection's backlog
into batch frames (adjacent same-type messages -> one frame, Phase2b
ack streams -> run-granular ack ranges via registered coalescers) and
pushes the whole thing out with ONE ``socket.sendmsg`` scatter/gather
writev over the original payload bytes -- no per-frame encode, no
per-frame ``bytes`` join, no per-message syscall. ``batching=False``
preserves the historical frame-per-message path (the A/B baseline arm
in ``bench/transport_lt.py``). The receive path scans the inbound
buffer over an offset cursor (no re-copy per scan pass) and expands
batch frames back into their original messages before delivery, so
actors, admission, and tracing see per-message semantics unchanged.
"""

from __future__ import annotations

import asyncio
import os
import struct
import threading
import time
from typing import Callable, Optional

from frankenpaxos_tpu.obs.trace import TraceContext
from frankenpaxos_tpu.runtime import paxwire
from frankenpaxos_tpu.runtime.actor import Actor
from frankenpaxos_tpu.runtime.logger import Logger, PrintLogger
from frankenpaxos_tpu.runtime.transport import Address, Timer, Transport

MAX_FRAME = 10 * 1024 * 1024  # 10 MiB, like the reference's frame decoder
_LEN = struct.Struct(">I")

_frame_lane_fn = None


def _get_frame_lane():
    """serve.lanes.frame_lane, lazily bound once (serve imports at
    module scope would cycle; a per-send module import would cost a
    sys.modules lookup on the hot path)."""
    global _frame_lane_fn
    if _frame_lane_fn is None:
        from frankenpaxos_tpu.serve.lanes import frame_lane
        _frame_lane_fn = frame_lane
    return _frame_lane_fn


def _encode_frame(src: Address, data: bytes,
                  ctx: "Optional[TraceContext]" = None) -> bytes:
    # The framing hot path runs through the native C++ codec when built
    # (frankenpaxos_tpu/native/codec.cpp), with an identical pure-Python
    # fallback inside `native.encode_frame`.
    from frankenpaxos_tpu import native

    host, port = src
    # paxtrace: the trace context rides the FRAME HEADER
    # (``host:port|<ctx>``), never the message codecs -- the wire tag
    # space 1..127 is fully allocated, and the header reaches every
    # protocol uniformly. Receivers without a "|" parse unchanged.
    if ctx is None:
        header = f"{host}:{port}".encode()
    else:
        header = f"{host}:{port}|{ctx.encode()}".encode()
    return native.encode_frame(header, data)


class TcpTimer(Timer):
    def __init__(self, loop: asyncio.AbstractEventLoop, name: str,
                 delay_s: float, f: Callable[[], None],
                 transport: "Optional[TcpTransport]" = None,
                 address: Optional[Address] = None):
        self._loop = loop
        self._name = name
        self._delay_s = delay_s
        self._f = f
        self._transport = transport
        self._address = address
        self._handle: Optional[asyncio.TimerHandle] = None

    @property
    def name(self) -> str:
        return self._name

    def start(self) -> None:
        self._loop.call_soon_threadsafe(self._start_on_loop)

    def _start_on_loop(self) -> None:
        if self._handle is None:
            self._handle = self._loop.call_later(self._delay_s, self._fire)

    def stop(self) -> None:
        self._loop.call_soon_threadsafe(self._stop_on_loop)

    def set_delay(self, delay_s: float) -> None:
        self._delay_s = delay_s

    def _stop_on_loop(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    def _fire(self) -> None:
        self._handle = None
        tracer = (self._transport.tracer
                  if self._transport is not None else None)
        if tracer is None:
            self._f()
            return
        with tracer.timer_span(str(self._address), self._name):
            self._f()


class _Conn:
    """One outbound connection with lazy connect + pending buffer
    (NettyTcpTransport.scala:377-445). The buffer is BOUNDED
    (paxload): a slow or dead peer must not grow it without limit --
    past the cap pending entries drop client-lane-oldest-first (the
    control plane is never shed behind client batches; at-most-once
    transport, protocol resends cover) and the stall is counted.

    ``pending`` holds ``(header, payload, lane, size)`` entries: the
    frame header bytes, the message payload bytes (frame assembly is
    deferred to the flush's batch planner), the frame lane for shed
    priority, and the entry's accounted wire size. The legacy
    per-frame arm (``batching=False``) stores the fully encoded frame
    in ``payload`` with ``header=None``."""

    __slots__ = ("writer", "pending", "pending_bytes", "hwm_reported",
                 "connecting", "header0", "headers")

    def __init__(self):
        self.writer: Optional[asyncio.StreamWriter] = None
        self.pending: list = []
        self.pending_bytes = 0
        # Largest pending_bytes already pushed to the HWM gauge: the
        # gauge (a mutex-protected prometheus read+set) is only touched
        # when this connection sets a NEW high-water mark, keeping the
        # per-frame cost to one int compare.
        self.hwm_reported = 0
        self.connecting = False
        # Encoded frame headers, cached per connection: the no-context
        # header (the common case) directly, traced headers by context
        # -- the per-send f-string format + encode was measurable at
        # batched rates.
        self.header0: Optional[bytes] = None
        self.headers: dict = {}


class TcpTransport(Transport):
    """Run the loop either externally (``await serve()``) or on a daemon
    thread (``start()``) for synchronous callers like the CLI mains."""

    threaded = True

    #: Per-connection outbound buffer cap in bytes (paxload). Past it
    #: the OLDEST pending frames drop -- within the at-most-once
    #: transport contract, like the dead-writer loss path above -- and
    #: fpx_runtime_outbound_stalls_total counts the overflow. Large
    #: enough that only a genuinely wedged/slow peer ever hits it.
    outbound_buffer_cap = 16 * 1024 * 1024

    #: Use ``socket.sendmsg`` scatter/gather output when the platform
    #: and the asyncio transport allow it (class-level so tests can
    #: force the contiguous-write fallback and assert bit-identity).
    use_sendmsg = True

    def __init__(self, listen_address: Optional[Address] = None,
                 logger: Optional[Logger] = None,
                 batching: bool = True):
        self.logger = logger or PrintLogger()
        self.listen_address = listen_address
        #: paxwire drain-granular batching; False = the historical
        #: frame-per-message path (the transport_lt baseline arm).
        self.batching = batching
        self.actors: dict[Address, Actor] = {}
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._conns: dict[tuple[Address, Address], _Conn] = {}
        self._servers: dict[Address, asyncio.AbstractServer] = {}
        self._drain_scheduled: set = set()
        # Connections with unflushed sends this event-loop pass; one
        # call_soon drains them all (_flush_pass) so every message a
        # drain produces rides one writev per peer.
        self._flush_queue: list = []
        self._flush_dirty: set = set()
        self._flush_scheduled = False
        #: paxchaos link-fault seam (faults/deployed_backend.LinkFaults
        #: .check, or any ``(src, dst) -> extra_delay_s | None``):
        #: consulted once per outbound message when armed -- None
        #: drops the frame (partition), > 0 defers the write by that
        #: many wall seconds (injected latency / brownout). Unarmed
        #: (the default) costs one attribute test per send.
        self.link_faults = None
        # Transport counters (the transport_lt A/B instruments these;
        # /metrics exports them when runtime_metrics is attached).
        # "syscalls" counts our sendmsg calls plus writer.write calls
        # (asyncio issues one send per uncongested write) -- the
        # syscalls/cmd proxy the A/B gate records.
        self.stat_syscalls = 0
        self.stat_flushes = 0
        self.stat_frames = 0
        self.stat_messages = 0
        self.stat_batch_bytes = 0
        self.stat_coalesced_acks = 0
        self._batch_depth: dict = {}  # messages in the current drain
        # CLIENT-lane messages in the current drain batch -- the
        # bounded-inbox measure (serve/lanes.py): only client frames
        # may count against (or be shed by) admission_inbox_capacity;
        # a Phase1b/watermark burst must never trip it.
        self._client_batch_depth: dict = {}
        self._batch_t0: dict = {}     # first delivery time (CoDel)
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    # --- lifecycle --------------------------------------------------------
    async def serve(self) -> None:
        """Bind (if a listen address was given) and run until cancelled."""
        self.loop = asyncio.get_running_loop()
        if self.listen_address is not None:
            await self._bind(self.listen_address)
        for address in list(self.actors):
            if isinstance(address, tuple):  # registered before start()
                await self._bind(address)
        self._started.set()
        try:
            await asyncio.Event().wait()  # run forever
        finally:
            await self._shutdown()

    async def _bind(self, address: Address) -> None:
        if address in self._servers:
            return
        import functools

        host, port = address
        self._servers[address] = await asyncio.start_server(
            functools.partial(self._handle_conn, local=address),
            host, port)

    def start(self) -> None:
        """Spawn the event loop on a daemon thread and wait until bound."""
        def runner():
            try:
                asyncio.run(self.serve())
            except asyncio.CancelledError:
                pass

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("TcpTransport failed to start")

    def stop(self) -> None:
        if self.loop is not None:
            self.loop.call_soon_threadsafe(
                lambda: [t.cancel() for t in asyncio.all_tasks(self.loop)])
        if self._thread is not None:
            self._thread.join(timeout=5)

    async def _shutdown(self) -> None:
        for server in self._servers.values():
            server.close()
        for conn in self._conns.values():
            if conn.writer is not None:
                conn.writer.close()

    # --- inbound ----------------------------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter,
                           local: Address) -> None:
        # Chunked reads + a native frame scan (codec.cpp
        # fpx_scan_frames) instead of two awaits per frame: a burst of
        # small frames costs ONE read syscall and one scan, and every
        # complete frame in the chunk dispatches in the same loop pass
        # (so they land in one actor drain; see _deliver). The scan
        # rides an OFFSET CURSOR into the growing bytearray: the old
        # ``scan_frames(bytes(buf))`` re-copied the whole inbound
        # buffer every 4096-frame pass (quadratic on deep backlogs),
        # and the per-pass ``del buf[:consumed]`` memmoved the tail the
        # same way -- now the prefix compacts only when it is large.
        from frankenpaxos_tpu import native

        buf = bytearray()
        pos = 0  # buf[:pos] is already dispatched
        try:
            while True:
                chunk = await reader.read(1 << 16)
                if not chunk:
                    break
                buf += chunk
                # Dispatch every complete frame currently buffered.
                # The head-frame length check gates each scan: while a
                # large frame is still arriving, each chunk costs one
                # unpack and no rescan of the whole buffer; the
                # oversize check is against the frame's DECLARED
                # length, never the buffer size (a near-cap frame
                # pipelined with the next frame's first bytes is
                # legitimate). The inner loop re-scans because the
                # native scanner caps one pass at 4096 frames -- a
                # single pass over a deeper backlog would strand the
                # remainder until the peer happened to send more.
                while len(buf) - pos >= 4:
                    (inner,) = _LEN.unpack_from(buf, pos)
                    if inner > MAX_FRAME:
                        self.logger.error(
                            f"oversized frame ({inner} bytes)")
                        return
                    if len(buf) - pos < 4 + inner:
                        break
                    try:
                        frames, pos = native.scan_frames(buf, offset=pos)
                    except ValueError as e:  # a mid-buffer oversized frame
                        self.logger.error(str(e))
                        return
                    for start, end in frames:
                        if not self._dispatch_frame(buf, start, end,
                                                    local):
                            return
                # Compact the dispatched prefix only when it is big
                # enough to matter (or the buffer is fully consumed):
                # each del memmoves the tail, so doing it per pass is
                # the quadratic copy this cursor exists to avoid.
                if pos and (pos >= len(buf) or pos >= (1 << 18)):
                    del buf[:pos]
                    pos = 0
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    def _dispatch_frame(self, buf: bytearray, start: int, end: int,
                        local: Address) -> bool:
        """Parse, decode, and deliver one wire frame (batch frames
        expand to their segments). False = corrupt frame, drop the
        connection.

        A corrupt frame (bad header length, non-UTF8 header, malformed
        port, message decode error, torn batch table) must not kill
        the connection task with an unretrieved exception: log it and
        drop the connection cleanly. Only parse/decode runs under the
        corrupt-frame guard -- exceptions from the actor's own
        ``receive()`` on a VALID frame are a different failure class
        and propagate (a FatalError from logger.fatal must stay fatal,
        matching the reference's crash-the-process check semantics,
        Logger.scala:62-117)."""
        try:
            (hlen,) = _LEN.unpack_from(buf, start)
            if hlen > end - start - 4:
                raise ValueError(
                    f"header length {hlen} exceeds frame "
                    f"payload {end - start - 4}")
            header = bytes(
                buf[start + 4:start + 4 + hlen]).decode()
            # paxtrace: ``host:port|<ctx>`` -- the address part first,
            # then the optional frame-layer trace context. On a batch
            # frame this ONE header (context included) covers every
            # expanded segment.
            addr_part, _, trace_part = header.partition("|")
            host, _, port = addr_part.rpartition(":")
            src: Address = (host, int(port))
            ctx = (TraceContext.decode(trace_part)
                   if trace_part else None)
            data = bytes(buf[start + 4 + hlen:end])
            # The frame's actor, resolved ONCE (decode below reuses it,
            # so the wire-sink check costs one attribute test net).
            actor = self._actor_for(local)
            # paxingest wire-sink fast path (Actor.wire_sinks): hand a
            # whole undecoded batch payload to the actor's column
            # parser -- no per-message decode, no expansion. Only the
            # PARSE runs under this corrupt-frame guard; the handler
            # runs below with ordinary handler semantics. Bypassed
            # under a tracer (per-message span semantics win).
            fast = None
            sinks = getattr(actor, "wire_sinks", None)
            if sinks is not None and self.tracer is None:
                sink = sinks.get(paxwire.leading_tag(data))
                if sink is not None:
                    metrics = self.runtime_metrics
                    if metrics is not None:
                        p0 = time.perf_counter()
                        parsed = sink[0](data)
                        metrics.observe_stage(
                            "decode", time.perf_counter() - p0)
                    else:
                        parsed = sink[0](data)
                    if parsed is not None:
                        fast = (actor, sink[1], parsed)
            if fast is not None:
                pass
            elif paxwire.is_batch_payload(data):
                segments = paxwire.split_batch(data)
            else:
                segments = (data,)
            deliveries = []
            tracer = self.tracer
            metrics = self.runtime_metrics
            for segment in segments if fast is None else ():
                if tracer is not None and ctx is not None \
                        and ctx.sampled:
                    m0 = tracer.mono()
                    delivery = self._decode(local, src, segment,
                                            actor)
                    if delivery is not None:
                        tracer.record_stage("decode", m0, ctx)
                elif metrics is not None:
                    # Unsampled (or context-less) frame with /metrics
                    # on: the drain-stage histogram still sees EVERY
                    # decode -- sampling must not starve it.
                    p0 = time.perf_counter()
                    delivery = self._decode(local, src, segment,
                                            actor)
                    if delivery is not None:
                        metrics.observe_stage(
                            "decode", time.perf_counter() - p0)
                else:
                    delivery = self._decode(local, src, segment,
                                            actor)
                if delivery is not None:
                    deliveries.append(delivery)
        except Exception as e:
            self.logger.error(
                f"dropping connection on corrupt frame: {e!r}")
            return False
        if fast is not None:
            actor, handler, parsed = fast
            # Handler semantics match receive(): exceptions on a VALID
            # frame propagate (a FatalError stays fatal).
            handler(src, parsed)
            self._note_delivered(actor, parsed.count)
            return True
        for delivery in deliveries:
            self._deliver(*delivery, ctx)
        return True

    def _actor_for(self, local: Address):
        """The registered actor for frames arriving on ``local``: each
        registered actor (the role itself plus any embedded
        election/heartbeat participants) listens on its own port."""
        actor = self.actors.get(local)
        if actor is None and self.listen_address is not None:
            actor = self.actors.get(self.listen_address)
        return actor

    def _decode(self, local: Address, src: Address, data: bytes,
                actor: "Actor | None" = None):
        """Frame payload -> (actor, src, message), or None if no actor
        is registered. Decode errors propagate to the caller's
        corrupt-frame guard. ``actor`` skips re-resolving when the
        caller already did (_dispatch_frame resolves once per frame)."""
        if actor is None:
            actor = self._actor_for(local)
        if actor is None:
            self.logger.warn(f"dropping frame from {src} to {local}: "
                             f"no registered actor")
            return None
        return actor, src, actor.serializer.from_bytes(data)

    def _note_delivered(self, actor: Actor, n: int) -> None:
        """Drain bookkeeping for a wire-sink delivery of ``n``
        messages' worth of work: batch-depth accounting plus the
        deferred on_drain, exactly like per-message _deliver. The
        client-lane bounded-inbox measure is intentionally NOT fed --
        admission at sink granularity is the sink handler's job."""
        admission = actor.admission
        if self.runtime_metrics is not None or admission is not None:
            self._batch_depth[actor] = \
                self._batch_depth.get(actor, 0) + n
        if actor not in self._drain_scheduled:
            self._drain_scheduled.add(actor)
            if admission is not None \
                    and admission.options.codel_target_s:
                self._batch_t0[actor] = time.perf_counter()
            self.loop.call_soon(self._drain_actor, actor)

    def _deliver(self, actor: Actor, src: Address, message,
                 ctx: "Optional[TraceContext]" = None) -> None:
        expand = getattr(message, "__wire_expand__", None)
        if expand is not None:
            # A coalesced wire envelope (paxwire): flatten back into
            # the messages the sender queued -- admission, tracing, and
            # the protocol handlers see per-message semantics.
            for inner in expand(actor.serializer):
                self._deliver(actor, src, inner, ctx)
            return
        admission = actor.admission
        if admission is not None and self._shed_inbound(actor, admission,
                                                        message):
            return
        tracer = self.tracer
        if tracer is None:
            metrics = self.runtime_metrics
            if metrics is not None:
                # Metrics-only mode: the handler stage (usually the
                # largest) must reach the drain-stage histogram like
                # every other canonical stage does.
                p0 = time.perf_counter()
                actor.receive(src, message)
                metrics.observe_stage("handler",
                                      time.perf_counter() - p0)
            else:
                actor.receive(src, message)
        else:
            span = tracer.receive_span(
                str(actor.address), type(message).__name__, ctx)
            with span:
                with tracer.stage("handler"):
                    actor.receive(src, message)
        if self.runtime_metrics is not None or admission is not None:
            self._batch_depth[actor] = \
                self._batch_depth.get(actor, 0) + 1
        if admission is not None and admission.options.inbox_capacity:
            from frankenpaxos_tpu.serve.lanes import (
                LANE_CLIENT,
                message_lane,
            )

            if message_lane(message) == LANE_CLIENT:
                self._client_batch_depth[actor] = \
                    self._client_batch_depth.get(actor, 0) + 1
        # Defer on_drain to the end of this event-loop pass so every
        # frame already buffered (a burst of Phase2bs) lands in ONE
        # drain -- the batching the device kernels amortize over
        # (the reference's event loop drains similarly: all readable
        # frames, then flush).
        if actor not in self._drain_scheduled:
            self._drain_scheduled.add(actor)
            if admission is not None \
                    and admission.options.codel_target_s:
                # CoDel's sojourn clock starts at the batch's FIRST
                # delivery; note_drain_delay closes it after on_drain.
                self._batch_t0[actor] = time.perf_counter()
            self.loop.call_soon(self._drain_actor, actor)

    def _shed_inbound(self, actor: Actor, admission, message) -> bool:
        """Bounded inbox + CoDel shedding at delivery (client lane
        only; serve/lanes.py). True = the frame was shed -- the client
        got an explicit Rejected instead of a handler call. TCP
        enforces reject-newest for both policies: already-delivered
        frames cannot be un-delivered, so drop-oldest only differs on
        SimTransport's buffered queue."""
        from frankenpaxos_tpu.serve.lanes import LANE_CLIENT, message_lane

        if message_lane(message) != LANE_CLIENT:
            return False
        if admission.shed_active():
            reason_queue = False
        elif admission.inbox_full(self._client_batch_depth.get(actor, 0)):
            reason_queue = True
        else:
            return False
        from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER
        from frankenpaxos_tpu.serve.admission import reject_replies_for
        from frankenpaxos_tpu.serve.messages import (
            REASON_CODEL,
            REASON_QUEUE,
        )

        admission.note_shed("reject-newest")
        for client, reply in reject_replies_for(
                message, admission.retry_after_ms(),
                REASON_QUEUE if reason_queue else REASON_CODEL):
            self._write(actor.address, client,
                        DEFAULT_SERIALIZER.to_bytes(reply), flush=True)
        return True

    def _drain_actor(self, actor: Actor) -> None:
        self._drain_scheduled.discard(actor)
        depth = self._batch_depth.pop(actor, 0)
        client_depth = self._client_batch_depth.pop(actor, 0)
        if self.runtime_metrics is not None:
            self.runtime_metrics.observe_batch(depth)
        tracer = self.tracer
        if tracer is None:
            actor.on_drain()
        else:
            with tracer.drain_span(str(actor.address)):
                actor.on_drain()
        admission = actor.admission
        if admission is not None:
            t0 = self._batch_t0.pop(actor, None)
            if t0 is not None:
                admission.note_drain_delay(time.perf_counter() - t0)
            # Client-lane depth only: the gauge is the BOUNDED-inbox
            # depth (what inbox_full checks), not the all-lane drain
            # batch -- a healthy Phase2b burst must not read as a
            # client inbox spike (SimTransport reports the same).
            admission.note_inbox_depth(client_depth)

    def listen_on(self, address: Address) -> None:
        """Bind a listener for ``address`` ahead of actor registration
        (used by supernode mode to make every role address reachable
        before any actor's construction-time sends go out)."""
        assert self.loop is not None, "transport not started"
        asyncio.run_coroutine_threadsafe(
            self._bind(address), self.loop).result(timeout=10)

    # --- Transport API ----------------------------------------------------
    def register(self, address: Address, actor: Actor) -> None:
        """Register ``actor`` and listen on its address.

        A role process hosts one main role actor plus embedded
        sub-actors (leader election, heartbeat participants), each with
        its own (host, port) from the cluster config: every registered
        address gets its own listener so remote peers can reach the
        sub-actors too (the reference runs them as Netty-registered
        actors on the shared event loop the same way).
        """
        if address in self.actors:
            raise ValueError(f"an actor is already registered at {address}")
        self.actors[address] = actor
        if self.loop is not None and address not in self._servers \
                and isinstance(address, tuple):
            if self._on_loop():
                task = self.loop.create_task(self._bind(address))
                task.add_done_callback(
                    lambda t: (not t.cancelled() and t.exception())
                    and self.logger.error(
                        f"bind {address} failed: {t.exception()!r}"))
            else:
                future = asyncio.run_coroutine_threadsafe(
                    self._bind(address), self.loop)
                future.result(timeout=10)

    def _conn_for(self, src: Address, dst: Address) -> _Conn:
        key = (src, dst)
        conn = self._conns.get(key)
        if conn is None:
            conn = _Conn()
            self._conns[key] = conn
        return conn

    def _header_for(self, conn: _Conn, src: Address,
                    ctx: "Optional[TraceContext]") -> bytes:
        """The frame header bytes (``host:port`` or
        ``host:port|<ctx>``), cached per connection -- the per-send
        f-string format + encode was measurable at batched rates."""
        if ctx is None:
            header = conn.header0
            if header is None:
                host, port = src
                header = conn.header0 = f"{host}:{port}".encode()
            return header
        key = (ctx.trace_id, ctx.span_id, ctx.sampled)
        header = conn.headers.get(key)
        if header is None:
            host, port = src
            header = f"{host}:{port}|{ctx.encode()}".encode()
            if len(conn.headers) > 256:  # sampled-trace churn bound
                conn.headers.clear()
            conn.headers[key] = header
        return header

    def _write(self, src: Address, dst: Address, data: bytes,
               flush: bool,
               ctx: "Optional[TraceContext]" = None,
               faulted: bool = False) -> None:
        assert self.loop is not None, "transport not started"
        if self.link_faults is not None and not faulted:
            # paxchaos: one verdict per message, evaluated at the
            # original send instant (a deferred write must not re-roll
            # against a table that changed while it slept).
            verdict = self.link_faults(src, dst)
            if verdict is None:
                return  # partitioned: dropped at the send path
            if verdict > 0:
                self.loop.call_later(
                    verdict, self._write, src, dst, data, flush, ctx,
                    True)
                return
        conn = self._conn_for(src, dst)
        if conn.writer is not None and conn.writer.is_closing():
            # The peer died (process crash / kill -9) or reset the
            # connection: drop the dead writer so this send triggers a
            # fresh lazy connect. Without this, every later message to
            # a RESTARTED role would pour into a closed socket forever
            # -- the failure mode the WAL chaos harness exists to
            # catch. Messages written into the dead socket before the
            # loss was detected are gone, which is within the
            # at-most-once transport contract; protocol resends cover
            # them.
            conn.writer = None
        lane = _get_frame_lane()(data)
        if self.batching:
            header = self._header_for(conn, src, ctx)
            if 4 + len(header) + len(data) > MAX_FRAME:
                # Same cap the receiver enforces -- but _write runs as
                # a loop callback (or inline inside a handler's send),
                # so raising here would abort the sending actor or
                # vanish into the loop's exception handler. Dropping
                # with a stall count is the documented at-most-once
                # behavior for an unsendable frame.
                metrics = self.runtime_metrics
                if metrics is not None:
                    metrics.outbound_stall(1)
                self.logger.error(
                    f"dropping {len(data)}-byte message to {dst}: "
                    f"frame exceeds the 10 MiB cap")
                return
            size = 12 + len(header) + len(data)
            conn.pending.append((header, data, lane, size))
        else:
            frame = _encode_frame(src, data, ctx)
            size = len(frame)
            conn.pending.append((None, frame, lane, size))
        conn.pending_bytes += size
        if conn.pending_bytes > conn.hwm_reported:
            conn.hwm_reported = conn.pending_bytes
            metrics = self.runtime_metrics
            if metrics is not None:
                metrics.outbound_buffer_hwm(conn.pending_bytes)
        if conn.pending_bytes > self.outbound_buffer_cap:
            dropped = self._shed_outbound(conn)
            metrics = self.runtime_metrics
            if metrics is not None:
                metrics.outbound_stall(dropped)
            self.logger.warn(
                f"outbound buffer to {dst} over "
                f"{self.outbound_buffer_cap} bytes; dropped {dropped} "
                f"oldest frames, client lane first (peer slow or gone; "
                f"resends cover)")
        if conn.writer is not None:
            if flush:
                if self.batching:
                    self._schedule_flush(conn)
                else:
                    self._flush_conn(conn)
        elif not conn.connecting:
            conn.connecting = True
            self.loop.create_task(self._connect(conn, dst))

    def _shed_outbound(self, conn: _Conn) -> int:
        """Bounded outbound buffer (paxload): a slow or dead peer must
        not grow ``pending`` without limit (reachable under chaos since
        the PR 3 reconnect fix). Sheds the OLDEST entries -- they have
        aged the most and their resend timers are the closest to
        firing -- CLIENT-LANE FIRST: control traffic (votes, Phase1,
        epoch commits, heartbeats) is never shed behind a backlog of
        client batches, the invariant the overload chaos tests assert.
        The newest entry always survives so a send makes progress."""
        from frankenpaxos_tpu.serve.lanes import LANE_CLIENT

        dropped = 0
        for pass_lane in (LANE_CLIENT, None):
            if conn.pending_bytes <= self.outbound_buffer_cap:
                break
            pending = conn.pending
            kept: list = []
            last = len(pending) - 1
            for k, entry in enumerate(pending):
                if (conn.pending_bytes > self.outbound_buffer_cap
                        and k != last
                        and (pass_lane is None
                             or entry[2] == pass_lane)):
                    conn.pending_bytes -= entry[3]
                    dropped += 1
                else:
                    kept.append(entry)
            conn.pending = kept
        return dropped

    def _schedule_flush(self, conn: _Conn) -> None:
        """Queue ``conn`` for the end-of-pass flush: every send of the
        current event-loop pass (one actor drain's whole output, often
        several actors') lands in the same writev."""
        if conn in self._flush_dirty:
            return
        self._flush_dirty.add(conn)
        self._flush_queue.append(conn)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self.loop.call_soon(self._flush_pass)

    def _flush_pass(self) -> None:
        self._flush_scheduled = False
        queue, self._flush_queue = self._flush_queue, []
        self._flush_dirty.clear()
        for conn in queue:
            self._flush_conn(conn)

    async def _connect(self, conn: _Conn, dst: Address) -> None:
        host, port = dst
        try:
            _, writer = await asyncio.open_connection(host, port)
        except OSError as e:
            self.logger.warn(f"connect to {dst} failed: {e}; "
                             f"dropping {len(conn.pending)} pending")
            conn.pending.clear()
            conn.pending_bytes = 0
            conn.connecting = False
            return
        conn.writer = writer
        conn.connecting = False
        self._flush_conn(conn)

    def _flush_conn(self, conn: _Conn) -> None:
        if conn.writer is None or not conn.pending:
            return
        entries = conn.pending
        conn.pending = []
        conn.pending_bytes = 0
        writer = conn.writer
        self.stat_flushes += 1
        self.stat_messages += len(entries)
        if not self.batching:
            # Legacy per-frame arm: frames were encoded at send time;
            # one join + write per flush (today's == pre-paxwire
            # behavior, the A/B baseline).
            self.stat_frames += len(entries)
            try:
                writer.write(b"".join(e[1] for e in entries))
                self.stat_syscalls += 1
            except (OSError, RuntimeError) as e:
                self.logger.warn(
                    f"write failed ({e}); dropping connection")
                conn.writer = None
            return
        plan = paxwire.plan_flush(entries)
        self.stat_frames += plan.frames
        self.stat_batch_bytes += plan.nbytes
        self.stat_coalesced_acks += plan.coalesced_acks
        metrics = self.runtime_metrics
        if metrics is not None:
            metrics.transport_flush(plan.frames, plan.nbytes)
            if plan.coalesced_acks:
                metrics.transport_coalesced_acks(plan.coalesced_acks)
        try:
            if not self._writev(writer, plan.segments):
                writer.write(b"".join(plan.segments))
                self.stat_syscalls += 1
        except (OSError, RuntimeError) as e:
            # Connection torn down mid-write: drop the writer; the
            # next send reconnects (see _write) and resends cover the
            # loss.
            self.logger.warn(f"write failed ({e}); dropping connection")
            conn.writer = None

    #: sendmsg iovec ceiling (POSIX IOV_MAX is commonly 1024).
    _IOV_MAX = 1024

    def _writev(self, writer: asyncio.StreamWriter,
                segments: list) -> bool:
        """Zero-copy scatter/gather output: push the flush plan's
        segments with ``os.writev`` -- the payload ``bytes`` objects go
        straight to the kernel as an iovec, never joined. Only safe
        when asyncio's own write buffer is empty (ordering); on a
        partial or blocked send the remainder is handed to
        ``writer.write`` and asyncio's flow control takes over. Returns
        False when writev cannot be used at all (caller falls back to
        one join+write)."""
        if not self.use_sendmsg:
            return False
        transport = writer.transport
        sock = transport.get_extra_info("socket")
        if sock is None or transport.get_write_buffer_size() != 0:
            return False
        try:
            fd = sock.fileno()
        except (OSError, ValueError):
            return False
        if fd < 0:
            return False
        i, n = 0, len(segments)
        while i < n:
            chunk = segments[i:i + self._IOV_MAX]
            try:
                sent = os.writev(fd, chunk)
                self.stat_syscalls += 1
            except (BlockingIOError, InterruptedError):
                sent = 0
            total = sum(len(s) for s in chunk)
            if sent == total:
                i += self._IOV_MAX
                continue
            # Kernel buffer full mid-flush: asyncio owns the rest.
            rest: list = []
            for seg in chunk:
                if sent >= len(seg):
                    sent -= len(seg)
                    continue
                rest.append(seg[sent:] if sent else seg)
                sent = 0
            rest.extend(segments[i + self._IOV_MAX:])
            writer.write(b"".join(rest))
            self.stat_syscalls += 1
            return True
        return True

    def _send_ctx(self) -> "Optional[TraceContext]":
        """The trace context to stamp on an outbound frame: captured at
        the SEND CALL (the caller's active span), not when the deferred
        write runs on the loop."""
        tracer = self.tracer
        return tracer.current if tracer is not None else None

    def send(self, src: Address, dst: Address, data: bytes) -> None:
        ctx = self._send_ctx()
        self._call_on_loop(
            lambda: self._write(src, dst, data, flush=True, ctx=ctx))

    def send_no_flush(self, src: Address, dst: Address, data: bytes) -> None:
        ctx = self._send_ctx()
        self._call_on_loop(
            lambda: self._write(src, dst, data, flush=False, ctx=ctx))

    def flush(self, src: Address, dst: Address) -> None:
        if self.batching:
            # Ride the end-of-pass flush: the explicit flush's messages
            # still leave in this loop pass, batched with everything
            # else the drain produced.
            self._call_on_loop(
                lambda: self._schedule_flush(self._conn_for(src, dst)))
        else:
            self._call_on_loop(
                lambda: self._flush_conn(self._conn_for(src, dst)))

    def _on_loop(self) -> bool:
        """Is THIS thread currently running our event loop? Never
        consults private loop attributes (loop._thread_id is
        CPython-internal)."""
        try:
            return asyncio.get_running_loop() is self.loop
        except RuntimeError:
            return False

    def _call_on_loop(self, f: Callable[[], None]) -> None:
        assert self.loop is not None, "transport not started"
        # Running f() inline when already on the loop keeps same-pass
        # sends in the current drain instead of deferring them to the
        # next pass.
        if self._on_loop():
            f()
        else:
            self.loop.call_soon_threadsafe(f)

    def timer(self, address: Address, name: str, delay_s: float,
              f: Callable[[], None]) -> TcpTimer:
        assert self.loop is not None, "transport not started"
        return TcpTimer(self.loop, name, delay_s, f, transport=self,
                        address=address)
