"""The actor runtime kernel (reference L0/L1).

Reference behavior: Actor.scala:7-51, Transport.scala:44-99, Chan.scala:3-17,
Timer.scala:23-42, Serializer.scala:5-10, Logger.scala:1-118,
FakeTransport.scala:64-183, NettyTcpTransport.scala:124-505.

The load-bearing invariant (Transport.scala:37-40): **every transport is a
single-threaded event loop** -- `receive` and timer callbacks run serially.
Protocols are therefore deterministic, lock-free state machines; all
parallelism lives in the batched device kernels they call into.
"""

from frankenpaxos_tpu.runtime.actor import Actor, Chan
from frankenpaxos_tpu.runtime.logger import (
    FakeLogger,
    FileLogger,
    Logger,
    LogLevel,
    PrintLogger,
)
from frankenpaxos_tpu.runtime.monitoring import (
    Collectors,
    Counter,
    FakeCollectors,
    FakeHistogram,
    Gauge,
    Histogram,
    PrometheusCollectors,
    Summary,
)
from frankenpaxos_tpu.runtime.serializer import PickleSerializer, Serializer
from frankenpaxos_tpu.runtime.sim_transport import SimTimer, SimTransport
from frankenpaxos_tpu.runtime.transport import Timer, Transport

__all__ = [
    "Actor",
    "Chan",
    "Collectors",
    "Counter",
    "FakeCollectors",
    "FakeHistogram",
    "FakeLogger",
    "FileLogger",
    "Gauge",
    "Histogram",
    "LogLevel",
    "Logger",
    "PickleSerializer",
    "PrintLogger",
    "PrometheusCollectors",
    "Serializer",
    "SimTimer",
    "SimTransport",
    "Summary",
    "Timer",
    "Transport",
]
