"""ClientTable: exactly-once semantics for out-of-order executors.

Reference behavior: clienttable/ClientTable.scala:135+. Clients annotate
commands with monotonically-increasing ids. Simple protocols (MultiPaxos)
execute each client's commands in id order, but generalized protocols
(EPaxos/BPaxos) may execute them out of order, so per client we keep:

  * the full set of executed ids as an IntPrefixSet (compacts to a
    watermark in the common in-order case), and
  * the output of the *largest* executed id (the only one a live client
    can still be waiting on).
"""

from __future__ import annotations

import dataclasses
from typing import Generic, Hashable, Optional, TypeVar

from frankenpaxos_tpu.compact import IntPrefixSet

A = TypeVar("A", bound=Hashable)
O = TypeVar("O")


class NotExecuted:
    """Sentinel: the command has not been executed; go ahead."""

    def __repr__(self):
        return "NotExecuted"


NOT_EXECUTED = NotExecuted()


@dataclasses.dataclass(frozen=True)
class Executed(Generic[O]):
    """The command already executed. ``output`` is cached only if this is
    the client's largest executed id (ClientTable.scala:62-83)."""

    output: Optional[O]


@dataclasses.dataclass
class _ClientState(Generic[O]):
    largest_id: int
    largest_output: O
    executed_ids: IntPrefixSet


class ClientTable(Generic[A, O]):
    def __init__(self):
        self._table: dict[A, _ClientState[O]] = {}

    def __repr__(self):
        return f"ClientTable({self._table!r})"

    def executed(self, client: A, client_id: int):
        """NOT_EXECUTED | Executed(output or None); see module docstring."""
        state = self._table.get(client)
        if state is None or not state.executed_ids.contains(client_id):
            return NOT_EXECUTED
        if client_id == state.largest_id:
            return Executed(state.largest_output)
        return Executed(None)

    def execute(self, client: A, client_id: int, output: O) -> None:
        """Record an execution. Callers must have checked ``executed``
        first; re-recording an id is a bug (fail-stop, like the
        reference's check)."""
        state = self._table.get(client)
        if state is None:
            state = _ClientState(largest_id=client_id, largest_output=output,
                                 executed_ids=IntPrefixSet())
            self._table[client] = state
        if state.executed_ids.add(client_id):
            raise ValueError(
                f"client {client!r} id {client_id} executed twice")
        if client_id >= state.largest_id:
            state.largest_id = client_id
            state.largest_output = output

    def to_dict(self) -> dict:
        """Wire form (ClientTableProto)."""
        return {
            "kv": [
                {
                    "client": client,
                    "largest_id": s.largest_id,
                    "largest_output": s.largest_output,
                    "executed_ids": s.executed_ids.to_dict(),
                }
                for client, s in self._table.items()
            ]
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ClientTable":
        table = cls()
        for kv in d["kv"]:
            table._table[kv["client"]] = _ClientState(
                largest_id=kv["largest_id"],
                largest_output=kv["largest_output"],
                executed_ids=IntPrefixSet.from_dict(kv["executed_ids"]),
            )
        return table
