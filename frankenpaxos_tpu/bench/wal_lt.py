"""WAL latency-throughput A/B: durability on vs off per drain width.

The question the "Paxos in the Cloud" experience report raises
(PAPERS.md): durable logging dominates Paxos unless writes are
batched. The paxlog WAL batches by construction -- ONE fsync per
event-loop drain (group commit at the on_drain boundary) -- so the
per-message durability overhead should SHRINK as drain width grows.
This bench measures exactly that, with the multipaxos_lt methodology:

  * the interleaved paired SimTransport A/B of the full coalesced
    actor pipeline (ClientRequestArray -> Phase2aRun -> Phase2bRange
    -> ChosenRun -> ClientReplyArray) per in-flight width, arms
    ``wal-off`` vs ``wal-on`` (FileStorage WALs, REAL fsyncs, a fresh
    directory per run); per width, ``reps`` pairs with rotating order,
    the MEDIAN of paired ratios, pooled over independent subprocess
    batches;
  * per-width WAL accounting from a dedicated instrumented run:
    fsync count, fsyncs per command, bytes and records per drain
    group commit, summed across every acceptor and replica;
  * deployed TCP points (every role its own OS process, --wal_dir on
    vs off) at small scales -- the multipaxos_lt deployed_points
    shape.

Usage::

    python -m frankenpaxos_tpu.bench.wal_lt \
        --out bench_results/wal_lt.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time


def _drive_waves(sim, inflight: int, waves: int, tag: bytes,
                 results: list) -> None:
    """Closed-loop waves of ``inflight`` coalesced writes delivered at
    event-loop drain granularity; pump recover/resend timers so holes
    never stall a wave (the mencius_lt driver shape)."""
    for b in range(waves):
        for p in range(inflight):
            sim.clients[0].write(p, b"%s%d.%d" % (tag, b, p),
                                 results.append)
        sim.clients[0].flush_writes()
        sim.transport.deliver_all_coalesced()
        for _ in range(60):
            if not sim.clients[0].states:
                break
            for timer in sim.transport.running_timers():
                if timer.name == "recover" \
                        or timer.name.startswith("resendWrite"):
                    sim.transport.trigger_timer(timer.id)
            sim.transport.deliver_all_coalesced()


def _make(arm: str, tmp_root: str):
    from tests.protocols.multipaxos_harness import make_multipaxos

    if arm == "wal-off":
        return make_multipaxos(f=1, coalesced=True), None
    wal_dir = tempfile.mkdtemp(dir=tmp_root, prefix="walarm_")
    return make_multipaxos(f=1, coalesced=True, wal=wal_dir), wal_dir


def wal_accounting(sim) -> dict:
    """Summed WAL metrics across every durable role."""
    roles = [a for a in sim.acceptors if a.wal is not None] \
        + [r for r in sim.replicas if r.wal is not None]
    total = {
        "fsyncs": sum(r.wal.metrics.syncs for r in roles),
        "bytes_synced": sum(r.wal.metrics.bytes_synced for r in roles),
        "records_synced": sum(r.wal.metrics.records_synced
                              for r in roles),
        "compactions": sum(r.wal.metrics.compactions for r in roles),
    }
    if total["fsyncs"]:
        total["bytes_per_drain_sync"] = round(
            total["bytes_synced"] / total["fsyncs"], 1)
        total["records_per_drain_sync"] = round(
            total["records_synced"] / total["fsyncs"], 2)
    return total


def sim_ab_pipeline(inflights, reps: int = 6, waves: int = 0,
                    warm: int = 2) -> dict:
    """Interleaved paired A/B (multipaxos_lt.sim_ab_pipeline
    methodology) of wal-on (real fsyncs) vs wal-off."""
    import gc
    import statistics

    tmp_root = tempfile.mkdtemp(prefix="fpx_wal_lt_")
    ARMS = ("wal-off", "wal-on")

    def measure(arm: str, inflight: int, w: int) -> float:
        gc.collect()
        sim, wal_dir = _make(arm, tmp_root)
        results: list = []
        _drive_waves(sim, inflight, warm, b"w", results)
        t0 = time.perf_counter()
        _drive_waves(sim, inflight, w, b"x", results)
        elapsed = time.perf_counter() - t0
        assert len(results) == (warm + w) * inflight, (
            arm, inflight, len(results))
        for role in sim.acceptors + sim.replicas:
            if role.wal is not None:
                role.wal.close()
        if wal_dir is not None:
            shutil.rmtree(wal_dir, ignore_errors=True)
        return w * inflight / elapsed

    table = {}
    for inflight in inflights:
        w = waves or max(8 if inflight >= 1024 else 16, 256 // inflight)
        runs: dict[str, list] = {arm: [] for arm in ARMS}
        ratios: list = []
        for rep in range(reps):
            rot = list(ARMS[rep % 2:]) + list(ARMS[:rep % 2])
            got = {arm: measure(arm, inflight, w) for arm in rot}
            for arm in ARMS:
                runs[arm].append(got[arm])
            ratios.append(got["wal-on"] / got["wal-off"])
        # One instrumented wal-on run for the fsync accounting (not
        # timed against the A/B).
        sim, wal_dir = _make("wal-on", tmp_root)
        results: list = []
        _drive_waves(sim, inflight, w, b"a", results)
        acct = wal_accounting(sim)
        acct["commands"] = len(results)
        if acct["fsyncs"]:
            acct["fsyncs_per_command"] = round(
                acct["fsyncs"] / len(results), 4)
        for role in sim.acceptors + sim.replicas:
            if role.wal is not None:
                role.wal.close()
        if wal_dir is not None:
            shutil.rmtree(wal_dir, ignore_errors=True)
        table[str(inflight)] = {
            "wal_off_cmds_per_sec": round(
                statistics.median(runs["wal-off"]), 1),
            "wal_on_cmds_per_sec": round(
                statistics.median(runs["wal-on"]), 1),
            "wal_on_over_off_ratio": round(statistics.median(ratios), 3),
            "ratio_range": [round(min(ratios), 3), round(max(ratios), 3)],
            "wal_accounting": acct,
        }
    shutil.rmtree(tmp_root, ignore_errors=True)
    return table


def deployed_points(suite, scales, duration_s: float) -> list:
    """Deployed TCP A/B (--wal_dir on vs off), the multipaxos_lt
    deployed_points shape."""
    from frankenpaxos_tpu.bench.multipaxos_suite import (
        MultiPaxosInput,
        run_benchmark,
    )

    points = []
    for arm in ("wal-off", "wal-on"):
        for procs, loops in scales:
            bench = suite.benchmark_directory()
            wal_root = (tempfile.mkdtemp(prefix="fpx_wal_dep_")
                        if arm == "wal-on" else None)
            try:
                stats = run_benchmark(bench, MultiPaxosInput(
                    duration_s=duration_s, num_clients=loops,
                    client_procs=procs, coalesced=True,
                    wal_dir=wal_root))
            except (RuntimeError, subprocess.TimeoutExpired) as e:
                points.append({"arm": arm, "client_procs": procs,
                               "loops_per_proc": loops,
                               "error": str(e)[-300:]})
                continue
            finally:
                if wal_root:
                    shutil.rmtree(wal_root, ignore_errors=True)
            point = {
                "arm": arm,
                "client_procs": procs,
                "loops_per_proc": loops,
                "duration_s": duration_s,
                "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
                "latency_median_ms": stats.get("latency.median_ms"),
                "latency_p99_ms": stats.get("latency.p99_ms"),
                "num_requests": stats.get("num_requests"),
            }
            points.append(point)
            print(json.dumps(point))
    return points


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--scales", type=str, default="1x5,2x10")
    parser.add_argument("--sim_inflight", type=str,
                        default="1,16,256,1024")
    parser.add_argument("--sim_repeats", type=int, default=4)
    parser.add_argument("--sim_ab_batches", type=int, default=3)
    parser.add_argument("--skip_deployed", action="store_true")
    parser.add_argument("--suite_dir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    from frankenpaxos_tpu.bench.deploy_suite import role_process_env
    from frankenpaxos_tpu.bench.harness import SuiteDirectory

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_wlt_")
    suite = SuiteDirectory(root, "wal_lt")
    scales = []
    for part in args.scales.split(","):
        procs, loops = part.lower().split("x")
        scales.append((int(procs), int(loops)))

    points = []
    if not args.skip_deployed:
        points = deployed_points(suite, scales, args.duration)

    import statistics as _stats

    inflights = [int(x) for x in args.sim_inflight.split(",")]
    per_width: dict = {str(i): [] for i in inflights}
    for _batch in range(args.sim_ab_batches):
        ab = subprocess.run(
            [sys.executable, "-c",
             "import json; from frankenpaxos_tpu.bench.wal_lt import "
             "sim_ab_pipeline; "
             f"print(json.dumps(sim_ab_pipeline({inflights!r}, "
             f"reps={args.sim_repeats})))"],
            capture_output=True, text=True, env=role_process_env(),
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        if ab.returncode != 0:
            print(f"sim A/B batch failed (rc={ab.returncode}): "
                  f"{ab.stderr[-500:]}", file=sys.stderr)
            continue
        out = json.loads(ab.stdout.strip().splitlines()[-1])
        print(json.dumps({"sim_ab_batch": out}))
        for key, row in out.items():
            per_width[key].append(row)
    sim_ab = {}
    for key, rows in per_width.items():
        if not rows:
            continue
        ratios = [r["wal_on_over_off_ratio"] for r in rows]
        sim_ab[key] = {
            "wal_on_over_off_ratio": round(_stats.median(ratios), 3),
            "ratio_range": [min(r["ratio_range"][0] for r in rows),
                            max(r["ratio_range"][1] for r in rows)],
            "wal_off_cmds_per_sec_med": round(_stats.median(
                r["wal_off_cmds_per_sec"] for r in rows), 1),
            "wal_on_cmds_per_sec_med": round(_stats.median(
                r["wal_on_cmds_per_sec"] for r in rows), 1),
            "wal_accounting": rows[0]["wal_accounting"],
            "batches": len(rows),
        }

    result = {
        "benchmark": "wal_lt",
        "host_cpus": os.cpu_count(),
        "duration_s": args.duration,
        "deployed_points": points,
        "sim_ab_pipeline": sim_ab,
        "sim_ab_methodology": (
            "per-width ratio = median over independent subprocess "
            "batches of each batch's paired-A/B median (the "
            "multipaxos_lt/mencius_lt sim_ab methodology); arms are "
            "wal-off (reference in-memory) vs wal-on (FileStorage "
            "WALs on every acceptor+replica, ONE group-commit fsync "
            "per event-loop drain, fresh directories per run); "
            "wal_accounting comes from a separate instrumented wal-on "
            "run per width"),
        "note": (
            "Group-commit amortization: per-message durability "
            "overhead (1 - ratio) should SHRINK as drain width grows "
            "because a drain of k messages shares one fsync -- "
            "fsyncs_per_command falls with width while "
            "records_per_drain_sync rises. Deployed points run every "
            "role as its own OS process over localhost TCP with "
            "--wal_dir on vs off."),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
