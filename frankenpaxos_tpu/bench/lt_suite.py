"""Latency-throughput sweep: the real actor framework, dict vs tpu.

The analog of the reference's LT-curve methodology
(benchmarks/multipaxos/multipaxos.py:292-785 + e1_lt_surprise.py):
sweep offered load (client processes x closed loops) over the deployed
multipaxos cluster and record throughput/latency per point, for both
quorum backends:

  * ``dict``  -- host-dict vote tracking in the proxy leader (the
    reference's semantics; CPU-pinned role processes).
  * ``tpu``   -- the proxy leader's Phase2b votes collected on the
    accelerator via TpuQuorumTracker (dense record_block runs + sparse
    scatter tail), one device call per event-loop drain.

Also runs an in-process SimTransport comparison (no TCP, same actor
code) isolating the per-drain tracker cost from network effects.

NOTE on this environment: the TPU is reached through a tunnel with
~10-100ms per device round-trip (see .claude/skills/verify/SKILL.md), so
per-drain device calls carry that RTT on the deployed path; the
committed results record it honestly alongside the device-pipeline
ceiling (bench.py).

Usage::

    python -m frankenpaxos_tpu.bench.lt_suite \
        --out bench_results/multipaxos_lt.json
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

from frankenpaxos_tpu.bench.harness import SuiteDirectory
from frankenpaxos_tpu.bench.multipaxos_suite import (
    MultiPaxosInput,
    run_benchmark,
)


def sim_transport_cmds_per_sec(quorum_backend: str,
                               num_commands: int = 300) -> float:
    """Drive the full actor pipeline over SimTransport (single process,
    no TCP): client -> leader -> proxy leader -> acceptors -> replicas,
    with the chosen quorum backend."""
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from tests.protocols.multipaxos_harness import make_multipaxos

    sim = make_multipaxos(f=1, quorum_backend=quorum_backend)
    results = []
    # Warm up (compiles the device kernels on the tpu backend).
    sim.clients[0].write(0, b"warmup", results.append)
    sim.transport.deliver_all()
    t0 = time.perf_counter()
    for i in range(num_commands):
        sim.clients[0].write(0, b"w%d" % i, results.append)
        sim.transport.deliver_all()
    elapsed = time.perf_counter() - t0
    assert len(results) == num_commands + 1
    return num_commands / elapsed


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--scales", type=str, default="1x5,2x10,4x10",
                        help="comma-separated client_procs x loops points")
    parser.add_argument("--tpu_scales", type=str, default="1x4",
                        help="sweep points to also run with the tpu "
                             "backend (each device drain pays the "
                             "tunnel RTT; keep the load small enough "
                             "that ops complete within it)")
    parser.add_argument("--sim_commands", type=int, default=300)
    parser.add_argument("--suite_dir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    def parse_scales(text):
        out = []
        for part in text.split(","):
            procs, loops = part.lower().split("x")
            out.append((int(procs), int(loops)))
        return out

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_lt_")
    suite = SuiteDirectory(root, "multipaxos_lt")

    points = []
    for backend in ("dict", "tpu"):
        scales = parse_scales(args.scales if backend == "dict"
                              else args.tpu_scales)
        for procs, loops in scales:
            # The tpu point needs a longer window (first drains pay
            # kernel compiles over the device link) + pipelined drains.
            point_duration = (args.duration if backend == "dict"
                              else max(args.duration, 15.0))
            stats = run_benchmark(
                suite.benchmark_directory(),
                MultiPaxosInput(num_clients=loops, client_procs=procs,
                                duration_s=point_duration,
                                quorum_backend=backend,
                                tpu_pipelined=(backend == "tpu")))
            point = {
                "quorum_backend": backend,
                "tpu_pipelined": backend == "tpu",
                "client_procs": procs,
                "loops_per_proc": loops,
                "duration_s": point_duration,
                "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
                "latency_median_ms": stats.get("latency.median_ms"),
                "latency_p99_ms": stats.get("latency.p99_ms"),
                "num_requests": stats["num_requests"],
            }
            points.append(point)
            print(json.dumps(point))

    sim_rows = {
        backend: round(sim_transport_cmds_per_sec(
            backend, args.sim_commands), 1)
        for backend in ("dict", "tpu")}
    # The same tpu-backend actor pipeline against LOCAL XLA (cpu) in a
    # subprocess: separates the per-drain kernel cost from the ~10-100ms
    # accelerator-tunnel RTT of this environment.
    import subprocess
    import sys as _sys

    from frankenpaxos_tpu.bench.deploy_suite import role_process_env

    local = subprocess.run(
        [_sys.executable, "-c",
         "from frankenpaxos_tpu.bench.lt_suite import "
         "sim_transport_cmds_per_sec; "
         f"print(sim_transport_cmds_per_sec('tpu', {args.sim_commands}))"],
        capture_output=True, text=True, env=role_process_env(),
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))))
    if local.returncode == 0:
        sim_rows["tpu_local_xla"] = round(float(
            local.stdout.strip().splitlines()[-1]), 1)
    else:
        print(f"tpu_local_xla measurement failed "
              f"(rc={local.returncode}): {local.stderr[-500:]}",
              file=_sys.stderr)
    print(json.dumps({"sim_transport_cmds_per_sec": sim_rows}))

    result = {
        "benchmark": "multipaxos_lt",
        "host_cpus": os.cpu_count(),
        "duration_s": args.duration,
        "deployed_points": points,
        "sim_transport_cmds_per_sec": sim_rows,
        "note": ("deployed tpu-backend points pay a ~10-100ms "
                 "accelerator-tunnel RTT per proxy-leader drain in this "
                 "environment"
                 + (": tpu_local_xla runs the same actor pipeline "
                    f"against local XLA at "
                    f"{sim_rows['tpu_local_xla']:.0f} cmds/s vs "
                    f"{sim_rows['tpu']:.0f} over the tunnel, so the "
                    "tunnel, not the kernel, dominates the gap"
                    if "tpu_local_xla" in sim_rows else "")
                 + ". Per-message drains cannot amortize a device call; "
                 "bench.py records the device-resident pipeline ceiling "
                 "where drains are block-granular."),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
