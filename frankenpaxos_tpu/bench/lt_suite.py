"""Latency-throughput sweep: the real actor framework, dict vs tpu.

The analog of the reference's LT-curve methodology
(benchmarks/multipaxos/multipaxos.py:292-785 + e1_lt_surprise.py):
sweep offered load (client processes x closed loops) over the deployed
multipaxos cluster and record throughput/latency per point, for both
quorum backends:

  * ``dict``  -- host-dict vote tracking in the proxy leader (the
    reference's semantics; CPU-pinned role processes).
  * ``tpu``   -- the proxy leader's Phase2b votes collected on the
    accelerator via TpuQuorumTracker (dense record_block runs + sparse
    scatter tail), one device call per event-loop drain.

Also runs an in-process SimTransport comparison (no TCP, same actor
code) isolating the per-drain tracker cost from network effects.

NOTE on this environment: the TPU is reached through a tunnel with
~10-100ms per device round-trip (see .claude/skills/verify/SKILL.md), so
per-drain device calls carry that RTT on the deployed path; the
committed results record it honestly alongside the device-pipeline
ceiling (bench.py).

Usage::

    python -m frankenpaxos_tpu.bench.lt_suite \
        --out bench_results/multipaxos_lt.json
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

from frankenpaxos_tpu.bench.harness import SuiteDirectory
from frankenpaxos_tpu.bench.multipaxos_suite import (
    MultiPaxosInput,
    run_benchmark,
)


def sim_transport_cmds_per_sec(quorum_backend: str,
                               num_commands: int = 300,
                               inflight: int = 1) -> float:
    """Drive the full actor pipeline over SimTransport (single process,
    no TCP): client -> leader -> proxy leader -> acceptors -> replicas,
    with the chosen quorum backend.

    ``inflight`` closed loops (client pseudonyms) issue concurrently and
    messages deliver in coalesced waves -- the real event loop's drain
    granularity (TcpTransport defers on_drain to the end of a loop
    pass), so a proxy leader drain carries ~inflight * (f+1) votes. At
    inflight=1 this degenerates to the serial one-command-per-drain
    workload, the device path's worst case.

    Both backends run with jax initialized and a warm XLA client:
    merely having the XLA runtime resident (its thread pool + heap)
    costs the whole actor pipeline ~10% on a single-CPU host, measured
    identically for a dict-backend run with an idle checker. Holding
    that state constant isolates what this sweep is after: the
    incremental cost of HOW votes are tracked, dict ops vs device
    kernels."""
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    import numpy as _np

    from frankenpaxos_tpu.ops.quorum import TpuQuorumChecker
    from frankenpaxos_tpu.quorums import SimpleMajority

    warm_checker = TpuQuorumChecker(
        SimpleMajority(range(6)).write_spec(), window=1 << 12)
    warm_block = _np.zeros((6, 64), dtype=_np.uint8)
    warm_block[0, 0] = 1
    warm_checker.record_block(0, warm_block)

    from tests.protocols.multipaxos_harness import make_multipaxos

    sim = make_multipaxos(f=1, quorum_backend=quorum_backend)
    results = []
    # Warm up (compiles the device kernels on the tpu backend).
    sim.clients[0].write(0, b"warmup", results.append)
    sim.transport.deliver_all_coalesced()
    assert len(results) == 1
    batches = max(1, num_commands // inflight)
    t0 = time.perf_counter()
    _drive_waves(sim, inflight, batches, b"w", results)
    elapsed = time.perf_counter() - t0
    assert len(results) == batches * inflight + 1
    return batches * inflight / elapsed


def _drive_waves(sim, inflight: int, waves: int, tag: bytes,
                 results: list) -> None:
    """Issue ``waves`` closed-loop waves of ``inflight`` writes each and
    deliver them in coalesced waves (the real event loop's drain
    granularity). Shared by every sim-pipeline benchmark here so the
    driving protocol cannot drift between them. ``flush_writes`` ships
    a coalescing client's staged array (no-op otherwise), standing in
    for the real event loop's end-of-pass flush."""
    for b in range(waves):
        for p in range(inflight):
            sim.clients[0].write(p, b"%s%d.%d" % (tag, b, p),
                                 results.append)
        sim.clients[0].flush_writes()
        sim.transport.deliver_all_coalesced()


def sim_ab_pipeline(inflights, reps: int = 6, waves: int = 0,
                    warm: int = 4) -> dict:
    """Interleaved A/B/C of the full SimTransport actor pipeline in ONE
    process with XLA resident throughout:

      * ``dict``     -- the reference design: per-message Python
        (ClientRequest/Phase2a/Phase2b/Chosen per slot), host-dict vote
        tracking. The baseline.
      * ``tpu``      -- the tpu-first design: the drain-granular run
        pipeline (ClientRequestArray -> Phase2aRun -> Phase2bRange ->
        ChosenRun -> ClientReplyArray; per-message Python scales with
        drains, not commands) with the device-backed quorum tracker.
      * ``dict+run`` -- ablation: the same run pipeline over the
        host-dict tracker, isolating how much of tpu-vs-dict comes
        from drain-granular message structure vs device vote tracking.

    Per in-flight width: ``reps`` triples of runs with rotating order,
    each yielding per-pair ratios; the MEDIAN of paired ratios is
    robust to the two confounds that made cross-process comparisons
    jitter +-30% on this 1-CPU host: process-to-process variance and
    the monotonic in-process slowdown drift."""
    import gc
    import statistics

    from tests.protocols.multipaxos_harness import make_multipaxos

    ARMS = {
        "dict": dict(quorum_backend="dict", coalesced=False),
        "tpu": dict(quorum_backend="tpu", coalesced=True),
        "dict+run": dict(quorum_backend="dict", coalesced=True),
    }

    def measure(arm: str, inflight: int, w: int) -> float:
        gc.collect()
        sim = make_multipaxos(f=1, **ARMS[arm])
        results = []
        sim.clients[0].write(0, b"warmup", results.append)
        sim.clients[0].flush_writes()
        sim.transport.deliver_all_coalesced()
        _drive_waves(sim, inflight, warm, b"w", results)
        t0 = time.perf_counter()
        _drive_waves(sim, inflight, w, b"x", results)
        elapsed = time.perf_counter() - t0
        assert len(results) == 1 + (warm + w) * inflight
        return w * inflight / elapsed

    measure("tpu", 16, 4)  # XLA + tracker kernels resident before timing
    order = ["dict", "tpu", "dict+run"]
    table = {}
    for inflight in inflights:
        # Enough waves that per-run noise stays small at narrow
        # widths; wide widths carry plenty of commands per wave, so
        # fewer waves keep a run to seconds.
        w = waves or max(12 if inflight >= 2048 else 24,
                         2048 // inflight)
        runs: dict[str, list] = {arm: [] for arm in ARMS}
        ratios: dict[str, list] = {"tpu_over_dict": [],
                                   "run_over_dict": [],
                                   "tpu_over_run": []}
        for rep in range(reps):
            rot = order[rep % 3:] + order[:rep % 3]
            got = {arm: measure(arm, inflight, w) for arm in rot}
            for arm in ARMS:
                runs[arm].append(got[arm])
            ratios["tpu_over_dict"].append(got["tpu"] / got["dict"])
            ratios["run_over_dict"].append(got["dict+run"] / got["dict"])
            ratios["tpu_over_run"].append(got["tpu"] / got["dict+run"])
        table[str(inflight)] = {
            "dict_cmds_per_sec": round(statistics.median(runs["dict"]), 1),
            "tpu_cmds_per_sec": round(statistics.median(runs["tpu"]), 1),
            "dict_run_cmds_per_sec": round(
                statistics.median(runs["dict+run"]), 1),
            "tpu_over_dict_ratio": round(
                statistics.median(ratios["tpu_over_dict"]), 3),
            "run_over_dict_ratio": round(
                statistics.median(ratios["run_over_dict"]), 3),
            "tpu_over_run_ratio": round(
                statistics.median(ratios["tpu_over_run"]), 3),
        }
    return table


def tracker_votes_per_sec(quorum_backend: str, drain_width: int,
                          num_votes: int = 200_000,
                          ranged: bool = False) -> float:
    """Replay an identical synthetic steady-state Phase2b stream into
    one QuorumTracker: contiguous slot runs of ``drain_width`` slots,
    2f+1 votes per slot, one drain per run -- the ProxyLeader hot loop
    (ProxyLeader.scala:217-258) with the actor pipeline stripped away.

    ``ranged=False`` delivers per-slot votes (the reference's Phase2b
    shape); ``ranged=True`` delivers one Phase2bRange per acceptor per
    drain (the framework's batched-ack shape) -- O(1) Python into the
    device tracker, per-slot expansion in the dict oracle.

    This isolates the exact component the backends differ in: per-vote
    dict/set updates vs batched recording + one device call per
    drain."""
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from frankenpaxos_tpu.protocols.multipaxos.quorum_tracker import (
        DictQuorumTracker,
        TpuQuorumTracker,
    )
    from tests.protocols.multipaxos_harness import make_multipaxos

    config = make_multipaxos(f=1).config
    if quorum_backend == "tpu":
        # min_device_slots=1: the replay isolates the DEVICE tracker
        # component (the auto threshold would route narrow replays to
        # the host tally, measuring the oracle twice).
        tracker = TpuQuorumTracker(config, window=1 << 14,
                                   min_device_slots=1)
    else:
        tracker = DictQuorumTracker(config)
    acceptors = 2 * config.f + 1
    drains = max(1, num_votes // (drain_width * acceptors))
    # Warm one drain (compiles nothing new; buckets prewarm at init).
    base = 0
    for slot in range(base, base + drain_width):
        for acc in range(acceptors):
            tracker.record(slot, 0, 0, acc)
    tracker.drain()
    base += drain_width
    chosen = 0
    t0 = time.perf_counter()
    if ranged:
        for _ in range(drains):
            for acc in range(acceptors):
                tracker.record_range(base, base + drain_width, 0, 0, acc)
            chosen += len(tracker.drain())
            base += drain_width
    else:
        for _ in range(drains):
            record = tracker.record
            for slot in range(base, base + drain_width):
                for acc in range(acceptors):
                    record(slot, 0, 0, acc)
            chosen += len(tracker.drain())
            base += drain_width
    elapsed = time.perf_counter() - t0
    assert chosen == drains * drain_width, (chosen, drains, drain_width)
    return drains * drain_width * acceptors / elapsed


def _overlap_metrics(role_metrics: dict) -> dict:
    """Aggregate the proxy leaders' pipelined-dispatch instrumentation
    (scraped /metrics) into the overlap summary the deployed
    tpu-pipelined point carries: how deep the in-flight dispatch queue
    runs (0 = the link RTT is serialized per drain, i.e. pipelining is
    NOT engaging) and what each device collect costs."""
    sums = {"dispatches": 0.0, "inflight_sum": 0.0, "inflight_count": 0.0,
            "collect_sum_s": 0.0, "collect_count": 0.0}
    p = "multipaxos_proxy_leader_tpu_"
    for label, metrics in role_metrics.items():
        if not label.startswith("proxy_leader"):
            continue
        sums["dispatches"] += metrics.get(f"{p}dispatches_total", 0.0)
        sums["inflight_sum"] += metrics.get(
            f"{p}inflight_at_dispatch_sum", 0.0)
        sums["inflight_count"] += metrics.get(
            f"{p}inflight_at_dispatch_count", 0.0)
        sums["collect_sum_s"] += metrics.get(
            f"{p}collect_seconds_sum", 0.0)
        sums["collect_count"] += metrics.get(
            f"{p}collect_seconds_count", 0.0)
    return {
        "dispatches": sums["dispatches"],
        "mean_inflight_at_dispatch": round(
            sums["inflight_sum"] / sums["inflight_count"], 3)
        if sums["inflight_count"] else None,
        "collects": sums["collect_count"],
        "mean_collect_ms": round(
            1e3 * sums["collect_sum_s"] / sums["collect_count"], 1)
        if sums["collect_count"] else None,
    }


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--scales", type=str, default="1x5,2x10,4x10",
                        help="comma-separated client_procs x loops points")
    parser.add_argument("--tpu_scales", type=str, default="1x4",
                        help="sweep points to also run with the tpu "
                             "backend (each device drain pays the "
                             "tunnel RTT; keep the load small enough "
                             "that ops complete within it)")
    parser.add_argument("--sim_commands", type=int, default=300)
    parser.add_argument("--sim_inflight", type=str,
                        default="1,256,1024,4096",
                        help="in-flight widths for the coalesced-wave "
                             "sim batch sweep (both backends, local XLA)")
    parser.add_argument("--sim_repeats", type=int, default=4,
                        help="A/B pairs per width per batch (and runs "
                             "per tracker-sweep point)")
    parser.add_argument("--sim_ab_batches", type=int, default=3,
                        help="independent subprocess batches pooled "
                             "for the sim A/B (process-scoped bias)")
    parser.add_argument("--tracker_widths", type=str,
                        default="16,64,256,1024,4096,8192",
                        help="drain widths for the tracker-only replay "
                             "sweep")
    parser.add_argument("--suite_dir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    def parse_scales(text):
        out = []
        for part in text.split(","):
            procs, loops = part.lower().split("x")
            out.append((int(procs), int(loops)))
        return out

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_lt_")
    suite = SuiteDirectory(root, "multipaxos_lt")

    # Deployed arms. The dict arm is the reference design; dict+run is
    # the drain-granular pipeline on the host tracker; tpu+run is the
    # sensible device config (sync adaptive routing: trickle drains
    # never pay the device-link RTT); tpu-pipelined is the
    # board-always mode, instrumented (prometheus) to measure dispatch
    # overlap -- the round-4 open question of WHY it deployed at 7/s.
    arms = [
        ("dict", dict()),
        ("dict+run", dict(coalesced=True)),
        ("tpu+run", dict(quorum_backend="tpu", coalesced=True)),
        ("tpu-pipelined", dict(quorum_backend="tpu", tpu_pipelined=True,
                               prometheus=True)),
    ]
    # Probe the accelerator BEFORE the tpu arms: a wedged device link
    # (observed: jax.devices() itself hanging on the axon tunnel) must
    # degrade this artifact to its dict arms, not hang the whole run.
    import subprocess
    import sys as _sys

    from frankenpaxos_tpu.bench.device_probe import device_probe

    tpu_available, tpu_probe_note = device_probe()
    if not tpu_available:
        print(json.dumps({"tpu_probe": tpu_probe_note,
                          "tpu_arms": "skipped"}))

    points = []
    for arm, kwargs in arms:
        backend = kwargs.get("quorum_backend", "dict")
        if backend == "tpu" and not tpu_available:
            points.append({"arm": arm, "skipped":
                           f"device unavailable: {tpu_probe_note}"})
            continue
        scales = parse_scales(args.scales if backend == "dict"
                              else args.tpu_scales)
        for procs, loops in scales:
            # The tpu arms need a longer window (first drains pay
            # kernel compiles over the device link).
            point_duration = (args.duration if backend == "dict"
                              else max(args.duration, 15.0))
            try:
                stats = run_benchmark(
                    suite.benchmark_directory(),
                    MultiPaxosInput(num_clients=loops,
                                    client_procs=procs,
                                    duration_s=point_duration,
                                    **kwargs))
            except RuntimeError as e:
                print(json.dumps({"arm": arm, "error": str(e)[-300:]}))
                continue
            point = {
                "arm": arm,
                "quorum_backend": backend,
                "tpu_pipelined": bool(kwargs.get("tpu_pipelined")),
                "coalesced": bool(kwargs.get("coalesced")),
                "client_procs": procs,
                "loops_per_proc": loops,
                "duration_s": point_duration,
                "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
                "latency_median_ms": stats.get("latency.median_ms"),
                "latency_p99_ms": stats.get("latency.p99_ms"),
                "num_requests": stats["num_requests"],
            }
            if kwargs.get("tpu_pipelined"):
                point["overlap_metrics"] = _overlap_metrics(
                    stats.get("role_metrics") or {})
            points.append(point)
            print(json.dumps(point))

    # Sim-pipeline comparison: the interleaved paired A/B
    # (sim_ab_pipeline) pooled over INDEPENDENT subprocesses. Pairing
    # inside one process cancels drift within a batch, but batches
    # carry a +-5-8% process-scoped bias (thread placement, CPU
    # state); the per-width ratio is the median over all batches'
    # pair medians, with the range recorded.
    import statistics as _stats

    from frankenpaxos_tpu.bench.deploy_suite import role_process_env

    inflights = [int(x) for x in args.sim_inflight.split(",")]
    per_width: dict = {str(i): [] for i in inflights}
    for _batch in range(args.sim_ab_batches):
        ab = subprocess.run(
            [_sys.executable, "-c",
             "import json; from frankenpaxos_tpu.bench.lt_suite import "
             "sim_ab_pipeline; "
             f"print(json.dumps(sim_ab_pipeline({inflights!r}, "
             f"reps={args.sim_repeats})))"],
            capture_output=True, text=True, env=role_process_env(),
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        if ab.returncode != 0:
            print(f"sim A/B batch failed (rc={ab.returncode}): "
                  f"{ab.stderr[-500:]}", file=_sys.stderr)
            continue
        out = json.loads(ab.stdout.strip().splitlines()[-1])
        print(json.dumps({"sim_ab_batch": out}))
        for key, row in out.items():
            per_width[key].append(row)
    sim_ab = {}
    for key, rows in per_width.items():
        if not rows:
            continue
        ratios = [r["tpu_over_dict_ratio"] for r in rows]
        run_ratios = [r["run_over_dict_ratio"] for r in rows]
        tpu_run_ratios = [r["tpu_over_run_ratio"] for r in rows]
        sim_ab[key] = {
            "tpu_over_dict_ratio": round(_stats.median(ratios), 3),
            "ratio_range": [min(ratios), max(ratios)],
            "run_over_dict_ratio": round(_stats.median(run_ratios), 3),
            "run_over_dict_range": [min(run_ratios), max(run_ratios)],
            "tpu_over_run_ratio": round(
                _stats.median(tpu_run_ratios), 3),
            "batches": len(rows),
            "dict_cmds_per_sec_med": round(_stats.median(
                r["dict_cmds_per_sec"] for r in rows), 1),
            "tpu_cmds_per_sec_med": round(_stats.median(
                r["tpu_cmds_per_sec"] for r in rows), 1),
            "dict_run_cmds_per_sec_med": round(_stats.median(
                r["dict_run_cmds_per_sec"] for r in rows), 1),
        }
    crossover = next((i for i in inflights
                      if sim_ab.get(str(i), {})
                      .get("tpu_over_dict_ratio", 0) >= 1.0), None)
    print(json.dumps({"sim_ab_pipeline": sim_ab,
                      "crossover_inflight": crossover}))

    # Tunnel control: the same pipeline in THIS process, where the
    # accelerator sits across the axon tunnel. The adaptive
    # host/device threshold routes trickle drains to the host tally,
    # so even the serial workload no longer pays per-drain tunnel RTTs.
    # Guarded by the SAME device probe as the deployed arms: this
    # section initializes the axon backend in-process, which hangs
    # indefinitely on a wedged link.
    if tpu_available:
        sim_rows = {
            backend: round(sim_transport_cmds_per_sec(
                backend, args.sim_commands), 1)
            for backend in ("dict", "tpu")}
    else:
        sim_rows = {"skipped": tpu_probe_note}
    print(json.dumps({"sim_tunnel_cmds_per_sec": sim_rows}))

    import statistics

    def subprocess_sweep(fn_name: str, points: dict, digits: int) -> dict:
        """{backend: {point_label: call_args}} -> median cmds/s table."""
        table = {}
        for backend, by_label in points.items():
            table[backend] = {}
            for label, call_args in by_label.items():
                samples = []
                for _ in range(args.sim_repeats):
                    run = subprocess.run(
                        [_sys.executable, "-c",
                         f"from frankenpaxos_tpu.bench.lt_suite import "
                         f"{fn_name}; print({fn_name}({call_args}))"],
                        capture_output=True, text=True,
                        env=role_process_env(),
                        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                            os.path.abspath(__file__)))))
                    if run.returncode == 0:
                        samples.append(float(
                            run.stdout.strip().splitlines()[-1]))
                    else:
                        print(f"{fn_name} point ({backend}, {label}) "
                              f"failed (rc={run.returncode}): "
                              f"{run.stderr[-500:]}", file=_sys.stderr)
                if samples:
                    table[backend][label] = round(
                        statistics.median(samples), digits) if digits \
                        else round(statistics.median(samples))
        return table

    def first_crossover(table: dict, labels) -> "int | None":
        return next(
            (x for x in labels
             if table.get("tpu", {}).get(str(x), 0)
             >= table.get("dict", {}).get(str(x), float("inf"))), None)

    # Tracker replay: the ProxyLeader vote-collection component alone
    # (no actor pipeline), identical synthetic Phase2b streams, drain
    # width swept. This is where the dict-vs-device crossover is
    # measured directly.
    widths = [int(x) for x in args.tracker_widths.split(",")]
    tracker = subprocess_sweep("tracker_votes_per_sec", {
        backend: {str(w): f"{backend!r}, {w}" for w in widths}
        for backend in ("dict", "tpu")}, digits=0)
    tracker_crossover = first_crossover(tracker, widths)
    print(json.dumps({"tracker_votes_per_sec": tracker,
                      "tracker_crossover_width": tracker_crossover}))

    # The same replay with RANGED acks (Phase2bRange, the acceptors'
    # batched steady-state shape): O(1) Python per ranged message into
    # the device tracker vs per-slot expansion in the dict oracle --
    # the regime where the device path structurally wins.
    tracker_ranged = subprocess_sweep("tracker_votes_per_sec", {
        backend: {str(w): f"{backend!r}, {w}, ranged=True"
                  for w in widths}
        for backend in ("dict", "tpu")}, digits=0)
    ranged_crossover = first_crossover(tracker_ranged, widths)
    print(json.dumps({
        "tracker_ranged_votes_per_sec": tracker_ranged,
        "tracker_ranged_crossover_width": ranged_crossover}))

    result = {
        "benchmark": "multipaxos_lt",
        "host_cpus": os.cpu_count(),
        "duration_s": args.duration,
        "tpu_available": tpu_available,
        "tpu_probe": tpu_probe_note,
        "deployed_points": points,
        "sim_ab_pipeline": sim_ab,
        "crossover_inflight": crossover,
        "sim_tunnel_cmds_per_sec": sim_rows,
        "tracker_votes_per_sec": tracker,
        "tracker_crossover_width": tracker_crossover,
        "tracker_ranged_votes_per_sec": tracker_ranged,
        "tracker_ranged_crossover_width": ranged_crossover,
        "sim_ab_methodology": (
            "per-width ratio = median over independent subprocess "
            "batches of each batch's paired-A/B median; ranges "
            "recorded"),
        "note": ("sim_ab_pipeline: full actor pipeline over "
                 "SimTransport, interleaved paired A/B/C medians "
                 "(local XLA). 'dict' is the reference design "
                 "(per-message Python, host-dict vote tracking); "
                 "'tpu' is the tpu-first drain-granular run pipeline "
                 "(ClientRequestArray -> Phase2aRun -> Phase2bRange "
                 "-> ChosenRun -> ClientReplyArray: per-message "
                 "Python scales with event-loop drains, not "
                 "commands; lazy value arrays mean forwarding roles "
                 "never materialize Command objects) over the "
                 "device-backed tracker; run_over_dict_ratio is the "
                 "dict-tracker ablation of the same run pipeline, "
                 "isolating message-structure wins from vote-"
                 "tracking wins. The tpu tracker routes adaptively: "
                 "trickle drains to a host tally, wide drains to ONE "
                 "stateless quorum matmul per drain. On this 1-CPU "
                 "host each local-XLA device call taxes the "
                 "surrounding pipeline ~2-4ms, so the auto threshold "
                 "engages the device at ~1k-slot drains; on real TPU "
                 "hardware the threshold is 96. "
                 "tracker_votes_per_sec isolates the ProxyLeader "
                 "vote-collection component with the device path "
                 "pinned on: per-slot replays cross over at ~1k-slot "
                 "drains, RANGED ack replays win from 256 up "
                 "(measured up to ~7x at 4096). Deployed tpu points "
                 "run pipelined drains over the axon tunnel "
                 "(~10-100ms RTT, hidden behind the event loop but "
                 "bounding choose latency)."),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
