"""Mencius latency-throughput A/B: per-message vs the coalesced run
pipeline.

The multipaxos_lt methodology applied to the partitioned log: the SAME
actor code runs in two arms --

  * ``per-message`` -- the reference design: one ClientRequest ->
    Phase2a -> Phase2b -> Chosen per command (mencius/Leader.scala:
    331-408's per-slot processClientRequestBatch).
  * ``coalesced``   -- the drain-granular run pipeline: one
    ClientRequestArray per event-loop pass, one strided Phase2aRun per
    drain (carrying the owner's slot stride), one Phase2bRun ack per
    acceptor, one ChosenRun per replica, one ClientReplyArray per
    client. Per-message Python scales with drains, not commands.

Two measurements:

  * deployed TCP points (every role its own OS process, closed loops
    from client processes through the registry's drive entry) at small
    in-flight widths -- the multipaxos_lt "deployed_points" shape.
  * the interleaved paired SimTransport A/B at batch widths up to 4096
    in-flight (the multipaxos_lt ``sim_ab_pipeline`` shape): per width,
    ``reps`` pairs of runs with rotating order, the MEDIAN of paired
    ratios -- robust to process variance and in-process drift on a
    1-CPU host. This is where the "coalesced >= 1.5x per-message at
    batch >= 1024" acceptance figure comes from.

Usage::

    python -m frankenpaxos_tpu.bench.mencius_lt \
        --out bench_results/mencius_lt.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time


def _drive_waves(sim, inflight: int, waves: int, tag: bytes,
                 results: list) -> None:
    """Issue ``waves`` closed-loop waves of ``inflight`` writes each and
    deliver them in coalesced waves (the real event loop's drain
    granularity); pump recover timers between waves so noop-skip holes
    (slots owned by idle leader groups) never stall a wave."""
    for b in range(waves):
        for p in range(inflight):
            sim.clients[0].write(p, b"%s%d.%d" % (tag, b, p),
                                 results.append)
        sim.clients[0].flush_writes()
        sim.transport.deliver_all_coalesced()
        for _ in range(60):
            if not sim.clients[0].states:  # every pseudonym resolved
                break
            for timer in sim.transport.running_timers():
                if timer.name == "recover" \
                        or timer.name.startswith("resendWrite"):
                    sim.transport.trigger_timer(timer.id)
            sim.transport.deliver_all_coalesced()


def sim_ab_pipeline(inflights, reps: int = 6, waves: int = 0,
                    warm: int = 2) -> dict:
    """Interleaved paired A/B of the full Mencius actor pipeline over
    SimTransport in ONE process (multipaxos_lt.sim_ab_pipeline's
    methodology): per in-flight width, ``reps`` pairs with rotating
    order; the per-width ratio is the median of paired ratios."""
    import gc
    import statistics

    from tests.protocols.mencius_harness import make_mencius

    ARMS = {
        "per-message": dict(coalesced=False),
        "coalesced": dict(coalesced=True),
    }

    def measure(arm: str, inflight: int, w: int) -> float:
        gc.collect()
        sim = make_mencius(f=1, num_leader_groups=2, lag_threshold=1,
                           **ARMS[arm])
        results: list = []
        sim.clients[0].write(0, b"warmup", results.append)
        sim.clients[0].flush_writes()
        sim.transport.deliver_all_coalesced()
        for _ in range(50):
            if results:
                break
            for timer in sim.transport.running_timers():
                if timer.name == "recover":
                    sim.transport.trigger_timer(timer.id)
            sim.transport.deliver_all_coalesced()
        assert results, "warmup write never committed"
        _drive_waves(sim, inflight, warm, b"w", results)
        t0 = time.perf_counter()
        _drive_waves(sim, inflight, w, b"x", results)
        elapsed = time.perf_counter() - t0
        assert len(results) == 1 + (warm + w) * inflight, (
            arm, inflight, len(results))
        return w * inflight / elapsed

    order = ["per-message", "coalesced"]
    table = {}
    for inflight in inflights:
        w = waves or max(8 if inflight >= 2048 else 16, 512 // inflight)
        runs: dict[str, list] = {arm: [] for arm in ARMS}
        ratios: list = []
        for rep in range(reps):
            rot = order[rep % 2:] + order[:rep % 2]
            got = {arm: measure(arm, inflight, w) for arm in rot}
            for arm in ARMS:
                runs[arm].append(got[arm])
            ratios.append(got["coalesced"] / got["per-message"])
        table[str(inflight)] = {
            "per_message_cmds_per_sec": round(
                statistics.median(runs["per-message"]), 1),
            "coalesced_cmds_per_sec": round(
                statistics.median(runs["coalesced"]), 1),
            "coalesced_over_per_message_ratio": round(
                statistics.median(ratios), 3),
            "ratio_range": [round(min(ratios), 3), round(max(ratios), 3)],
        }
    return table


def deployed_points(suite, arms, scales, duration_s: float) -> list:
    """Deployed TCP A/B: launch the mencius cluster (one OS process per
    role), drive closed loops from client processes through the
    registry drive entry, per-message vs coalesced clients."""
    from frankenpaxos_tpu.bench.deploy_suite import (
        launch_roles,
        role_process_env,
    )
    from frankenpaxos_tpu.bench.harness import (
        LocalHost,
        free_port,
        latency_throughput_stats,
    )
    from frankenpaxos_tpu.deploy import get_protocol

    points = []
    for arm, client_options in arms:
        for procs, loops in scales:
            bench = suite.benchmark_directory()
            try:
                protocol = get_protocol("mencius")
                raw = protocol.cluster(1, lambda: ["127.0.0.1",
                                                   free_port()])
                config_path = bench.write_json("config.json", raw)
                config = protocol.load_config(raw)
                launch_roles(
                    bench, "mencius", config_path, config,
                    state_machine="AppendLog",
                    overrides={"resend_phase1as_period_s": "0.5",
                               # Idle groups must skip promptly (the
                               # protocol_suite LT settings).
                               "send_high_watermark_every_n": "1",
                               "send_noop_range_if_lagging_by": "1"})
                host = LocalHost()
                env = role_process_env()
                client_procs = []
                for i in range(procs):
                    out_csv = bench.abspath(f"client_{i}_data.csv")
                    client_procs.append((out_csv, bench.popen(
                        host, f"client_{i}",
                        [sys.executable, "-m",
                         "frankenpaxos_tpu.bench.client_main",
                         "--protocol", "mencius",
                         "--config", config_path,
                         "--num_clients", str(loops),
                         "--duration", str(duration_s),
                         "--seed", str(i + 1), "--out", out_csv]
                        + (["--client_options",
                            json.dumps(client_options)]
                           if client_options else []), env=env)))
                latencies, starts = [], []
                for out_csv, proc in client_procs:
                    code = proc.wait(timeout=duration_s + 90)
                    if code != 0:
                        raise RuntimeError(
                            f"client process exited {code}; see "
                            f"{bench.path}")
                    with open(out_csv) as f_csv:
                        next(f_csv)
                        for line in f_csv:
                            _, start, latency = line.strip().split(",")
                            latencies.append(float(latency))
                            starts.append(float(start))
            except (RuntimeError, subprocess.TimeoutExpired) as e:
                # A wedged client process (TimeoutExpired from
                # proc.wait) is one bad point, not a reason to abort
                # every remaining arm and the sim sweep.
                points.append({"arm": arm, "client_procs": procs,
                               "loops_per_proc": loops,
                               "error": str(e)[-300:]})
                continue
            finally:
                bench.cleanup()
            stats = latency_throughput_stats(latencies, duration_s,
                                             starts_s=starts)
            point = {
                "arm": arm,
                "coalesced": bool(client_options),
                "client_procs": procs,
                "loops_per_proc": loops,
                "duration_s": duration_s,
                "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
                "latency_median_ms": stats.get("latency.median_ms"),
                "latency_p99_ms": stats.get("latency.p99_ms"),
                "num_requests": stats["num_requests"],
            }
            points.append(point)
            print(json.dumps(point))
    return points


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--scales", type=str, default="1x5,2x10",
                        help="deployed client_procs x loops points")
    parser.add_argument("--sim_inflight", type=str,
                        default="1,256,1024,4096",
                        help="in-flight widths for the paired sim A/B")
    parser.add_argument("--sim_repeats", type=int, default=4,
                        help="A/B pairs per width per batch")
    parser.add_argument("--sim_ab_batches", type=int, default=3,
                        help="independent subprocess batches pooled "
                             "(process-scoped bias)")
    parser.add_argument("--skip_deployed", action="store_true")
    parser.add_argument("--suite_dir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    from frankenpaxos_tpu.bench.deploy_suite import role_process_env
    from frankenpaxos_tpu.bench.harness import SuiteDirectory

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_mlt_")
    suite = SuiteDirectory(root, "mencius_lt")

    scales = []
    for part in args.scales.split(","):
        procs, loops = part.lower().split("x")
        scales.append((int(procs), int(loops)))

    points = []
    if not args.skip_deployed:
        points = deployed_points(
            suite,
            [("per-message", None),
             ("coalesced", {"coalesce_writes": "true"})],
            scales, args.duration)

    # Paired sim A/B pooled over independent subprocesses (the
    # multipaxos_lt sim_ab methodology).
    import statistics as _stats

    inflights = [int(x) for x in args.sim_inflight.split(",")]
    per_width: dict = {str(i): [] for i in inflights}
    for _batch in range(args.sim_ab_batches):
        ab = subprocess.run(
            [sys.executable, "-c",
             "import json; from frankenpaxos_tpu.bench.mencius_lt import "
             "sim_ab_pipeline; "
             f"print(json.dumps(sim_ab_pipeline({inflights!r}, "
             f"reps={args.sim_repeats})))"],
            capture_output=True, text=True, env=role_process_env(),
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        if ab.returncode != 0:
            print(f"sim A/B batch failed (rc={ab.returncode}): "
                  f"{ab.stderr[-500:]}", file=sys.stderr)
            continue
        out = json.loads(ab.stdout.strip().splitlines()[-1])
        print(json.dumps({"sim_ab_batch": out}))
        for key, row in out.items():
            per_width[key].append(row)
    sim_ab = {}
    for key, rows in per_width.items():
        if not rows:
            continue
        ratios = [r["coalesced_over_per_message_ratio"] for r in rows]
        sim_ab[key] = {
            "coalesced_over_per_message_ratio": round(
                _stats.median(ratios), 3),
            "ratio_range": [min(r["ratio_range"][0] for r in rows),
                            max(r["ratio_range"][1] for r in rows)],
            "per_message_cmds_per_sec_med": round(_stats.median(
                r["per_message_cmds_per_sec"] for r in rows), 1),
            "coalesced_cmds_per_sec_med": round(_stats.median(
                r["coalesced_cmds_per_sec"] for r in rows), 1),
            "batches": len(rows),
        }
    crossover = next((i for i in inflights
                      if sim_ab.get(str(i), {})
                      .get("coalesced_over_per_message_ratio", 0)
                      >= 1.0), None)

    result = {
        "benchmark": "mencius_lt",
        "host_cpus": os.cpu_count(),
        "duration_s": args.duration,
        "deployed_points": points,
        "sim_ab_pipeline": sim_ab,
        "crossover_inflight": crossover,
        "sim_ab_methodology": (
            "per-width ratio = median over independent subprocess "
            "batches of each batch's paired-A/B median (the "
            "multipaxos_lt sim_ab methodology); ranges recorded"),
        "note": ("per-message is the reference Mencius shape (one "
                 "ClientRequest/Phase2a/Phase2b/Chosen per command); "
                 "coalesced is the drain-granular strided run pipeline "
                 "(ClientRequestArray -> Phase2aRun -> Phase2bRun -> "
                 "ChosenRun -> ClientReplyArray, runs carrying the "
                 "owner's slot stride so idle groups' slots coalesce "
                 "into Phase2aNoopRange skip ranges). Deployed points "
                 "run every role as its own OS process over localhost "
                 "TCP at small in-flight widths; the sim A/B sweeps "
                 "batch widths to 4096 in one process with paired "
                 "interleaved runs."),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
