"""paxload overload A/B: goodput/p99/p999 vs offered load, 1x-20x.

THE GATE (ISSUE 6): at 10x measured capacity, with admission ON,

  * goodput (commands completing within the SLO deadline) stays
    >= 70% of the 1x peak,
  * admitted-request p99 stays <= 5x the 1x-load p99,
  * no unbounded queue growth (max queue depth across the run stays
    within a constant factor of the 1x depth),

and the paired no-admission BASELINE arm violates the gate -- the
degrade-by-shedding vs degrade-by-collapse A/B "The Performance of
Paxos in the Cloud" (PAPERS.md) motivates.

Model: the serve/loadgen.py virtual-time service model over the
coalesced multipaxos SimTransport pipeline -- 1M-session SoA open-loop
arrivals (the SHARED bench/workload.OpenLoopWorkload), a CPU budget of
one virtual second per virtual second (1/capacity per completed
command + a per-message cost), timers on virtual deadlines. Fully
deterministic per seed.

Also records ``admission_overhead``: the trace_overhead-style paired
A/B proving the DISABLED admission hooks (transport ``is None`` tests
+ the leader's _admit early-outs) cost <3% -- every deployment pays
the disabled path.

Usage::

    python -m frankenpaxos_tpu.bench.overload_lt \
        --out bench_results/overload_lt.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import time

#: The virtual service model (loadgen.SimOverloadDriver): cluster
#: capacity in commands/virtual-second and the per-message CPU cost.
CAPACITY_CMDS_S = 500.0
MSG_COST_S = 0.0001
#: Nominal 1x offered rate: under effective capacity (capacity minus
#: per-message overhead) so the 1x arm is a healthy system.
NOMINAL_1X = 300.0
SLO_DEADLINE_S = 1.0
LOADS = (1, 2, 5, 10, 20)

#: The admission arm's server/client knobs (docs/SERVING.md): token
#: bucket at ~the effective capacity, a watermark-tied in-flight
#: budget of ~0.5s of capacity, a bounded client-lane inbox, explicit
#: reject-newest, and client backoff with a bounded retry budget.
ADMISSION = dict(
    admission_token_rate=430.0,
    admission_token_burst=30.0,
    admission_inflight_limit=80,
    admission_inbox_capacity=64,
    admission_inbox_policy="reject",
    admission_retry_after_ms=100,
)
CLIENT_RETRY_BUDGET = 4
#: Client backoff under rejection: starts high enough that one
#: rejected burst does not re-arrive within the next few ticks.
CLIENT_BACKOFF = dict(initial_s=0.15, max_s=2.0, multiplier=2.0,
                      jitter=0.5)


def run_arm(load_x: float, admission_on: bool, *, duration_s: float,
            num_sessions: int, seed: int = 0) -> dict:
    from frankenpaxos_tpu.bench.workload import OpenLoopWorkload
    from frankenpaxos_tpu.serve.loadgen import SimOverloadDriver
    from tests.protocols.multipaxos_harness import make_multipaxos

    from frankenpaxos_tpu.serve.backoff import Backoff

    sim = make_multipaxos(
        f=1, coalesced=True, seed=seed,
        leader_admission=ADMISSION if admission_on else None,
        client_retry_budget=CLIENT_RETRY_BUDGET if admission_on else 0,
        client_backoff=Backoff(**CLIENT_BACKOFF) if admission_on
        else None)
    workload = OpenLoopWorkload(rate=NOMINAL_1X * load_x,
                                zipf_s=1.1, num_keys=1 << 16)
    driver = SimOverloadDriver(
        sim, workload, num_sessions=num_sessions,
        capacity_cmds_per_s=CAPACITY_CMDS_S, msg_cost_s=MSG_COST_S,
        slo_deadline_s=SLO_DEADLINE_S, seed=seed + int(load_x * 100))
    t0 = time.perf_counter()
    stats = driver.run(duration_s=duration_s, warmup_s=1.0,
                       settle_s=10.0)
    stats["load_x"] = load_x
    stats["admission"] = {"enabled": admission_on, **stats["admission"]}
    stats["wall_seconds"] = round(time.perf_counter() - t0, 1)
    return stats


def evaluate_gate(arms: dict) -> dict:
    """arms: {"admission"/"baseline": {load_x: stats}}.

    The p99 in the gate is the ADMITTED-request p99
    (``p99_admitted_s``): ops the server admitted on arrival, so the
    number is the latency the admission-controlled pipeline delivered
    -- client backoff sleeps from earlier rejections are a different
    (intended, bounded) cost, reported separately as the end-to-end
    ``p99_latency_s``. For the baseline nothing is ever rejected, so
    the two coincide -- the A/B compares like with like."""
    adm, base = arms["admission"], arms["baseline"]
    peak_1x = adm[1]["goodput_cmds_per_s"]
    p99_1x = adm[1]["p99_admitted_s"] or 1e-9
    depth_1x = max(1, adm[1]["max_queue_depth"])
    ten = adm[10]
    ten_base = base[10]
    goodput_ok = ten["goodput_cmds_per_s"] >= 0.7 * peak_1x
    p99_ok = (ten["p99_admitted_s"] or float("inf")) <= 5 * p99_1x
    # "Bounded": the admission knobs bound the queue by construction
    # (inbox capacity + in-flight budget + token burst, times a small
    # constant for replies in flight), independent of offered load or
    # duration -- the baseline's depth instead grows with both.
    depth_bound = 16 * depth_1x + 2 * (
        ADMISSION["admission_inbox_capacity"]
        + ADMISSION["admission_inflight_limit"]
        + int(ADMISSION["admission_token_burst"]))
    depth_ok = ten["max_queue_depth"] <= depth_bound
    # Load-independence: when the sweep includes 20x, the 20x depth
    # must not outgrow the 10x depth by more than jitter.
    depth_flat = None
    if 20 in adm:
        depth_flat = (adm[20]["max_queue_depth"]
                      <= 1.5 * max(1, ten["max_queue_depth"]))
        depth_ok = depth_ok and depth_flat
    baseline_violations = []
    if ten_base["goodput_cmds_per_s"] < 0.7 * peak_1x:
        baseline_violations.append("goodput")
    if (ten_base["p99_admitted_s"] or float("inf")) > 5 * p99_1x:
        baseline_violations.append("p99")
    if ten_base["max_queue_depth"] > depth_bound:
        baseline_violations.append("queue_growth")
    return {
        "peak_1x_goodput": peak_1x,
        "p99_1x_s": p99_1x,
        "at_10x": {
            "goodput": ten["goodput_cmds_per_s"],
            "goodput_floor": round(0.7 * peak_1x, 2),
            "goodput_ok": goodput_ok,
            "p99_admitted_s": ten["p99_admitted_s"],
            "p99_e2e_s": ten["p99_latency_s"],
            "p99_ceiling_s": round(5 * p99_1x, 4),
            "p99_ok": p99_ok,
            "max_queue_depth": ten["max_queue_depth"],
            "queue_depth_bound": depth_bound,
            "depth_flat_10x_to_20x": depth_flat,
            "queue_bounded": depth_ok,
        },
        "baseline_at_10x": {
            "goodput": ten_base["goodput_cmds_per_s"],
            "p99_admitted_s": ten_base["p99_admitted_s"],
            "max_queue_depth": ten_base["max_queue_depth"],
            "violations": baseline_violations,
        },
        "gate_passed": bool(goodput_ok and p99_ok and depth_ok
                            and baseline_violations),
    }


# --- disabled-hook overhead A/B (trace_overhead methodology) --------------


def _nohooks_patch():
    """(enter, exit) swapping the paxload hook sites for hook-free
    bodies: SimTransport send without the bounded-inbox admission
    check, and the leader client-request handlers without the _admit
    early-outs.

    Post-paxsim the benched delivery path is the wave engine
    (``_run_wave``), where the admission-off inbox cost is one falsy
    branch per delivered frame -- there is no per-message ``_deliver``
    hook left to strip, and patching ``_deliver`` would disable the
    wave fast path in this arm only (sim_transport.WAVE_SAFE_DELIVERS),
    so the A/B would measure engines, not hooks."""
    from frankenpaxos_tpu.protocols.multipaxos import leader as leader_mod
    from frankenpaxos_tpu.protocols.multipaxos.leader import (
        Leader,
        _Inactive,
        _Phase1,
    )
    from frankenpaxos_tpu.protocols.multipaxos.messages import (
        ClientRequestBatch,
        CommandBatch,
        NotLeaderClient,
        Phase2aRun,
    )
    from frankenpaxos_tpu.runtime.sim_transport import (
        SimMessage,
        SimTransport,
    )

    def send(self, src, dst, data):
        tracer = self.tracer
        trace = tracer.current if tracer is not None else None
        self.messages.append(
            SimMessage(next(self._ids), src, dst, data, trace))

    def _handle_client_request(self, src, request):
        if isinstance(self.state, _Inactive):
            self.send(src, NotLeaderClient())
        elif isinstance(self.state, _Phase1):
            self.state.pending_batches.append(
                ClientRequestBatch(CommandBatch((request.command,))))
        else:
            self._process_client_request_batch(
                ClientRequestBatch(CommandBatch((request.command,))))

    def _handle_client_request_array(self, src, array):
        if not array.commands:
            return
        if isinstance(self.state, _Inactive):
            self.send(src, NotLeaderClient())
            return
        if isinstance(self.state, _Phase1):
            for command in array.commands:
                self.state.pending_batches.append(
                    ClientRequestBatch(CommandBatch((command,))))
            return
        if self.config.num_acceptor_groups > 1 and not self.config.flexible:
            for command in array.commands:
                self._process_client_request_batch(
                    ClientRequestBatch(CommandBatch((command,))))
            return
        pending = self._epoch_buffering()
        if pending is not None:
            pending.extend(CommandBatch((c,)) for c in array.commands)
            return
        if self._epoch_tagging:
            self._send_epoch_runs(
                tuple(CommandBatch((c,)) for c in array.commands))
            return
        run = Phase2aRun(
            start_slot=self.next_slot, round=self.round,
            values=tuple(CommandBatch((c,)) for c in array.commands))
        k = len(array.commands)
        self.next_slot += k
        dst = self._proxy_leader_address()
        self.send(dst, run)
        self._account_sent_slots(dst, k)

    def _handle_chosen_watermark(self, src, msg):
        self.chosen_watermark = max(self.chosen_watermark, msg.slot)

    originals = (SimTransport.send,
                 Leader._handle_client_request,
                 Leader._handle_client_request_array,
                 Leader._handle_chosen_watermark)

    def enter():
        SimTransport.send = send
        Leader._handle_client_request = _handle_client_request
        Leader._handle_client_request_array = _handle_client_request_array
        Leader._handle_chosen_watermark = _handle_chosen_watermark
        leader_mod  # keep the import referenced

    def exit():
        (SimTransport.send,
         Leader._handle_client_request,
         Leader._handle_client_request_array,
         Leader._handle_chosen_watermark) = originals

    return enter, exit


#: ~1K commands per interleave chunk, 32 timed chunks per arm per
#: block (~32K commands timed per arm), 4 warm-up chunks discarded.
OVERHEAD_CHUNK_CMDS = 1024
OVERHEAD_CHUNKS = 32
OVERHEAD_WARMUP_CHUNKS = 4


def measure_overhead_block(inflight: int) -> float:
    """One chunk-interleaved A/B block: two persistent sims (shipped
    hooks with admission OFF vs verbatim pre-paxload bodies via
    `_nohooks_patch`) driven alternately in ~1K-command chunks with GC
    disabled, arm order flipped every chunk; returns the off/no-hooks
    throughput ratio from the summed per-arm times.

    Why this shape (calibrated on this 2-CPU container, see
    docs/BENCH_HISTORY.md): separate whole-rep arms flake against the
    3% gate no matter the estimator -- per-rep noise is ~+-20% at
    0.5s reps and an A/A control (two IDENTICAL sims) still spread
    +-8% at 2s reps because gen2 GC pauses over the sims' growing
    heaps land on whichever arm is running. Fine interleaving makes
    the two arms share every throttle/steal window, and disabling GC
    during the timed chunks removes the pause lottery: the same A/A
    control lands within ~1.5% after process warm-up."""
    import gc

    from frankenpaxos_tpu.bench.wal_lt import _drive_waves
    from tests.protocols.multipaxos_harness import make_multipaxos

    enter, exit = _nohooks_patch()
    chunk_waves = max(1, OVERHEAD_CHUNK_CMDS // inflight)
    sims: dict = {}
    results: dict = {}
    for arm in ("off", "no-hooks"):
        if arm == "no-hooks":
            enter()
        try:
            sims[arm] = make_multipaxos(f=1, coalesced=True)
            results[arm] = []
            _drive_waves(sims[arm], inflight, 2, b"w", results[arm])
        finally:
            if arm == "no-hooks":
                exit()
    total = {"off": 0.0, "no-hooks": 0.0}
    gc.collect()
    gc.disable()
    try:
        for k in range(OVERHEAD_WARMUP_CHUNKS + OVERHEAD_CHUNKS):
            order = (("off", "no-hooks") if k % 2
                     else ("no-hooks", "off"))
            for arm in order:
                if arm == "no-hooks":
                    enter()
                try:
                    t0 = time.perf_counter()
                    _drive_waves(sims[arm], inflight, chunk_waves, b"x",
                                 results[arm])
                    elapsed = time.perf_counter() - t0
                finally:
                    if arm == "no-hooks":
                        exit()
                if k >= OVERHEAD_WARMUP_CHUNKS:
                    total[arm] += elapsed
    finally:
        gc.enable()
    expected = (2 + (OVERHEAD_WARMUP_CHUNKS + OVERHEAD_CHUNKS)
                * chunk_waves) * inflight
    assert len(results["off"]) == len(results["no-hooks"]) == expected
    return total["no-hooks"] / total["off"]


def admission_overhead(inflights=(16, 256, 1024), blocks: int = 7) -> dict:
    """Paired chunk-interleaved A/B (`measure_overhead_block`); the
    reported ratio is the MEDIAN over ``blocks`` independent blocks
    (fresh sims each, so one cold-process or GC-debt-laden block
    cannot swing it). Per-block ratios are recorded as ratio_range
    for noise visibility."""
    table = {}
    worst = 0.0
    for inflight in inflights:
        ratios = sorted(measure_overhead_block(inflight)
                        for _ in range(blocks))
        chunk_waves = max(1, OVERHEAD_CHUNK_CMDS // inflight)
        row = {
            "ratio_off_over_no_hooks": round(statistics.median(ratios), 4),
            "ratio_range": [round(ratios[0], 4), round(ratios[-1], 4)],
            "commands_timed": chunk_waves * inflight * OVERHEAD_CHUNKS
            * blocks,
        }
        overhead_pct = round((1.0 - row["ratio_off_over_no_hooks"]) * 100,
                             2)
        row["off_overhead_pct"] = overhead_pct
        worst = max(worst, overhead_pct)
        table[str(inflight)] = row
    return {"per_width": table,
            "off_overhead_pct_worst_width": round(worst, 2),
            "gate": "admission-off per-message overhead must be < 3%",
            "estimator": ("median of chunk-interleaved gc-disabled "
                          "block ratios"),
            "gate_passed": worst < 3.0}


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None)
    parser.add_argument("--smoke", action="store_true",
                        help="2-minute CI smoke: fewer loads, shorter "
                             "windows, smaller session array")
    parser.add_argument("--num_sessions", type=int, default=None)
    parser.add_argument("--duration", type=float, default=None)
    parser.add_argument("--skip_overhead", action="store_true")
    args = parser.parse_args(argv)

    loads = (1, 10) if args.smoke else LOADS
    duration_s = args.duration or (4.0 if args.smoke else 8.0)
    num_sessions = args.num_sessions or (
        1 << 18 if args.smoke else 1_000_000)

    arms: dict = {"admission": {}, "baseline": {}}
    for load_x in loads:
        for name, on in (("baseline", False), ("admission", True)):
            stats = run_arm(load_x, on, duration_s=duration_s,
                            num_sessions=num_sessions)
            arms[name][load_x] = stats
            print(json.dumps({"arm": name, **{
                k: stats[k] for k in ("load_x", "goodput_cmds_per_s",
                                      "p99_admitted_s", "p99_latency_s",
                                      "p999_latency_s",
                                      "max_queue_depth", "giveups",
                                      "wall_seconds")}}), flush=True)

    gate = evaluate_gate(arms)
    result = {
        "benchmark": "overload_lt",
        "host_cpus": os.cpu_count(),
        "model": {
            "capacity_cmds_per_s": CAPACITY_CMDS_S,
            "msg_cost_s": MSG_COST_S,
            "nominal_1x_rate": NOMINAL_1X,
            "slo_deadline_s": SLO_DEADLINE_S,
            "num_sessions": num_sessions,
            "duration_s": duration_s,
            "admission_knobs": ADMISSION,
            "client_retry_budget": CLIENT_RETRY_BUDGET,
        },
        "curves": {name: {str(k): v for k, v in rows.items()}
                   for name, rows in arms.items()},
        "gate": gate,
        "methodology": (
            "serve/loadgen.py virtual-time service model over the "
            "coalesced multipaxos SimTransport pipeline: open-loop "
            "Zipf(1.1) arrivals from the shared OpenLoopWorkload over "
            "an SoA session array, cluster CPU budget = 1 virtual "
            "second/second (1/capacity per completed command + "
            "msg_cost per delivery), timers on virtual deadlines; "
            "goodput counts completions within the SLO deadline among "
            "commands ISSUED in the measured window; paired arms "
            "share seeds. Deterministic per seed."),
    }
    if not args.skip_overhead:
        # Full-strength A/B even in the smoke: whole-rep arms flake
        # against the 3% gate on this container at ANY rep count
        # (see measure_overhead_block), so the smoke only trims the
        # width list, never the blocks.
        result["admission_overhead"] = admission_overhead(
            inflights=(16, 256) if args.smoke else (16, 256, 1024))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps({"gate": gate,
                      "overhead": result.get("admission_overhead", {}).get(
                          "off_overhead_pct_worst_width")}, indent=2))
    return result


if __name__ == "__main__":
    main()
