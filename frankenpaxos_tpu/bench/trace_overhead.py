"""paxtrace overhead A/B: no-hooks vs off vs sampled vs full.

THE GATE (ISSUE 4): the tracing hooks must cost <3% per message when
tracing is OFF -- every role ships with them compiled in, so the
disabled path (one attribute load + an ``is None`` test per hook
site) is the price everyone pays. The bench proves it with the
multipaxos_lt methodology over the full coalesced actor pipeline:

  * arm ``no-hooks``: SimTransport's deliver/drain/send monkeypatched
    with verbatim copies of the PRE-paxtrace bodies (no tracer checks
    at all) -- the true baseline a committed repo can no longer run;
  * arm ``off``: the shipped code, no tracer attached;
  * arm ``sampled``: a Tracer at 1/64 root sampling;
  * arm ``full``: a Tracer at 1.0 (every command traced).

Per in-flight width: interleaved paired reps with rotating arm order,
the MEDIAN of paired ratios, pooled over independent subprocess
batches (the multipaxos_lt/mencius_lt/wal_lt sim A/B shape).

Usage::

    python -m frankenpaxos_tpu.bench.trace_overhead \
        --out bench_results/trace_overhead.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

ARMS = ("no-hooks", "off", "sampled", "full")


def _nohooks_patch():
    """(enter, exit) functions swapping SimTransport's traced send and
    drain for hook-free bodies.

    Post-paxsim, the benched ``deliver_all_coalesced`` path runs the
    wave engine (``_run_wave``), whose per-message tracer cost when a
    tracer is detached is one ``is not None`` branch -- there is no
    per-message ``_deliver`` hook left to strip, and patching
    ``_deliver`` anyway would disable the wave fast path in THIS arm
    only (sim_transport.WAVE_SAFE_DELIVERS), turning the hook A/B into
    an engine A/B. So the no-hooks arm strips exactly the surviving
    per-event hook sites: the send-side trace stamp and the per-drain
    tracer check."""
    from frankenpaxos_tpu.runtime.sim_transport import (
        SimMessage,
        SimTransport,
    )

    def send(self, src, dst, data):
        self.messages.append(
            SimMessage(next(self._ids), src, dst, data))

    def _drain(self, actor):
        actor.on_drain()

    originals = (SimTransport.send, SimTransport._drain)

    def enter():
        SimTransport.send = send
        SimTransport._drain = _drain

    def exit():
        (SimTransport.send, SimTransport._drain) = originals

    return enter, exit


def measure(arm: str, inflight: int, waves: int, warm: int = 2,
            sample_rate: float = 1.0 / 64) -> dict:
    """One timed run of the coalesced multipaxos pipeline under
    ``arm``; returns {"cmds_per_sec": ..., "spans": ...}."""
    import gc

    from frankenpaxos_tpu.bench.wal_lt import _drive_waves
    from tests.protocols.multipaxos_harness import make_multipaxos

    gc.collect()
    enter = exit = None
    if arm == "no-hooks":
        enter, exit = _nohooks_patch()
        enter()
    try:
        sim = make_multipaxos(f=1, coalesced=True)
        tracer = None
        if arm in ("sampled", "full"):
            from frankenpaxos_tpu.obs import Tracer

            tracer = Tracer(
                role="bench",
                sample_rate=1.0 if arm == "full" else sample_rate)
            sim.transport.tracer = tracer
        results: list = []
        _drive_waves(sim, inflight, warm, b"w", results)
        t0 = time.perf_counter()
        _drive_waves(sim, inflight, waves, b"x", results)
        elapsed = time.perf_counter() - t0
        assert len(results) == (warm + waves) * inflight, (
            arm, inflight, len(results))
        return {"cmds_per_sec": waves * inflight / elapsed,
                "spans": len(tracer.spans) if tracer else 0}
    finally:
        if exit is not None:
            exit()


def sim_ab(inflights, reps: int = 6, waves: int = 0) -> dict:
    """Interleaved paired A/B across the four arms (multipaxos_lt
    sim_ab_pipeline methodology; ratios are per-rep pairs, the table
    rows their medians)."""
    import statistics

    table = {}
    for inflight in inflights:
        # Enough waves that each timed segment runs long enough to
        # swamp scheduler noise (~8k commands per measurement): a
        # 20ms segment cannot resolve a 3% gate.
        w = waves or max(8, 8192 // inflight)
        runs: dict = {arm: [] for arm in ARMS}
        ratios: dict = {key: [] for key in
                        ("off/no-hooks", "sampled/off", "full/off")}
        spans = {}
        for rep in range(reps):
            order = list(ARMS[rep % len(ARMS):]) \
                + list(ARMS[:rep % len(ARMS)])
            got = {}
            for arm in order:
                result = measure(arm, inflight, w)
                got[arm] = result["cmds_per_sec"]
                if result["spans"]:
                    spans[arm] = result["spans"]
            for arm in ARMS:
                runs[arm].append(got[arm])
            ratios["off/no-hooks"].append(got["off"] / got["no-hooks"])
            ratios["sampled/off"].append(got["sampled"] / got["off"])
            ratios["full/off"].append(got["full"] / got["off"])
        row = {f"{arm.replace('-', '_')}_cmds_per_sec":
               round(statistics.median(runs[arm]), 1) for arm in ARMS}
        for key, values in ratios.items():
            row[f"ratio_{key.replace('/', '_over_').replace('-', '_')}"] \
                = round(statistics.median(values), 4)
            row[f"ratio_{key.replace('/', '_over_').replace('-', '_')}"
                + "_range"] = [round(min(values), 4),
                               round(max(values), 4)]
        row["spans_per_arm"] = spans
        row["commands_timed"] = w * inflight
        table[str(inflight)] = row
    return table


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sim_inflight", type=str, default="16,256,1024")
    parser.add_argument("--sim_repeats", type=int, default=6)
    parser.add_argument("--sim_ab_batches", type=int, default=3)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    import statistics

    from frankenpaxos_tpu.bench.deploy_suite import role_process_env

    inflights = [int(x) for x in args.sim_inflight.split(",")]
    per_width: dict = {str(i): [] for i in inflights}
    for _batch in range(args.sim_ab_batches):
        ab = subprocess.run(
            [sys.executable, "-c",
             "import json; from frankenpaxos_tpu.bench.trace_overhead "
             f"import sim_ab; print(json.dumps(sim_ab({inflights!r}, "
             f"reps={args.sim_repeats})))"],
            capture_output=True, text=True, env=role_process_env(),
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        if ab.returncode != 0:
            print(f"sim A/B batch failed (rc={ab.returncode}): "
                  f"{ab.stderr[-500:]}", file=sys.stderr)
            continue
        out = json.loads(ab.stdout.strip().splitlines()[-1])
        print(json.dumps({"sim_ab_batch": out}))
        for key, row in out.items():
            per_width[key].append(row)

    merged = {}
    worst_off_overhead = 0.0
    for key, rows in per_width.items():
        if not rows:
            continue
        row = {}
        for field in rows[0]:
            if field.endswith("_range"):
                row[field] = [min(r[field][0] for r in rows),
                              max(r[field][1] for r in rows)]
            elif field == "spans_per_arm":
                row[field] = rows[0][field]
            elif field == "commands_timed":
                row[field] = rows[0][field]
            else:
                row[field] = round(statistics.median(
                    r[field] for r in rows), 4)
        row["batches"] = len(rows)
        overhead_pct = round(
            (1.0 - row["ratio_off_over_no_hooks"]) * 100, 2)
        row["off_overhead_pct"] = overhead_pct
        worst_off_overhead = max(worst_off_overhead, overhead_pct)
        merged[key] = row

    result = {
        "benchmark": "trace_overhead",
        "host_cpus": os.cpu_count(),
        "sim_ab": merged,
        "off_overhead_pct_worst_width": round(worst_off_overhead, 2),
        "gate": "tracing-off per-message overhead must be < 3%",
        "gate_passed": worst_off_overhead < 3.0,
        "methodology": (
            "per-width ratio = median over independent subprocess "
            "batches of each batch's paired-A/B median (the "
            "multipaxos_lt sim_ab methodology) over the coalesced "
            "multipaxos SimTransport pipeline; arms are no-hooks "
            "(SimTransport deliver/drain/send monkeypatched with "
            "verbatim pre-paxtrace bodies), off (shipped hooks, no "
            "tracer), sampled (Tracer at 1/64 root sampling), full "
            "(Tracer at 1.0). off/no-hooks isolates the disabled-"
            "hook cost every deployment pays; sampled/off and "
            "full/off price the tracing itself."),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
