"""An in-process Prometheus: periodic /metrics scraping into a tiny
TSDB plus a PromQL subset queried into pandas.

The reference spins a REAL Prometheus server per benchmark and queries
PromQL through its HTTP API into DataFrames
(benchmarks/prometheus.py:10-132, ``PrometheusQueryer.query`` -> a
time-indexed DataFrame with one frozenset-labeled column per series).
This environment has no prometheus binary, so this module provides the
same query surface over samples the harness scrapes itself:

    db = MetricsDB(scrape_interval_s=0.25)
    db.start({"replica_0": 9001, "replica_1": 9002})
    ... drive load ...
    db.stop()
    df = db.query('rate(multipaxos_replica_executed_commands_total[2s])')
    df = db.query('sum(rate(foo_total[2s]))')
    df = db.query('sum by (job) (rate(foo_total[2s]))')

Query results mirror the reference's shape: a DataFrame indexed by
sample time whose columns are ``frozenset({("__name__", name),
("job", label), ...})``.

Supported PromQL subset (the pieces the reference's benchmarks use):

  * instant/range selectors: ``name`` or ``name{label="v", ...}``
    (returns every collected sample, like the reference's ``up[24h]``);
  * ``rate(selector[Ns])`` over counters, with Prometheus-style
    counter-reset handling;
  * ``sum(...)``, ``avg(...)``, ``max(...)``, ``min(...)``, optionally
    ``by (label, ...)``.
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Callable, Optional

Labels = frozenset  # of (key, value) pairs

_SELECTOR = re.compile(
    r"^\s*(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)"
    r"(?:\{(?P<matchers>[^}]*)\})?"
    r"(?:\[(?P<window>\d+(?:\.\d+)?)(?P<unit>ms|s|m|h)\])?\s*$")
_AGG = re.compile(
    r"^\s*(?P<op>sum|avg|max|min)\s*"
    r"(?:by\s*\((?P<by>[^)]*)\)\s*)?"
    r"\((?P<inner>.*)\)\s*$", re.DOTALL)
_RATE = re.compile(r"^\s*rate\s*\((?P<inner>.*)\)\s*$", re.DOTALL)
# Label values are quoted strings WITH escapes (the exposition format
# escapes backslash, double-quote, and newline): ``[^"]*`` would end a
# value at the first escaped quote.
_MATCHER = re.compile(
    r'([A-Za-z_][A-Za-z0-9_]*)\s*=\s*"((?:[^"\\]|\\.)*)"')
_SCRAPED_KEY = re.compile(
    r"^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)(?:\{(?P<labels>.*)\})?$")

_UNIT_S = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


_ESCAPE = re.compile(r"\\(.)")


def _unescape(value: str) -> str:
    # Left-to-right (chained str.replace mangles ``\\n`` -- an escaped
    # backslash followed by a literal n -- into a newline).
    return _ESCAPE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), value)


def _parse_scraped_key(key: str, job: str) -> Optional[Labels]:
    m = _SCRAPED_KEY.match(key)
    if m is None:
        return None
    labels = [("__name__", m.group("name")), ("job", job)]
    if m.group("labels"):
        labels.extend((k, _unescape(v))
                      for k, v in _MATCHER.findall(m.group("labels")))
    return frozenset(labels)


class MetricsDB:
    """Scrapes ``{job_label: port}`` endpoints on a background thread;
    answers the PromQL subset over everything collected."""

    def __init__(self, scrape_interval_s: float = 0.25,
                 scrape_fn: Optional[Callable[[int], dict]] = None):
        if scrape_fn is None:
            from frankenpaxos_tpu.bench.metrics import scrape as scrape_fn
        self._scrape = scrape_fn
        self.scrape_interval_s = scrape_interval_s
        #: series -> [(unix time, value)] in scrape order.
        self.series: dict[Labels, list[tuple[float, float]]] = {}
        # Guards self.series between the scraper thread and
        # query()/to_json() callers (dict iteration during insert would
        # raise; a Series built from a list mid-append could get
        # mismatched value/index lengths).
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- collection -------------------------------------------------------
    def scrape_once(self, targets: dict) -> None:
        now = time.time()
        for job, port in targets.items():
            try:
                samples = self._scrape(port)
            except Exception:
                # Endpoint not up yet, mid-teardown truncated response
                # (HTTPException, not OSError), parse garbage: skip the
                # tick -- one bad scrape must never end collection.
                continue
            with self._lock:
                for key, value in samples.items():
                    labels = _parse_scraped_key(key, job)
                    if labels is not None:
                        self.series.setdefault(labels, []).append(
                            (now, value))

    def start(self, targets: dict) -> None:
        def loop():
            while not self._stop.is_set():
                self.scrape_once(targets)
                self._stop.wait(self.scrape_interval_s)

        self._stop.clear()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="metrics-db")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # --- persistence ------------------------------------------------------
    def to_json(self, path: str) -> None:
        with self._lock:
            data = [{"labels": sorted(labels), "samples": list(samples)}
                    for labels, samples in sorted(
                        self.series.items(), key=lambda kv: sorted(kv[0]))]
        with open(path, "w") as f:
            json.dump(data, f)

    @classmethod
    def from_json(cls, path: str) -> "MetricsDB":
        db = cls(scrape_fn=lambda port: {})
        with open(path) as f:
            for row in json.load(f):
                db.series[frozenset(map(tuple, row["labels"]))] = [
                    tuple(s) for s in row["samples"]]
        return db

    # --- query ------------------------------------------------------------
    def query(self, q: str):
        """Evaluate the PromQL subset; returns a time-indexed pandas
        DataFrame with frozenset-labeled columns (the reference's
        ``PrometheusQueryer.query`` shape, prometheus.py:81-132)."""
        import pandas as pd

        agg = _AGG.match(q)
        if agg is not None:
            inner = self.query(agg.group("inner"))
            if inner.empty:
                return inner
            by = tuple(part.strip()
                       for part in (agg.group("by") or "").split(",")
                       if part.strip())
            groups: dict[Labels, list] = {}
            for col in inner.columns:
                key = (frozenset((k, v) for k, v in col if k in by)
                       if by else frozenset())
                groups.setdefault(key, []).append(col)
            op = agg.group("op")
            out = {}
            for key, cols in groups.items():
                # Align series on the union index (scrapes of different
                # jobs tick together but not identically); forward-fill
                # like Prometheus's staleness-window lookup.
                block = inner[cols].ffill()
                out[key] = getattr(block, op if op != "avg" else "mean")(
                    axis=1)
            return pd.DataFrame(out)

        rate = _RATE.match(q)
        if rate is not None:
            sel = _SELECTOR.match(rate.group("inner"))
            if sel is None or sel.group("window") is None:
                raise ValueError(
                    f"rate() needs `selector[window]`: {q!r}")
            window = (float(sel.group("window"))
                      * _UNIT_S[sel.group("unit")])
            out = {}
            for labels, samples in self._select(sel):
                # Prometheus-style: accumulate CONSECUTIVE-pair
                # increases (a drop between adjacent samples is a
                # counter reset; the post-reset value is the increase,
                # and pre-reset growth inside the window is kept).
                # Prefix sums + a monotone window-start pointer make
                # the whole series O(n).
                inc = [0.0] * len(samples)
                for i in range(1, len(samples)):
                    delta = samples[i][1] - samples[i - 1][1]
                    inc[i] = inc[i - 1] + (delta if delta >= 0
                                           else samples[i][1])
                pts = []
                j = 0
                for i, (t, v) in enumerate(samples):
                    lo = t - window
                    while samples[j][0] < lo:
                        j += 1
                    if j >= i or t <= samples[j][0]:
                        continue
                    pts.append((t, (inc[i] - inc[j])
                                / (t - samples[j][0])))
                if pts:
                    out[labels] = pd.Series(
                        [v for _, v in pts],
                        index=pd.to_datetime([t for t, _ in pts],
                                             unit="s"))
            return pd.DataFrame(out)

        sel = _SELECTOR.match(q)
        if sel is None:
            raise ValueError(f"unsupported PromQL: {q!r}")
        out = {}
        for labels, samples in self._select(sel):
            out[labels] = pd.Series(
                [v for _, v in samples],
                index=pd.to_datetime([t for t, _ in samples], unit="s"))
        return pd.DataFrame(out)

    def _select(self, sel) -> list:
        name = sel.group("name")
        raw = sel.group("matchers") or ""
        # Only `name="value"` matchers are supported; anything else
        # (!=, =~, !~) must ERROR, not silently match everything.
        stripped = _MATCHER.sub("", raw).replace(",", "").strip()
        if stripped:
            raise ValueError(
                f"unsupported label matchers {raw!r} (only "
                f'`name="value"` equality is implemented)')
        matchers = {k: _unescape(v) for k, v in _MATCHER.findall(raw)}
        hits = []
        with self._lock:
            items = [(labels, list(samples))
                     for labels, samples in self.series.items()]
        for labels, samples in items:
            as_dict = dict(labels)
            if as_dict.get("__name__") != name:
                continue
            if all(as_dict.get(k) == v for k, v in matchers.items()):
                hits.append((labels, samples))
        return hits
