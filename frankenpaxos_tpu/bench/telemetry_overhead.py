"""paxpulse telemetry overhead: pinned-baseline / off / on paired A/B.

The device telemetry plane (ops/telemetry.py) claims to be FREE when
disabled: a ``None`` telemetry leaf compiles out of the drain loop
entirely, so the telemetry-off pipeline must trace to the same program
as the pre-paxpulse pipeline. This bench holds that claim to a gate
the same way trace_overhead.py gates the host tracer:

  * **baseline** -- the verbatim pre-paxpulse pipeline, PINNED in
    ``bench/pipeline_baseline.py`` (runtime/sim_legacy.py idiom) so
    the comparison arm cannot drift when the live module is edited;
  * **off** -- the live pipeline with ``telemetry=False`` (the
    default). Gate: < 3% throughput overhead vs baseline at the worst
    width;
  * **on** -- the live pipeline with ``telemetry=True``, recorded
    honestly (the real cost of the counters: reductions + a histogram
    scatter per drain) but not gated -- enabling telemetry is an
    explicit opt-in.

Methodology (multipaxos_lt / trace_overhead calibration): all three
arms keep persistent states driven in ``iters``-drain chunks with a
TRACED start (``run_steps_from``), order rotated every chunk, GC off
across the timed region, warmup chunks discarded; per-block ratios,
median over independent blocks with fresh states per block.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import statistics
import time

ARMS = ("baseline", "off", "on")


def _spec_arrays():
    from frankenpaxos_tpu.quorums import SimpleMajority

    return SimpleMajority(range(3)).write_spec().as_arrays()


def measure_ab_block(window: int, block_size: int, *, warmup: int,
                     chunks: int, iters: int) -> dict:
    """One chunk-interleaved block over the three persistent arms."""
    import jax.numpy as jnp

    from frankenpaxos_tpu.bench import pipeline as live
    from frankenpaxos_tpu.bench import pipeline_baseline as pinned

    masks, thresholds, combine_any = _spec_arrays()
    masks_t = tuple(tuple(int(x) for x in row) for row in masks)
    thresholds_t = tuple(int(t) for t in thresholds)
    n_acc = masks.shape[1]

    states = {
        "baseline": pinned.make_state(window, n_acc),
        "off": live.make_state(window, n_acc, telemetry=False),
        "on": live.make_state(window, n_acc, telemetry=True),
    }

    def advance(arm, state, start):
        mod = pinned if arm == "baseline" else live
        return mod.run_steps_from(state, start, iters, block_size,
                                  masks_t, thresholds_t, combine_any)

    # Warm every executable at the timed shape; the arms must stay in
    # lockstep (same committed watermark) for the pairing to be fair.
    start = jnp.int32(0)
    for arm in ARMS:
        states[arm] = advance(arm, states[arm], start)
    committed = {arm: int(states[arm].committed) for arm in ARMS}
    assert len(set(committed.values())) == 1, committed
    at = iters

    total = {arm: 0.0 for arm in ARMS}
    gc.collect()
    gc.disable()
    try:
        for k in range(warmup + chunks):
            order = ARMS[k % 3:] + ARMS[:k % 3]
            start = jnp.int32(at)
            for arm in order:
                t0 = time.perf_counter()
                states[arm] = advance(arm, states[arm], start)
                _ = int(states[arm].committed)  # value fetch: full sync
                if k >= warmup:
                    total[arm] += time.perf_counter() - t0
            at += iters
    finally:
        gc.enable()

    committed = {arm: int(states[arm].committed) for arm in ARMS}
    cmds = chunks * iters * block_size
    return {
        **{f"{arm}_s": total[arm] for arm in ARMS},
        **{f"{arm}_cmds_per_sec": cmds / total[arm] for arm in ARMS},
        "off_over_baseline_ratio": total["baseline"] / total["off"],
        "on_over_off_ratio": total["off"] / total["on"],
        "arms_agree": len(set(committed.values())) == 1,
        "committed": committed["off"],
    }


def measure_width(window: int, block_size: int, knobs: dict) -> dict:
    """Median-of-blocks for one (window, block) width; fresh states per
    block so one cold or GC-debt-laden block cannot swing the ratio."""
    rows = [measure_ab_block(window, block_size,
                             warmup=knobs["warmup"],
                             chunks=knobs["chunks"],
                             iters=knobs["iters"])
            for _ in range(knobs["blocks"])]
    out = {
        "window": window,
        "block": block_size,
        "blocks": len(rows),
        "drains_per_chunk": knobs["iters"],
        "arms_agree": all(r["arms_agree"] for r in rows),
    }
    for arm in ARMS:
        out[f"{arm}_cmds_per_sec_med"] = round(statistics.median(
            r[f"{arm}_cmds_per_sec"] for r in rows), 1)
    for key in ("off_over_baseline_ratio", "on_over_off_ratio"):
        values = [r[key] for r in rows]
        out[key] = round(statistics.median(values), 4)
        out[key + "_range"] = [round(min(values), 4),
                               round(max(values), 4)]
    return out


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(
        description="paxpulse telemetry-plane overhead A/B")
    parser.add_argument("--out", default=None,
                        help="write the artifact here (default "
                             "bench_results/telemetry_overhead.json)")
    parser.add_argument("--smoke", action="store_true",
                        help="reduced widths/blocks for CI")
    parser.add_argument("--blocks", type=int, default=None)
    args = parser.parse_args(argv)

    if args.smoke:
        # One width, but chunks long enough (ms-scale) that the timer
        # can resolve a 3% band at all; sub-ms chunks measure only
        # dispatch jitter.
        widths = [(1 << 12, 1 << 8)]
        knobs = {"warmup": 1, "chunks": 4, "iters": 1024,
                 "blocks": args.blocks or 7}
    else:
        widths = [(1 << 12, 1 << 8), (1 << 13, 1 << 9)]
        knobs = {"warmup": 2, "chunks": 5, "iters": 512,
                 "blocks": args.blocks or 5}

    pairs = {}
    for window, block in widths:
        pairs[str(block)] = measure_width(window, block, knobs)

    off_worst = max((1.0 - row["off_over_baseline_ratio"]) * 100.0
                    for row in pairs.values())
    on_worst = max((1.0 - row["on_over_off_ratio"]) * 100.0
                   for row in pairs.values())
    result = {
        "benchmark": "telemetry_overhead",
        "host_cpus": os.cpu_count(),
        "smoke": args.smoke,
        "pairs": pairs,
        "off_overhead_pct_worst_width": round(off_worst, 2),
        "on_overhead_pct_worst_width": round(on_worst, 2),
        "gate": "telemetry-off pipeline must be < 3% below the pinned "
                "pre-paxpulse baseline at the worst width; the ON arm "
                "is recorded, not gated (explicit opt-in)",
        "gate_passed": off_worst < 3.0,
        "methodology": (
            "three-arm paired in-process A/B, alternating-chunk with GC "
            "off (multipaxos_lt / trace_overhead calibration): pinned "
            "pre-paxpulse pipeline (bench/pipeline_baseline.py, immune "
            "to live-module edits) vs live telemetry-off vs live "
            "telemetry-on. Persistent per-arm states advance in "
            "traced-start run_steps_from chunks (ring positions and "
            "arrival hashes continue across chunks, one compiled "
            "executable per arm), order rotated per chunk, warmup "
            "chunks discarded, committed watermarks asserted equal "
            "across arms. Per-block ratio = summed-time ratio; table "
            "row = median over independent fresh-state blocks."),
    }

    out = args.out or os.path.join("bench_results",
                                   "telemetry_overhead.json")
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"telemetry_overhead: off {off_worst:+.2f}% / on "
          f"{on_worst:+.2f}% at worst width -> "
          f"{'PASS' if result['gate_passed'] else 'FAIL'} ({out})")
    return result


if __name__ == "__main__":
    main()
