"""paxchaos deployed-TCP twins of the scenario matrix.

The scenario matrix (scenarios/matrix.py, docs/GLOBAL.md) gates
planet-scale serving entirely on VIRTUAL time. This module runs the
same fault plans against a REAL wpaxos deployment -- every role its
own OS process over TCP, WALs on real files with real fsyncs, zone
outages as real SIGKILL + verbatim relaunch, fsync stalls as real
blocking sleeps inside the role's event loop -- wall-clock, and
cross-checks the deployed SLO row against the sim row within a stated
tolerance band.

ONE FAULT PLANE: each twin builds its FaultSchedule with the SAME
builder the sim scenario uses (``faults.zone_outage_schedule`` /
``fsync_stall_schedule``) and records the schedule digest next to its
row -- "both worlds ran the same plan" is a checkable equality.

THE DEPLOYED CLAUSE SET is the measurable subset of the matrix's:
goodput floor, admitted-p99/p999 ceilings on the surviving lanes,
``no_silent_wedge`` (every issued op concludes), bounded recovery,
and ``zero_acked_write_loss`` -- here checked by a WAL POST-MORTEM:
after the run, every acceptor's on-disk WAL is recovered in-process
and each client-acked payload must be provably chosen (a same-ballot
row-majority of durable ``WalGeoVote`` records in some zone's row).
``control_plane_never_shed`` is structural in the deployed world (the
transport sheds client-lane frames only, asserted by unit tests) and
is recorded as such rather than re-measured. The WAL oracle assumes
no acceptor compacted mid-run (smoke volumes stay far below the 4 MiB
compaction threshold).

THE TOLERANCE BAND (docs/GLOBAL.md "Deployed twins"): sim rows are
exact per seed; deployed rows ride a loaded CI host's scheduler, so
the cross-check compares DISCIPLINE, not microseconds --

* in-SLO fraction (in-SLO completions / issued):
  deployed >= ``CROSS_CHECK_GOODPUT_FRACTION`` x sim;
* recovery after repair: deployed <= ``CROSS_CHECK_RECOVERY_MULT`` x
  the sim clause bound;
* acked-write loss: ZERO in both worlds, no band;
* fsync twin: the fault-on/fault-off p999 amplification must
  REPRODUCE deployed (>= ``CROSS_CHECK_AMPLIFICATION_MIN``) -- the
  "Paxos in the Cloud" pathology is real, not a sim artifact.

Usage::

    python -m frankenpaxos_tpu.bench.deployed_twin --smoke \
        --scenario zone_outage --out deployed_twin_smoke.json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import threading
import time

import numpy as np

from frankenpaxos_tpu.bench.harness import BenchmarkDirectory, free_port
from frankenpaxos_tpu.bench.workload import OpenLoopWorkload
from frankenpaxos_tpu.faults import (
    DeployedBackend,
    fsync_fault_args,
    fsync_stall_schedule,
    ingest_handoff_schedule,
    run_wall,
    ScheduleRunner,
    zone_outage_schedule,
)
from frankenpaxos_tpu.scenarios.matrix import clause
from frankenpaxos_tpu.serve.backoff import RETRY_EXHAUSTED

#: The cross-check tolerance band (see module docstring + GLOBAL.md).
CROSS_CHECK_GOODPUT_FRACTION = 0.5
CROSS_CHECK_RECOVERY_MULT = 2.0
CROSS_CHECK_AMPLIFICATION_MIN = 2.0
#: Stall-band threshold: completions at or above 0.75x the schedule's
#: stall length are attributed to the fault (the fault-off arm
#: measures how often a loaded host's scheduler alone reaches it).
STALL_BAND_S = 0.075

#: Deployed smoke sizing: modest rates (localhost, 15 role processes,
#: shared CI cores) but the same fault plan shape as the sim twin.
SLO_DEADLINE_S = 1.0


@dataclasses.dataclass(frozen=True)
class TwinScale:
    name: str
    per_zone_rate: float
    duration_s: float
    warm_s: float
    outage_dwell_s: float
    settle_s: float
    sessions_per_lane: int


#: Deployed scales mirror the SIM scales' fault TIMING exactly (warm,
#: window, dwell) AND the per-zone request RATE -- the schedule
#: builders then produce byte-identical plans in both worlds (digest
#: equality is a real check, not a formality), and sync-count-cadenced
#: faults (fsync stalls every N-th group commit) bite at the same
#: points of the run. Only the SESSION count differs: localhost
#: wall-clock with 15 role processes is not a 1.2M-session virtual
#: fabric.
SMOKE = TwinScale("smoke", per_zone_rate=50.0, duration_s=9.0,
                  warm_s=1.0, outage_dwell_s=1.5, settle_s=12.0,
                  sessions_per_lane=512)
FULL = TwinScale("full", per_zone_rate=60.0, duration_s=21.0,
                 warm_s=1.0, outage_dwell_s=2.0, settle_s=15.0,
                 sessions_per_lane=2048)


# --- the wall-clock open-loop lane driver ------------------------------------


@dataclasses.dataclass
class TwinLane:
    name: str
    client: object          # a WPaxosClient on the shared transport
    keys: list
    workload: OpenLoopWorkload


class DeployedLaneDriver:
    """Open-loop per-zone lanes against a live TcpTransport cluster,
    wall-clock: the deployed sibling of serve/loadgen's
    GeoOverloadDriver, with the same conclusions bookkeeping (acked
    payloads for the loss oracle, RETRY_EXHAUSTED giveups, per-lane
    admitted-completion attribution). Arrival windows ride an absolute
    schedule on the transport loop (catch-up windows back-to-back), so
    offered load does not self-throttle under chaos."""

    def __init__(self, transport, lanes, *, seed: int = 0,
                 dt: float = 0.02, slo_deadline_s: float = SLO_DEADLINE_S):
        self.transport = transport
        self.lanes = list(lanes)
        self.dt = dt
        self.slo_deadline_s = slo_deadline_s
        self.np_rng = np.random.default_rng(seed)
        #: (lane index, issue offset s, latency s, admitted_first)
        self.completions: list = []
        self.acked: list = []
        self.giveups = 0
        self.issued = 0
        self.thinned = 0
        self._idle: list = [[] for _ in self.lanes]
        self._rejected: list = []
        self._done = threading.Event()
        self.t0 = None

    def _hook_rejections(self) -> None:
        for li, lane in enumerate(self.lanes):
            flags: dict = {}
            self._rejected.append(flags)
            original = lane.client._handle_rejected

            def wrapped(src, m, _o=original, _flags=flags):
                for pseudonym, _cid in m.entries:
                    _flags[pseudonym] = True
                return _o(src, m)

            lane.client._handle_rejected = wrapped

    def run(self, duration_s: float, warm_s: float,
            sessions_per_lane: int) -> None:
        """Blocks until the measured window (warm + duration) ends;
        call :meth:`settle` afterwards."""
        self._idle = [list(range(sessions_per_lane))
                      for _ in self.lanes]
        self._hook_rejections()
        self._done.clear()
        self.t0 = time.monotonic()
        stop_at = self.t0 + warm_s + duration_s
        sched = {"t": self.t0}

        def window() -> None:
            now = time.monotonic()
            if now >= stop_at:
                self._done.set()
                return
            for li, lane in enumerate(self.lanes):
                k = lane.workload.arrival_count(
                    self.np_rng, sched["t"] - self.t0, self.dt)
                for _ in range(k):
                    if not self._idle[li]:
                        self.thinned += 1
                        continue
                    pseudonym = self._idle[li].pop()
                    self._issue(li, lane, pseudonym, now)
            sched["t"] += self.dt
            self.transport.loop.call_later(
                max(0.0, sched["t"] - time.monotonic()), window)

        self.transport.loop.call_soon_threadsafe(window)
        if not self._done.wait(timeout=warm_s + duration_s + 60):
            raise RuntimeError("twin lane driver never finished")

    def _issue(self, li: int, lane: TwinLane, pseudonym: int,
               now: float) -> None:
        self.issued += 1
        self._rejected[li].pop(pseudonym, None)
        key_index = int(self.np_rng.integers(0, len(lane.keys)))
        payload = b"%s.s%d.%d" % (lane.name.encode(), pseudonym,
                                  self.issued)
        t_issue = time.monotonic()

        def finished(result, _li=li, _p=pseudonym,
                     _payload=payload, _t=t_issue) -> None:
            self._idle[_li].append(_p)
            if result is RETRY_EXHAUSTED:
                self.giveups += 1
                return
            self.acked.append(_payload)
            self.completions.append(
                (_li, _t - self.t0, time.monotonic() - _t,
                 not self._rejected[_li].get(_p, False)))

        lane.client.write(pseudonym, payload, finished,
                          key=lane.keys[key_index % len(lane.keys)])

    def settle(self, settle_s: float) -> int:
        """No new arrivals; wait for every pending op to conclude
        (ack or RETRY_EXHAUSTED). Returns ops still pending at the
        deadline -- the silent-wedge count."""
        deadline = time.monotonic() + settle_s
        while time.monotonic() < deadline:
            pending = sum(len(lane.client.pending)
                          for lane in self.lanes)
            if pending == 0:
                return 0
            time.sleep(0.2)
        return sum(len(lane.client.pending) for lane in self.lanes)

    # --- stats -----------------------------------------------------------
    def lane_band_fraction(self, lane_index: int, warm_s: float,
                           duration_s: float, band_s: float) -> float:
        """Fraction of one lane's measured admitted completions at or
        above ``band_s`` -- the stall-band occupancy discriminator the
        fsync twin gates on (a loaded host's p999 is scheduler noise;
        band counting is not)."""
        lo, hi = warm_s, warm_s + duration_s
        rows = [c for c in self.completions
                if c[0] == lane_index and c[3] and lo <= c[1] < hi]
        if not rows:
            return 0.0
        return round(sum(1 for c in rows if c[2] >= band_s)
                     / len(rows), 4)

    def lane_stats(self, warm_s: float, duration_s: float) -> dict:
        lo, hi = warm_s, warm_s + duration_s
        measured = [c for c in self.completions if lo <= c[1] < hi]
        in_slo = sum(1 for c in measured
                     if c[2] <= self.slo_deadline_s)
        out = {
            "issued": self.issued,
            "completed": len(measured),
            "in_slo": in_slo,
            "goodput_cmds_per_s": round(in_slo / duration_s, 2),
            "in_slo_fraction": round(in_slo / max(1, self.issued), 4),
            "giveups": self.giveups,
            "thinned": self.thinned,
            "lanes": {},
        }
        for li, lane in enumerate(self.lanes):
            rows = [c for c in measured if c[0] == li]
            admitted = sorted(c[2] for c in rows if c[3])
            out["lanes"][lane.name] = {
                "completed": len(rows),
                "p50_admitted_s": _q(admitted, 0.50),
                "p99_admitted_s": _q(admitted, 0.99),
                "p999_admitted_s": _q(admitted, 0.999),
            }
        return out

    def recovery_after(self, lane_index: int, t_repair: float):
        """Seconds from ``t_repair`` (offset from t0) to the first
        completion on ``lane_index`` issued-and-finished after it."""
        times = [c[1] + c[2] for c in self.completions
                 if c[0] == lane_index and c[1] + c[2] >= t_repair]
        return round(min(times) - t_repair, 3) if times else None


def _q(sorted_values: list, q: float):
    if not sorted_values:
        return None
    index = min(len(sorted_values) - 1, int(q * len(sorted_values)))
    return round(sorted_values[index], 4)


# --- cluster launch + the WAL post-mortem oracle -----------------------------


def _launch_wpaxos(bench: BenchmarkDirectory, *, wal_dir: str,
                   trace_dir: "str | None" = None,
                   extra_role_args: "dict | None" = None):
    from frankenpaxos_tpu.bench.deploy_suite import launch_roles
    from frankenpaxos_tpu.deploy import get_protocol

    protocol = get_protocol("wpaxos")
    raw = protocol.cluster(1, lambda: ["127.0.0.1", free_port()])
    config_path = bench.write_json("config.json", raw)
    config = protocol.load_config(raw)
    overrides = {
        "resend_phase1a_period_s": "0.5",
        # The matrix's admission knobs, scaled for the smoke rates.
        "admission_token_rate": "150.0",
        "admission_token_burst": "30.0",
        "admission_inflight_limit": "96",
        "admission_inbox_capacity": "256",
        "admission_retry_after_ms": "100",
    }
    launch_roles(bench, "wpaxos", config_path, config,
                 state_machine="AppendLog", overrides=overrides,
                 wal_dir=wal_dir, trace_dir=trace_dir,
                 extra_role_args=extra_role_args)
    return raw, config


def _twin_clients(transport, config, scale: TwinScale, seed: int):
    """One WPaxosClient per zone on the shared client transport, each
    stamped with its zone (the placement EWMA feed) and armed with the
    matrix's retry discipline sized for wall-clock outages."""
    from frankenpaxos_tpu.protocols.wpaxos import (
        WPaxosClient,
        WPaxosClientOptions,
    )
    from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
    from frankenpaxos_tpu.serve.backoff import Backoff

    logger = FakeLogger(LogLevel.FATAL)
    clients = []
    for z in range(len(config.leader_addresses)):
        address = (transport.listen_address if z == 0
                   else ("127.0.0.1", free_port()))
        options = WPaxosClientOptions(
            resend_period_s=1.0, adaptive_timeouts=False,
            retry_budget=6,
            reject_backoff=Backoff(initial_s=0.1, max_s=1.0,
                                   multiplier=2.0, jitter=0.5),
            zone=z)
        clients.append(WPaxosClient(address, transport, logger,
                                    config, options, seed=seed + z))
    return clients


def _keys_for_zone(config, zone: int, n: int) -> list:
    keys: list = []
    i = 0
    while len(keys) < n:
        key = b"obj-%d" % i
        if config.initial_home[config.group_of_key(key)] == zone:
            keys.append(key)
        i += 1
    return keys


def wal_chosen_payloads(wal_dir: str, raw_config: dict) -> set:
    """The WAL post-mortem: recover every acceptor's on-disk log and
    return the set of payloads provably CHOSEN -- a (group, slot,
    ballot) whose ``WalGeoVote`` records cover a row majority of some
    zone's acceptor row. An acked write missing from this set was
    acked without durable quorum evidence: the loss the clause
    hunts."""
    from frankenpaxos_tpu.protocols.multipaxos.wire import decode_value
    from frankenpaxos_tpu.wal import FileStorage, Wal
    from frankenpaxos_tpu.wal.records import WalGeoVote

    rows = raw_config["acceptors"]
    width = len(rows[0])
    majority = width // 2 + 1
    # (group, slot, ballot, zone) -> {member: value bytes}
    votes: dict = {}
    flat = 0
    for zone in range(len(rows)):
        for member in range(width):
            label = f"acceptor_{flat}"
            flat += 1
            root = os.path.join(wal_dir, label)
            if not os.path.isdir(root):
                continue
            wal = Wal(FileStorage(root))
            for record in wal.recover():
                if isinstance(record, WalGeoVote):
                    key = (record.group, record.slot, record.ballot,
                           zone)
                    votes.setdefault(key, {})[member] = record.value
            wal.close()
    chosen: set = set()
    for (_g, _s, _b, _z), members in votes.items():
        if len(members) < majority:
            continue
        value = decode_value(next(iter(members.values())))
        for command in getattr(value, "commands", ()):
            chosen.add(command.command)
    return chosen


# --- the twins ---------------------------------------------------------------


def _build_lanes(config, clients, scale: TwinScale,
                 diurnal_zone: "int | None" = None) -> list:
    lanes = []
    for z in range(len(clients)):
        keys = _keys_for_zone(config, z, 8)
        workload = OpenLoopWorkload(
            rate=scale.per_zone_rate, zipf_s=1.1, num_keys=len(keys),
            diurnal_amplitude=0.8 if z == diurnal_zone else 0.0,
            diurnal_period_s=scale.duration_s,
            diurnal_phase_s=-scale.warm_s)
        lanes.append(TwinLane(f"zone-{z}", clients[z], keys, workload))
    return lanes


def _sim_row(scenario: str, seed: int, scale: TwinScale) -> dict:
    """The sim twin, run in-process at the matrix scale whose fault
    timing this deployed scale mirrors -- the cross-check reference
    (virtual time: seconds of wall clock), and the source of the
    schedule digest the deployed row must equal."""
    from frankenpaxos_tpu.scenarios import FULL as SIM_FULL
    from frankenpaxos_tpu.scenarios import run_scenario
    from frankenpaxos_tpu.scenarios import SMOKE as SIM_SMOKE

    sim_scale = SIM_FULL if scale.name == "full" else SIM_SMOKE
    return run_scenario(scenario, seed=seed, scale=sim_scale)


def run_zone_outage_twin(out_dir: str, scale: TwinScale = SMOKE,
                         seed: int = 0) -> dict:
    """Deployed twin of ``zone_outage_peak``: SIGKILL all five of
    zone 0's role processes at the diurnal peak, relaunch after the
    dwell (acceptors recover their real WALs), same schedule builder,
    same clause shapes, wall-clock."""
    from frankenpaxos_tpu.bench.chaos import wpaxos_zone_roles
    from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
    from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport

    t_wall = time.time()
    bench = BenchmarkDirectory(os.path.join(out_dir, "zone_outage"))
    wal_dir = bench.abspath("wal")
    trace_dir = bench.abspath("trace")
    raw, config = _launch_wpaxos(bench, wal_dir=wal_dir,
                                 trace_dir=trace_dir)
    schedule = zone_outage_schedule(
        t_kill=scale.warm_s + scale.duration_s / 4,
        dwell_s=scale.outage_dwell_s, zone=0, seed=seed)
    backend = DeployedBackend(
        bench, zone_roles={0: wpaxos_zone_roles(raw, 0)})
    runner = ScheduleRunner(schedule, backend)

    transport = None
    try:
        transport = TcpTransport(("127.0.0.1", free_port()),
                                 FakeLogger(LogLevel.FATAL))
        transport.start()
        clients = _twin_clients(transport, config, scale, seed)
        driver = DeployedLaneDriver(
            transport, _build_lanes(config, clients, scale,
                                    diurnal_zone=0), seed=seed)
        chaos = run_wall(runner)
        driver.run(scale.duration_s, scale.warm_s,
                   scale.sessions_per_lane)
        chaos.join(timeout=60)
        pending = driver.settle(scale.settle_s)
        stats = driver.lane_stats(scale.warm_s, scale.duration_s)
        t_restart = next(
            t for t, e in runner.fired if e.kind == "restart_zone")
        recovery = driver.recovery_after(0, t_restart)
    finally:
        if transport is not None:
            transport.stop()
        bench.cleanup()

    # The WAL post-mortem (after cleanup: every role exited, logs
    # quiesced on disk).
    chosen = wal_chosen_payloads(wal_dir, raw)
    lost = [p for p in driver.acked if p not in chosen]

    sim = _sim_row("zone_outage_peak", seed, scale)
    sim_fraction = (sim["stats"]["completed_in_slo"]
                    / max(1, sim["stats"]["issued"]))
    offered = 3 * scale.per_zone_rate
    surviving = [stats["lanes"]["zone-1"], stats["lanes"]["zone-2"]]
    surviving_p99 = max((lane["p99_admitted_s"] or 0.0)
                        for lane in surviving) \
        if any(lane["p99_admitted_s"] is not None
               for lane in surviving) else None
    clauses = {
        "goodput_floor": clause(stats["goodput_cmds_per_s"],
                                0.5 * offered, "min"),
        "surviving_p99_admitted_ceiling_s": clause(
            surviving_p99, SLO_DEADLINE_S),
        "zero_acked_write_loss": clause(len(lost), 0, "zero"),
        "no_silent_wedge": clause(pending, 0, "zero"),
        "bounded_recovery_s": clause(
            recovery, CROSS_CHECK_RECOVERY_MULT
            * sim["slo"]["bounded_recovery_s"]["bound"]),
        "cross_check_in_slo_fraction": clause(
            stats["in_slo_fraction"],
            round(CROSS_CHECK_GOODPUT_FRACTION * sim_fraction, 4),
            "min"),
    }
    row = {
        "scenario": "zone_outage_peak/deployed",
        "seed": seed,
        "scale": scale.name,
        "fault_schedule_sha256": schedule.digest(),
        "sim_fault_schedule_sha256":
            sim["events"]["fault_schedule_sha256"],
        "schedule_matches_sim":
            schedule.digest() == sim["events"]["fault_schedule_sha256"],
        "wall_seconds": round(time.time() - t_wall, 1),
        "stats": stats,
        "events": {
            "applied": backend.applied,
            "recovery_after_relaunch_s": recovery,
            "acked_writes": len(driver.acked),
            "wal_chosen_payloads": len(chosen),
            "control_plane_never_shed": "structural (client-lane-only "
                                        "shedding; tests/test_serve.py)",
        },
        "sim_row": {"stats": sim["stats"], "slo": sim["slo"],
                    "gate_passed": sim["gate_passed"]},
        "slo": clauses,
        "artifacts": {"bench_dir": bench.path,
                      "trace_dir": trace_dir},
    }
    row["gate_passed"] = (all(c["passed"] for c in clauses.values())
                          and row["schedule_matches_sim"]
                          and sim["gate_passed"])
    return row


def run_fsync_stall_twin(out_dir: str, scale: TwinScale = SMOKE,
                         seed: int = 0) -> dict:
    """Deployed twin of ``fsync_stalls``: the same schedule arms a
    BLOCKING FsyncStallStorage over two of zone 0's acceptors' real
    FileStorage WALs (one stalls alone -- row quorum masks; the
    other's stalls overlap -- only those reach the tail), against a
    same-seed fault-off arm; the p999 amplification must reproduce
    wall-clock."""
    from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
    from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport

    t_wall = time.time()
    schedule = fsync_stall_schedule(zone=0, seed=seed)
    width = 3  # f=1 rows

    def acceptor_label(zone: int, member: int) -> str:
        return f"acceptor_{zone * width + member}"

    arms = {}
    for arm in ("fault_off", "fault_on"):
        bench = BenchmarkDirectory(
            os.path.join(out_dir, f"fsync_{arm}"))
        wal_dir = bench.abspath("wal")
        extra = (fsync_fault_args(schedule, acceptor_label)
                 if arm == "fault_on" else None)
        raw, config = _launch_wpaxos(bench, wal_dir=wal_dir,
                                     extra_role_args=extra)
        transport = None
        try:
            transport = TcpTransport(("127.0.0.1", free_port()),
                                     FakeLogger(LogLevel.FATAL))
            transport.start()
            clients = _twin_clients(transport, config, scale, seed)
            driver = DeployedLaneDriver(
                transport, _build_lanes(config, clients, scale),
                seed=seed)
            driver.run(scale.duration_s, scale.warm_s,
                       scale.sessions_per_lane)
            pending = driver.settle(scale.settle_s)
            stats = driver.lane_stats(scale.warm_s, scale.duration_s)
            band = driver.lane_band_fraction(
                0, scale.warm_s, scale.duration_s, STALL_BAND_S)
        finally:
            if transport is not None:
                transport.stop()
            bench.cleanup()
        chosen = wal_chosen_payloads(wal_dir, raw)
        lost = [p for p in driver.acked if p not in chosen]
        arms[arm] = {"stats": stats, "pending": pending,
                     "lost": len(lost),
                     "zone0_stall_band_fraction": band,
                     "acked": len(driver.acked)}

    on, off = arms["fault_on"], arms["fault_off"]
    p999_on = on["stats"]["lanes"]["zone-0"]["p999_admitted_s"]
    p999_off = off["stats"]["lanes"]["zone-0"]["p999_admitted_s"]
    amplification = (round(p999_on / p999_off, 2)
                     if p999_on and p999_off else None)
    band_on = on["zone0_stall_band_fraction"]
    band_off = off["zone0_stall_band_fraction"]
    sim = _sim_row("fsync_stalls", seed, scale)
    offered = 3 * scale.per_zone_rate
    clauses = {
        "goodput_floor": clause(
            on["stats"]["goodput_cmds_per_s"], 0.6 * offered, "min"),
        "zero_acked_write_loss": clause(
            on["lost"] + off["lost"], 0, "zero"),
        "no_silent_wedge": clause(on["pending"] + off["pending"], 0,
                                  "zero"),
        # The tail pathology REPRODUCES wall-clock: the faulted
        # zone's stall-band occupancy (completions >= 0.75x the stall
        # length) is both non-trivial and a multiple of the fault-off
        # arm's scheduler-noise floor. A raw p999 ratio would gate on
        # a loaded CI host's scheduler, not on the fault.
        "stall_band_reproduces": clause(band_on, 0.012, "min"),
        "stall_band_attributable": clause(
            band_on, round(max(0.012,
                               CROSS_CHECK_AMPLIFICATION_MIN
                               * band_off), 4), "min"),
        "p999_bounded_s": clause(p999_on, SLO_DEADLINE_S),
    }
    row = {
        "scenario": "fsync_stalls/deployed",
        "seed": seed,
        "scale": scale.name,
        "fault_schedule_sha256": schedule.digest(),
        "sim_fault_schedule_sha256":
            sim["events"]["fault_schedule_sha256"],
        "schedule_matches_sim":
            schedule.digest() == sim["events"]["fault_schedule_sha256"],
        "wall_seconds": round(time.time() - t_wall, 1),
        "arms": arms,
        "events": {
            "p999_amplification": amplification,
            "p999_fault_off_s": p999_off,
            "stall_band_fraction_on": band_on,
            "stall_band_fraction_off": band_off,
            "sim_amplification":
                sim["events"]["p999_amplification"],
            "sim_affected_fraction":
                sim["events"]["zone0_affected_fraction"],
        },
        "sim_row": {"stats": sim["stats"], "slo": sim["slo"],
                    "gate_passed": sim["gate_passed"]},
        "slo": clauses,
    }
    row["gate_passed"] = (all(c["passed"] for c in clauses.values())
                          and row["schedule_matches_sim"]
                          and sim["gate_passed"])
    return row


class _MultiPaxosLaneClient:
    """DeployedLaneDriver adapter over a multipaxos ``Client``: the
    driver speaks ``write(pseudonym, payload, cb, key=...)``,
    ``pending``, and patches ``_handle_rejected``; multipaxos routes
    by (client, pseudonym) through the ingest ring, so the wpaxos
    locality ``key`` is dropped and the pending map is ``states``.
    The ``_handle_rejected`` property proxies to the INNER actor so
    the driver's rejection hook patches the real dispatch path
    (Client.receive looks the handler up on self)."""

    def __init__(self, inner):
        self._inner = inner

    def write(self, pseudonym: int, payload: bytes, callback,
              key=None) -> None:
        self._inner.write(pseudonym, payload, callback)

    @property
    def pending(self) -> dict:
        return self._inner.states

    @property
    def _handle_rejected(self):
        return self._inner._handle_rejected

    @_handle_rejected.setter
    def _handle_rejected(self, fn) -> None:
        self._inner._handle_rejected = fn

    @property
    def fan(self):
        return self._inner._fan


def _handoff_clients(transport, config, seed: int, lanes: int = 3):
    """One multipaxos client per lane on the shared transport, armed
    with the twin retry discipline (budgeted retries, Rejected
    backoff) and a 1s resend period -- the ring-failover detection
    clock the clause budget is sized against."""
    from frankenpaxos_tpu.protocols.multipaxos.client import (
        Client,
        ClientOptions,
    )
    from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
    from frankenpaxos_tpu.serve.backoff import Backoff

    logger = FakeLogger(LogLevel.FATAL)
    options = ClientOptions(
        resend_client_request_period_s=1.0, retry_budget=6,
        backoff=Backoff(initial_s=0.1, max_s=1.0, multiplier=2.0,
                        jitter=0.5))
    clients = []
    for z in range(lanes):
        address = (transport.listen_address if z == 0
                   else ("127.0.0.1", free_port()))
        clients.append(_MultiPaxosLaneClient(Client(
            address, transport, logger, config, options,
            seed=seed + z)))
    return clients


def run_ingest_handoff_twin(out_dir: str, scale: TwinScale = SMOKE,
                            seed: int = 0) -> dict:
    """paxfan failover twin: SIGKILL ingest-batcher shard 1 of the
    15-role multipaxos serving cluster MID-DESCRIPTOR-HANDOFF (staged
    columns and un-credited IngestRuns die with the process), relaunch
    after the dwell, wall-clock. The dead shard's pinned sessions must
    fail over to the clockwise ring survivors on their resend timeout
    (``failover_exercised`` asserts the ring actually moved) and the
    WAL post-mortem must show the outage cost RETRIES, never acked
    loss. Deployed-only: the sim chaos soak covers this plan's virtual
    twin (tests/protocols/test_ingest_chaos.py), so the row records
    its schedule digest with no sim cross-check."""
    from frankenpaxos_tpu.bench.deployed_serving_lt import (
        launch_multipaxos_serving,
        wal_chosen_payloads_multipaxos,
    )
    from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
    from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport

    t_wall = time.time()
    bench = BenchmarkDirectory(os.path.join(out_dir, "ingest_handoff"))
    wal_dir = bench.abspath("wal")
    raw, config, _labels = launch_multipaxos_serving(
        bench, wal_dir=wal_dir,
        admission_token_rate=40.0 * scale.per_zone_rate)
    # The kill lands a quarter into the measured window: batcher 1 is
    # mid-stream (staged commands + in-flight descriptor windows).
    schedule = ingest_handoff_schedule(
        t_kill=scale.warm_s + scale.duration_s / 4,
        dwell_s=scale.outage_dwell_s, shard=1, seed=seed)
    backend = DeployedBackend(bench,
                              zone_roles={1: ["ingest_batcher_1"]})
    runner = ScheduleRunner(schedule, backend)

    transport = None
    try:
        transport = TcpTransport(("127.0.0.1", free_port()),
                                 FakeLogger(LogLevel.FATAL))
        transport.start()
        clients = _handoff_clients(transport, config, seed)
        lanes = []
        for z, client in enumerate(clients):
            workload = OpenLoopWorkload(
                rate=scale.per_zone_rate, zipf_s=1.1, num_keys=8,
                diurnal_amplitude=0.0,
                diurnal_period_s=scale.duration_s,
                diurnal_phase_s=-scale.warm_s)
            lanes.append(TwinLane(f"lane-{z}", client, [b"x"],
                                  workload))
        driver = DeployedLaneDriver(transport, lanes, seed=seed)
        chaos = run_wall(runner)
        driver.run(scale.duration_s, scale.warm_s,
                   scale.sessions_per_lane)
        chaos.join(timeout=60)
        pending = driver.settle(scale.settle_s)
        stats = driver.lane_stats(scale.warm_s, scale.duration_s)
        failovers = sum(c.fan.failovers for c in clients
                        if c.fan is not None)
        t_restart = next(
            t for t, e in runner.fired if e.kind == "restart_zone")
        recovery = driver.recovery_after(0, t_restart)
    finally:
        if transport is not None:
            transport.stop()
        bench.cleanup()

    chosen = wal_chosen_payloads_multipaxos(wal_dir, raw)
    lost = [p for p in driver.acked if p not in chosen]

    offered = len(lanes) * scale.per_zone_rate
    clauses = {
        "goodput_floor": clause(stats["goodput_cmds_per_s"],
                                0.5 * offered, "min"),
        "zero_acked_write_loss": clause(len(lost), 0, "zero"),
        "no_silent_wedge": clause(pending, 0, "zero"),
        # The ring MOVED: at least one client suspected the dead shard
        # and failed its keys over to a clockwise survivor.
        "failover_exercised": clause(failovers, 1, "min"),
    }
    row = {
        "scenario": "ingest_handoff/deployed",
        "seed": seed,
        "scale": scale.name,
        "fault_schedule_sha256": schedule.digest(),
        "wall_seconds": round(time.time() - t_wall, 1),
        "stats": stats,
        "events": {
            "applied": backend.applied,
            "ring_failovers": failovers,
            "recovery_after_relaunch_s": recovery,
            "acked_writes": len(driver.acked),
            "wal_chosen_payloads": len(chosen),
            "control_plane_never_shed": "structural (client-lane-only "
                                        "shedding; tests/test_serve.py)",
        },
        "slo": clauses,
    }
    row["gate_passed"] = all(c["passed"] for c in clauses.values())
    return row


TWINS = {
    "zone_outage": run_zone_outage_twin,
    "fsync_stalls": run_fsync_stall_twin,
    "ingest_handoff": run_ingest_handoff_twin,
}


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--scenario", default="all",
                        choices=["all"] + sorted(TWINS))
    parser.add_argument("--smoke", action="store_true")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default=None)
    parser.add_argument("--work_dir", default=None)
    args = parser.parse_args(argv)

    scale = SMOKE if args.smoke else FULL
    work_dir = args.work_dir or os.path.join(
        "deployed_twin_work", str(int(time.time())))
    rows = []
    names = sorted(TWINS) if args.scenario == "all" else [args.scenario]
    for name in names:
        # One retry on a lost startup race (a role process losing the
        # scheduling lottery on a loaded CI host is an artifact, not
        # a twin failure) -- the same policy the deployment smoke
        # uses; the retry runs in a fresh directory with fresh ports.
        for attempt in (1, 2):
            try:
                row = TWINS[name](os.path.join(work_dir,
                                               f"attempt{attempt}"),
                                  scale=scale, seed=args.seed)
                break
            except RuntimeError as e:
                print(f"twin {name} attempt {attempt} failed: {e}",
                      flush=True)
                if attempt == 2:
                    raise
        print(json.dumps({"scenario": row["scenario"],
                          "gate_passed": row["gate_passed"],
                          "wall_seconds": row["wall_seconds"]}),
              flush=True)
        rows.append(row)
    result = {
        "benchmark": "deployed_twin",
        "host_cpus": os.cpu_count(),
        "scale": scale.name,
        "tolerance_band": {
            "in_slo_fraction_vs_sim": CROSS_CHECK_GOODPUT_FRACTION,
            "recovery_mult_vs_sim_bound": CROSS_CHECK_RECOVERY_MULT,
            "amplification_min": CROSS_CHECK_AMPLIFICATION_MIN,
        },
        "rows": rows,
        "gate_passed": all(r["gate_passed"] for r in rows),
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    print(json.dumps({"gate_passed": result["gate_passed"],
                      "rows": {r["scenario"]: r["gate_passed"]
                               for r in rows}}, indent=2))
    return result


if __name__ == "__main__":
    raise SystemExit(0 if main()["gate_passed"] else 1)
