"""Library microbenchmarks: the hot host data structures + device ops.

The reference ships ScalaMeter benches for its data structures
(jvm/src/bench/scala/frankenpaxos/: BufferMapBench, IntPrefixSetBench,
DependencyGraphBench, VertexIdPrefixSetBench). This is the analog:
per-structure operation throughput, committed as
``bench_results/libbench.json`` so regressions become visible
round-over-round.

Covered: BufferMap put/get/GC, IntPrefixSet add/union/materialized
diff, the three dependency-graph implementations on the EPaxos commit ->
execute shape, the watermark/depset device kernels, and the wire
serializer (binary vs pickle on the hottest message).

Usage::

    python -m frankenpaxos_tpu.bench.libbench \
        --out bench_results/libbench.json
"""

from __future__ import annotations

import argparse
import json
import time


def _rate(n: int, f) -> float:
    """ops/s of f() (which performs n operations), best of 3."""
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        f()
        best = min(best, time.perf_counter() - t0)
    return n / best


def bench_buffer_map(n: int = 200_000) -> dict:
    from frankenpaxos_tpu.utils.buffer_map import BufferMap

    def puts():
        m = BufferMap(grow_size=5000)
        for i in range(n):
            m.put(i, i)

    filled = BufferMap(grow_size=5000)
    for i in range(n):
        filled.put(i, i)

    def gets():
        for i in range(n):
            filled.get(i)

    def put_gc():
        m = BufferMap(grow_size=5000)
        for i in range(n):
            m.put(i, i)
            if i % 10_000 == 9_999:
                m.garbage_collect(i - 5_000)

    return {"put_ops_per_s": round(_rate(n, puts)),
            "get_ops_per_s": round(_rate(n, gets)),
            "put_gc_ops_per_s": round(_rate(n, put_gc))}


def bench_int_prefix_set(n: int = 200_000) -> dict:
    from frankenpaxos_tpu.compact import IntPrefixSet

    def adds_in_order():
        s = IntPrefixSet()
        for i in range(n):
            s.add(i)

    def adds_scattered():
        s = IntPrefixSet()
        for i in range(0, 2 * n, 2):
            s.add(i)

    a = IntPrefixSet.from_watermark(n)
    b = IntPrefixSet.from_watermark(n // 2)
    for i in range(n // 2, n, 7):
        b.add(i)

    def diffs():
        for _ in range(200):
            list(a.materialized_diff(b))

    diff_items = 200 * len(list(a.materialized_diff(b)))
    return {"add_in_order_ops_per_s": round(_rate(n, adds_in_order)),
            "add_scattered_ops_per_s": round(_rate(n, adds_scattered)),
            "materialized_diff_items_per_s": round(
                _rate(diff_items, diffs))}


def bench_depgraphs(n: int = 20_000, conflict_stride: int = 10) -> dict:
    """EPaxos shape: command i depends on the previous command touching
    its key (i - conflict_stride), committed in order, executed in
    batches (DependencyGraphBench's commit/execute mix)."""
    from frankenpaxos_tpu.depgraph import make_dependency_graph

    out = {}
    for name in ("tarjan", "incremental", "zigzag", "naive"):
        # The naive oracle is quadratic; keep its input small.
        size = n if name != "naive" else n // 20

        def run_sized(name=name, size=size):
            if name == "zigzag":
                # Zigzag keys decompose into (leader, id) vertex ids.
                g = make_dependency_graph(name, num_leaders=1)
                key = (lambda i: (0, i))
            else:
                g = make_dependency_graph(name)
                key = (lambda i: i)
            for i in range(size):
                deps = ([key(i - conflict_stride)]
                        if i >= conflict_stride else [])
                g.commit(key(i), 0, deps)
                if i % 100 == 99:
                    g.execute()
            g.execute()

        out[f"{name}_commit_execute_ops_per_s"] = round(
            _rate(size, run_sized))
    return out


def bench_device_ops(batch: int = 4096, iters: int = 50) -> dict:
    """The watermark + depset kernels (device twins of QuorumWatermark /
    EPaxos dep sets) at a realistic batch width."""
    import numpy as np

    from frankenpaxos_tpu.ops.depset import DepSetBatch, union, union_reduce
    from frankenpaxos_tpu.ops.watermark import (
        contiguous_prefix_length,
        quorum_watermark_vector,
    )

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.ops.watermark import quorum_watermark

    watermarks = np.random.default_rng(0).integers(
        0, 1 << 20, size=(5, batch)).astype(np.int32)
    quorum_watermark_vector(watermarks, 3)  # compile + sync-path check
    watermarks_dev = jnp.asarray(watermarks.T)  # [batch, nodes]
    quorum_size = jnp.int32(3)

    def watermark_run():
        outs = [quorum_watermark(watermarks_dev, quorum_size)
                for _ in range(iters)]
        jax.block_until_ready(outs)

    present = np.ones(batch, dtype=bool)
    present[batch // 2] = False
    present_dev = jnp.asarray(present)
    contiguous_prefix_length(present_dev)  # compile

    # Device runs chain all iterations and sync ONCE: a per-iteration
    # fetch would measure the device-link RTT, not the kernel (the
    # accelerator sits across a tunnel in this environment).
    def prefix_run():
        outs = [contiguous_prefix_length(present_dev)
                for _ in range(iters)]
        jax.block_until_ready(outs)

    rng = np.random.default_rng(1)
    leaders, window = 3, 64
    deps = DepSetBatch(
        watermarks=jnp.asarray(rng.integers(
            0, 1 << 16, size=(batch, leaders)), dtype=jnp.int32),
        tails=jnp.asarray(rng.integers(
            0, 2, size=(batch, leaders, window)), dtype=jnp.uint8),
        tail_base=jnp.int32(1 << 16))
    np.asarray(union(deps, deps).watermarks)  # compile
    np.asarray(union_reduce(deps).watermarks)

    def depset_run():
        outs = [union_reduce(union(deps, deps)).watermarks
                for _ in range(iters)]
        jax.block_until_ready(outs)

    return {
        "quorum_watermark_slots_per_s": round(
            _rate(iters * batch, watermark_run)),
        "contiguous_prefix_slots_per_s": round(
            _rate(iters * batch, prefix_run)),
        "depset_union_reduce_deps_per_s": round(
            _rate(iters * batch, depset_run)),
    }


def bench_serializer(n: int = 50_000) -> dict:
    import frankenpaxos_tpu.protocols.multipaxos  # noqa: F401 - codecs
    from frankenpaxos_tpu.protocols.multipaxos.messages import Phase2b
    from frankenpaxos_tpu.runtime.serializer import (
        DEFAULT_SERIALIZER,
        PickleSerializer,
    )

    message = Phase2b(group_index=1, acceptor_index=2, slot=123456,
                      round=3)

    def binary():
        s = DEFAULT_SERIALIZER
        for _ in range(n):
            s.from_bytes(s.to_bytes(message))

    def pickled():
        s = PickleSerializer()
        for _ in range(n):
            s.from_bytes(s.to_bytes(message))

    return {"phase2b_binary_roundtrips_per_s": round(_rate(n, binary)),
            "phase2b_pickle_roundtrips_per_s": round(_rate(n, pickled))}


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    # Probe the accelerator link BEFORE any in-process jax use (a
    # wedged link hangs jax.devices() itself). On a dead/absent link,
    # run the device kernels on labeled local CPU XLA -- the same
    # degradation policy as bench.py -- which also keeps the XLA
    # runtime resident either way, so the serializer rows (measured
    # after, and ~10% slower with XLA's thread pool live on a 1-CPU
    # host) stay comparable round over round.
    from frankenpaxos_tpu.bench.device_probe import device_probe

    available, probe_note = device_probe()
    if not available:
        import jax

        jax.config.update("jax_platforms", "cpu")
    device_ops = bench_device_ops()
    if not available:
        device_ops["note"] = (
            f"accelerator unavailable ({probe_note}); ran on local "
            f"CPU XLA -- not comparable to device-run rows")
    result = {
        "benchmark": "libbench",
        "buffer_map": bench_buffer_map(),
        "int_prefix_set": bench_int_prefix_set(),
        "depgraph": bench_depgraphs(),
        "device_ops": device_ops,
        "serializer": bench_serializer(),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
