"""depset_lt: paired A/B of the coalesced EPaxos dependency plane vs
the per-message path (docs/RUN_PIPELINE.md).

    python -m frankenpaxos_tpu.bench.depset_lt \
        --out bench_results/depset_lt.json

Methodology (the multipaxos_lt alternating-chunk shape): per in-flight
width, the SAME drain of PreAcceptOk replies -- realistic seq/deps
payloads around a moving executed watermark -- is processed by two
leader-edge arms in one process:

  * ``per_message`` (baseline -- today's deployed path): every tag-15
    payload decodes through ``PreAcceptOkCodec`` into an
    ``InstancePrefixSet``-carrying message, then the slow-path
    aggregation runs as the host loop the replica runs today:
    ``seq = max(seqs)`` plus ``deps.add_all`` per reply
    (epaxos/Replica.scala:795-813). One Python object graph and one
    host set-walk PER MESSAGE.
  * ``coalesced``: the drain arrives as ONE ``PreAcceptOkRun`` frame
    (runs/wire.py tag 208 -- the paxwire flush coalescer folded it on
    the sending side, so frame production is not this receiver's
    cost): one fixed-layout decode, one ``columns_to_batch`` scatter
    into a ``[B, L, W]`` DepSetBatch, and one fused
    ``ops/depset.conflict_max`` reduction for the whole drain.

Both arms consume pre-encoded wire bytes (the load generator must not
cap the plane under test) and produce the same (sequence number,
dependency set) aggregate; the bench asserts the two results are
BIT-IDENTICAL every chunk before timing counts -- a throughput win
that changes the answer is a bug, not a result.

Chunks alternate arm order with GC off (the multipaxos_lt / overload
calibration: frequency and allocator drift land on both arms equally)
and the per-arm figure is the median over blocks. The sender-side
coalesce cost (decode + column build + run encode at the remote
replica's flush) is excluded from the gate but measured and recorded
as ``coalesce_encode_per_msg_us`` so the report stays honest about
where the work moved.

Committed gates (ISSUE 18 acceptance):
  * coalesced/per_message throughput >= 2x at every width >= 1024;
  * host and device aggregates bit-identical at every width.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import random
import statistics
import time

import numpy as np

from frankenpaxos_tpu.compact import IntPrefixSet
from frankenpaxos_tpu.ops import depset
import frankenpaxos_tpu.protocols.epaxos  # noqa: F401 (codecs + runs/wire)
from frankenpaxos_tpu.protocols.epaxos.instance_prefix_set import (
    Instance,
    InstancePrefixSet,
)
from frankenpaxos_tpu.protocols.epaxos.messages import PreAcceptOk
from frankenpaxos_tpu.runs import depruns
from frankenpaxos_tpu.runs.wire import _coalesce_pre_accept_ok
from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER

WIDTHS = (256, 1024, 4096)
NUM_LEADERS = 3  # f=1 EPaxos: n = 3 dependency columns per set
TAIL_SPAN = 24  # sparse ids live within this window above the base


def make_drain(width: int, rng: random.Random) -> list:
    """One drain of ``width`` PreAcceptOks: per-column watermarks near
    a shared executed frontier, a few sparse tail ids above it, and
    random conflict sequence numbers -- the steady-state shape the
    replica's slow path sees."""
    base = rng.randrange(1000, 2000)
    messages = []
    for i in range(width):
        columns = []
        for _ in range(NUM_LEADERS):
            watermark = base + rng.randrange(0, 4)
            tail = {base + rng.randrange(4, TAIL_SPAN)
                    for _ in range(rng.randrange(0, 4))}
            columns.append(IntPrefixSet(watermark,
                                        {v for v in tail
                                         if v >= watermark}))
        deps = InstancePrefixSet(NUM_LEADERS, columns)
        messages.append(PreAcceptOk(
            instance=Instance(i % NUM_LEADERS, base + i),
            ballot=(0, i % NUM_LEADERS),
            replica_index=i % NUM_LEADERS,
            sequence_number=rng.randrange(0, 1 << 20),
            dependencies=deps))
    return messages


def host_aggregate(messages: list) -> tuple:
    """The per-message slow-path loop, verbatim host semantics."""
    union = InstancePrefixSet(NUM_LEADERS)
    seq = 0
    for message in messages:
        seq = max(seq, message.sequence_number)
        union.add_all(message.dependencies)
    return seq, union


def run_per_message(payloads: list) -> tuple:
    """Arm A: decode every payload, then the host aggregation."""
    from_bytes = DEFAULT_SERIALIZER.from_bytes
    messages = [from_bytes(p) for p in payloads]
    return host_aggregate(messages)


def run_coalesced(run_payload: bytes) -> tuple:
    """Arm B: one run decode -> one scatter -> one fused reduction."""
    import jax.numpy as jnp

    run = DEFAULT_SERIALIZER.from_bytes(run_payload)
    batch = depruns.columns_to_batch(run.num_leaders, run.watermarks,
                                     run.counts, run.values)
    seqs = jnp.asarray([h[5] for h in run.headers], dtype=jnp.int32)
    seq, reduced = depset.conflict_max(seqs, batch)
    return int(seq), reduced


def device_to_host_set(reduced) -> InstancePrefixSet:
    from frankenpaxos_tpu.protocols.epaxos import device_deps

    return device_deps.from_row(np.asarray(reduced.watermarks)[0],
                                np.asarray(reduced.tails)[0],
                                int(reduced.tail_base))


def run_pair(width: int, blocks: int, drains_per_block: int,
             seed: int) -> dict:
    rng = random.Random(seed)
    to_bytes = DEFAULT_SERIALIZER.to_bytes
    # Pre-encode every drain's wire bytes outside the measured window;
    # time and record the sender-side coalesce separately.
    drains = []
    coalesce_s = 0.0
    for _ in range(drains_per_block):
        messages = make_drain(width, rng)
        payloads = [to_bytes(m) for m in messages]
        t0 = time.perf_counter()
        run_payload = _coalesce_pre_accept_ok(payloads)
        coalesce_s += time.perf_counter() - t0
        assert run_payload is not None, "coalescer declined uniform drain"
        drains.append((messages, payloads, run_payload))

    # Oracle bit-identity on every drain BEFORE any timing counts.
    for messages, payloads, run_payload in drains:
        host_seq, host_union = host_aggregate(messages)
        msg_seq, msg_union = run_per_message(payloads)
        dev_seq, reduced = run_coalesced(run_payload)
        assert (msg_seq, msg_union) == (host_seq, host_union)
        assert dev_seq == host_seq, (dev_seq, host_seq)
        assert device_to_host_set(reduced) == host_union

    per_block: dict = {"per_message": [], "coalesced": []}
    gc_was_enabled = gc.isenabled()
    gc.disable()
    try:
        for block in range(blocks):
            arms = (("per_message", "coalesced") if block % 2 == 0
                    else ("coalesced", "per_message"))
            for arm in arms:
                t0 = time.perf_counter()
                if arm == "per_message":
                    for _, payloads, _ in drains:
                        run_per_message(payloads)
                else:
                    for _, _, run_payload in drains:
                        run_coalesced(run_payload)
                elapsed = time.perf_counter() - t0
                per_block[arm].append(
                    width * drains_per_block / elapsed)
    finally:
        if gc_was_enabled:
            gc.enable()
    pair = {
        arm: {
            "arm": arm,
            "in_flight": width,
            "msgs_per_s": statistics.median(rates),
            "blocks_msgs_per_s": rates,
        }
        for arm, rates in per_block.items()
    }
    pair["throughput_ratio"] = (pair["coalesced"]["msgs_per_s"]
                                / pair["per_message"]["msgs_per_s"])
    pair["oracle_bit_identical"] = True  # asserted above, every drain
    pair["coalesce_encode_per_msg_us"] = (
        coalesce_s / (width * drains_per_block) * 1e6)
    return pair


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(
        description="coalesced EPaxos depset A/B (docs/RUN_PIPELINE.md)")
    parser.add_argument("--out", default=None)
    parser.add_argument("--smoke", action="store_true",
                        help="reduced widths/drains (~30 s)")
    parser.add_argument("--blocks", type=int, default=7)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    widths = (1024,) if args.smoke else WIDTHS
    blocks = 3 if args.smoke else args.blocks
    pairs: dict = {}
    for width in widths:
        drains_per_block = max(2, (4 if args.smoke else 16)
                               * 1024 // width)
        # Warm the jitted reduction for this batch shape outside the
        # measured blocks (compilation must not land in either arm).
        warm = make_drain(width, random.Random(args.seed + 99))
        run_coalesced(_coalesce_pre_accept_ok(
            [DEFAULT_SERIALIZER.to_bytes(m) for m in warm]))
        pairs[width] = run_pair(width, blocks, drains_per_block,
                                args.seed)
        p = pairs[width]
        print(f"in_flight={width:5d}: per_message "
              f"{p['per_message']['msgs_per_s']:9.0f}/s "
              f"coalesced {p['coalesced']['msgs_per_s']:9.0f}/s "
              f"ratio {p['throughput_ratio']:.2f}x  "
              f"coalesce-cost "
              f"{p['coalesce_encode_per_msg_us']:.2f}us/msg")
    gate_widths = {w: pairs[w]["throughput_ratio"]
                   for w in pairs if w >= 1024}
    gates = {
        "throughput_ratio_at_ge_1024": {
            str(w): r for w, r in gate_widths.items()},
        "throughput_2x_passed": all(r >= 2.0
                                    for r in gate_widths.values()),
        "oracle_bit_identical": all(
            pairs[w]["oracle_bit_identical"] for w in pairs),
    }
    gates["gate_passed"] = (gates["throughput_2x_passed"]
                            and gates["oracle_bit_identical"])
    result = {
        "benchmark": "depset_lt",
        "methodology": (
            "paired in-process A/B, alternating-chunk with GC off "
            "(multipaxos_lt calibration): identical pre-encoded "
            "drains of EPaxos PreAcceptOk replies drive (a) the "
            "per-message baseline -- PreAcceptOkCodec decode + the "
            "replica's host max/add_all slow-path loop per reply -- "
            "and (b) the coalesced plane: one PreAcceptOkRun frame "
            "(runs/wire.py, folded sender-side by the paxwire flush "
            "coalescer) -> one columns_to_batch scatter -> one fused "
            "ops/depset.conflict_max reduction per drain. Both arms' "
            "(seq, deps) aggregates are asserted bit-identical per "
            "drain before timing. Per-arm figure: median msgs/s over "
            "alternating blocks. Sender-side coalesce cost is "
            "excluded from the gate (it rides the remote flush) but "
            "recorded as coalesce_encode_per_msg_us."),
        "smoke": bool(args.smoke),
        "blocks": blocks,
        "num_leaders": NUM_LEADERS,
        "pairs": {str(w): pairs[w] for w in sorted(pairs)},
        "gates": gates,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    print(f"gate_passed={gates['gate_passed']}")
    return result


if __name__ == "__main__":
    main()
