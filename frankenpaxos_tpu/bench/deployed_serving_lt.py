"""deployed_serving_lt: the paxfan deployed million-session serving gate.

    python -m frankenpaxos_tpu.bench.deployed_serving_lt \
        --out bench_results/deployed_serving_lt.json

The headline gate of the scale-out ingestion fabric (ingest/fan.py,
docs/TRANSPORT.md "Scale-out fan-in"): a SoA open-loop session tier --
a 1M+-pseudonym population, Zipf session heat, diurnal rate ramp --
drives a REAL 15-role multipaxos cluster (3 leaders, 3 proxy leaders,
3 acceptors with on-disk WALs, 2 replicas, 4 ingest batchers; every
role its own OS process over TCP) through the consistent batcher
ring, sweeping the live batcher count 1 -> 2 -> 4. Per "Paxos in the
Cloud" (PAPERS.md) the headline is NOT a peak-throughput number: each
arm is gated by wall-clock SLO clauses --

  * goodput floor: in-SLO admitted completions/s >= a fraction of the
    arm's OFFERED rate (open loop: arrivals never self-throttle);
  * admitted p99 ceiling: sessions the cluster admitted (never drew a
    ``serve.Rejected``) must finish under the SLO deadline;
  * zero acked loss, by WAL POST-MORTEM: after teardown every
    acceptor's on-disk log is recovered in-process and every
    client-acked payload must be provably CHOSEN (a same-(slot, round)
    majority of durable ``WalVote``/``WalVoteRun`` records in its
    group) -- an ack without durable quorum evidence is the loss this
    oracle hunts;
  * every session concludes loudly: after the measured window the tier
    settles until zero commands remain in flight (resends ride the
    replica client-table dedupe) -- leftover in-flight = silent wedge;
  * control never shed: structural in the deployed world (the
    transport sheds client-lane frames only and IngestCredit rides the
    control lane by construction; tests/test_serve.py,
    tests/protocols/test_ingest_chaos.py) and recorded as such.

The sweep clause is the scale-OUT claim itself: each arm offers
``base_rate x N`` so a single shard's absorb rate is the arm-1
ceiling, and the 4-batcher arm must carry >= 2x the 1-batcher arm's
goodput while holding the same clauses.

Python-bytes/cmd discipline (the ingest_lt convention, both paths
measured at the tier): commands ship as pre-encoded tag-115
ClientRequestArray frames -- per frame, Python formats only the
count word against a cached header prefix -- and replies land through
the tag-118 column scan (``ingest.columns.parse_reply_array``,
native ``fpx_reply_columns``): Python touches the 5-byte frame
header, numpy does the rest. Both per-command figures must hold the
paxingest ~0.1 floor (rejected entries and batch-container copies are
charged in full, so a shedding cluster pays its Python honestly).
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import threading
import time

import numpy as np

from frankenpaxos_tpu import native
from frankenpaxos_tpu.bench.harness import BenchmarkDirectory, free_port
from frankenpaxos_tpu.bench.workload import OpenLoopWorkload
from frankenpaxos_tpu.ingest import BatcherRing, stable_key
from frankenpaxos_tpu.ingest.columns import parse_reply_array
import frankenpaxos_tpu.protocols.multipaxos  # noqa: F401 (codecs)
from frankenpaxos_tpu.protocols.multipaxos.wire import _put_address
from frankenpaxos_tpu.runtime import FakeLogger
from frankenpaxos_tpu.runtime.actor import Actor
from frankenpaxos_tpu.runtime.logger import LogLevel
from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport
from frankenpaxos_tpu.scenarios.matrix import clause

_CLIENT_ARRAY_TAG = 115
_REPLY_ARRAY_TAG = 118
_I32 = struct.Struct("<i")
_QQ = struct.Struct("<qq")

#: Session payloads are the (pseudonym, id) pair packed little-endian:
#: 16 opaque bytes the WAL oracle can regenerate from acked reply
#: columns without the tier keeping a per-op payload list.
PAYLOAD_LEN = 16

SLO_DEADLINE_S = 1.0
#: In-SLO admitted goodput must clear this fraction of OFFERED load.
GOODPUT_FLOOR_FRACTION = 0.7
#: The 4-batcher arm must carry this multiple of the 1-batcher arm.
SCALING_FLOOR = 2.0
SCALING_FLOOR_SMOKE = 1.2

_ENTRY_DTYPE = np.dtype([("pseudonym", "<i8"), ("id", "<i8"),
                         ("len", "<i4"), ("payload", "S%d" % PAYLOAD_LEN)])


class _ReplyFrame:
    """One reply-array frame's columns through the wire sink (the
    transport's drain bookkeeping requires ``count``)."""

    __slots__ = ("cols", "count")

    def __init__(self, cols: np.ndarray):
        self.cols = cols
        self.count = len(cols)


class _ReplyBatch:
    __slots__ = ("frames", "count")

    def __init__(self, frames: list):
        self.frames = frames
        self.count = sum(f.count for f in frames)


# --- cluster launch + the WAL post-mortem oracle -----------------------------


def multipaxos_cluster_raw(num_ingest_batchers: int = 4) -> dict:
    """The 15-role serving placement: f=1 multipaxos with THREE
    leaders (round-robin rounds over 3), a proxy-leader per leader,
    one 3-acceptor group, two replicas, and the 4-shard ingest tier.
    3 + 3 + 3 + 2 + 4 = 15 role processes."""
    port = lambda: ["127.0.0.1", free_port()]  # noqa: E731
    return {
        "f": 1,
        "batchers": [],
        "ingest_batchers": [port() for _ in range(num_ingest_batchers)],
        "read_batchers": [],
        "leaders": [port() for _ in range(3)],
        "leader_elections": [port() for _ in range(3)],
        "proxy_leaders": [port() for _ in range(3)],
        "acceptors": [[port() for _ in range(3)]],
        "replicas": [port() for _ in range(2)],
        "proxy_replicas": [],
    }


def launch_multipaxos_serving(bench: BenchmarkDirectory, *,
                              wal_dir: str,
                              trace_dir: "str | None" = None,
                              admission_token_rate: float,
                              extra_role_args: "dict | None" = None,
                              num_ingest_batchers: int = 4):
    """Launch the serving cluster with admission ARMED on leaders and
    replicas (sized above the sweep's peak offered rate, so steady
    state admits and genuine overload sheds with explicit Rejected
    replies) and acceptor WALs on real files for the post-mortem."""
    from frankenpaxos_tpu.bench.deploy_suite import launch_roles
    from frankenpaxos_tpu.deploy import get_protocol

    protocol = get_protocol("multipaxos")
    raw = multipaxos_cluster_raw(num_ingest_batchers)
    config_path = bench.write_json("config.json", raw)
    config = protocol.load_config(raw)
    overrides = {
        "resend_phase1as_period_s": "0.5",
        "admission_token_rate": str(admission_token_rate),
        "admission_token_burst": str(admission_token_rate / 4),
        "admission_retry_after_ms": "60",
    }
    labels = launch_roles(bench, "multipaxos", config_path, config,
                          state_machine="AppendLog",
                          overrides=overrides, wal_dir=wal_dir,
                          trace_dir=trace_dir,
                          extra_role_args=extra_role_args)
    return raw, config, labels


def wal_chosen_payloads_multipaxos(wal_dir: str, raw_config: dict) -> set:
    """Recover every acceptor's on-disk WAL and return the payload set
    provably CHOSEN: a (slot, round) whose ``WalVote``/``WalVoteRun``
    records agree across a majority of the slot's acceptor group. An
    acked payload missing from this set was acked without durable
    quorum evidence. Assumes no acceptor compacted mid-run (the arm
    volumes stay below the WAL's compaction threshold)."""
    from frankenpaxos_tpu.protocols.multipaxos.wire import (
        decode_value,
        decode_value_array,
    )
    from frankenpaxos_tpu.wal import FileStorage, Wal
    from frankenpaxos_tpu.wal.records import WalVote, WalVoteRun

    chosen: set = set()
    flat = 0
    for group in raw_config["acceptors"]:
        width = len(group)
        majority = width // 2 + 1
        # (slot, round) -> {member: decoded CommandBatchOrNoop}
        votes: dict = {}
        for member in range(width):
            root = os.path.join(wal_dir, f"acceptor_{flat}")
            flat += 1
            if not os.path.isdir(root):
                continue
            wal = Wal(FileStorage(root))
            for record in wal.recover():
                if isinstance(record, WalVote):
                    votes.setdefault(
                        (record.slot, record.round), {})[member] = \
                        decode_value(record.value)
                elif isinstance(record, WalVoteRun):
                    values = decode_value_array(record.values)
                    for i, value in enumerate(values):
                        slot = record.start_slot + i * record.stride
                        votes.setdefault(
                            (slot, record.round), {})[member] = value
            wal.close()
        for _key, members in votes.items():
            if len(members) < majority:
                continue
            value = next(iter(members.values()))
            for command in getattr(value, "commands", ()):
                chosen.add(command.command)
    return chosen


# --- the SoA open-loop serving tier ------------------------------------------


class ServingTier(Actor):
    """The million-session SoA load tier, open loop over real TCP.

    Per-session state is five numpy arrays over the full pseudonym
    population (next id, in-flight flag, issue time, rejected flag,
    ring shard); arrivals ride an absolute schedule on the transport
    loop (catch-up windows back to back, so offered load never
    self-throttles), each window's commands grouped per ring shard
    into ONE pre-encoded tag-115 frame per live batcher. Replies land
    through the tag-118/150 wire sinks as native reply columns --
    completion matching, latency, and ack bookkeeping are all numpy
    column ops. Zipf heat: a busy hot session redirects its arrival to
    a uniform idle session (open loop must not drop offered load; the
    redirect models the hot session's own pipelining limit)."""

    def __init__(self, address, transport, logger, *,
                 batcher_addresses, num_live_shards: int,
                 num_sessions: int, workload: OpenLoopWorkload,
                 ring_keys: list, seed: int = 0, dt: float = 0.1,
                 slo_deadline_s: float = SLO_DEADLINE_S,
                 resend_after_s: float = 1.5):
        super().__init__(address, transport, logger)
        self.batchers = [tuple(a) for a in batcher_addresses]
        self.num_live_shards = num_live_shards
        self.num_sessions = num_sessions
        self.workload = workload
        self.dt = dt
        self.slo_deadline_s = slo_deadline_s
        self.resend_after_s = resend_after_s
        self.np_rng = np.random.default_rng(seed)

        # paxfan client-side routing: the consistent ring over the
        # FULL batcher tier with a first-N liveness overlay -- the
        # sweep knob is membership, exactly the failover remap path.
        ring = BatcherRing(len(self.batchers))
        alive = frozenset(range(num_live_shards))
        self.shard_of = np.fromiter(
            (ring.owner(k, alive) for k in ring_keys),
            dtype=np.int8, count=num_sessions)

        self.next_id = np.zeros(num_sessions, dtype=np.int64)
        self.inflight = np.zeros(num_sessions, dtype=bool)
        self.issue_t = np.zeros(num_sessions, dtype=np.float64)
        self.was_rejected = np.zeros(num_sessions, dtype=bool)

        self.issued = 0
        self.redirected = 0
        self.thinned = 0
        self.resent = 0
        self.rejections = 0
        self.acked_frames = 0
        self.py_bytes_send = 0
        self.py_bytes_return = 0
        #: measured completion columns, appended per reply frame:
        #: (issue offset s, latency s, admitted) float64/float64/bool
        self._completions: list = []
        #: acked (pseudonym, id) pairs for the WAL oracle
        self._acked: list = []
        self._done = threading.Event()
        self.t0 = None

        addr_bytes = bytearray()
        _put_address(addr_bytes, address)
        # Cached constant frame prefix per shard: tag + client address.
        # Python formats only the 4-byte count per frame.
        self._frame_prefix = bytes((_CLIENT_ARRAY_TAG,)) + bytes(addr_bytes)
        self.wire_sinks = {
            _REPLY_ARRAY_TAG: (self._parse_reply, self._on_replies),
            150: (self._parse_reply_batch, self._on_reply_list),
        }

    # --- open-loop arrival schedule --------------------------------------

    def run(self, duration_s: float, warm_s: float) -> None:
        """Blocks until the measured window (warm + duration) ends;
        call :meth:`settle` afterwards."""
        self._done.clear()
        self.t0 = time.monotonic()
        stop_at = self.t0 + warm_s + duration_s
        sched = {"t": self.t0}

        def window() -> None:
            now = time.monotonic()
            if now >= stop_at:
                self._done.set()
                return
            k = self.workload.arrival_count(
                self.np_rng, sched["t"] - self.t0, self.dt)
            if k > 0:
                self._arrivals(k, now)
            sched["t"] += self.dt
            # paxlint: disable=PAX104 -- deployed-only open-loop
            # driver: the absolute arrival schedule is wall-clock by
            # design (this actor never runs under a sim).
            self.transport.loop.call_later(
                max(0.0, sched["t"] - time.monotonic()), window)

        self.transport.loop.call_soon_threadsafe(window)
        if not self._done.wait(timeout=warm_s + duration_s + 60):
            raise RuntimeError("serving tier schedule never finished")

    def _arrivals(self, k: int, now: float) -> None:
        sessions = np.asarray(
            self.workload.sample_keys(self.np_rng, k), dtype=np.int64)
        sessions = np.unique(sessions)
        dup = k - len(sessions)
        busy = self.inflight[sessions]
        free = sessions[~busy]
        need = int(busy.sum()) + dup
        # Busy/hot arrivals redirect to uniform idle sessions: the
        # offered load stays offered (open loop), the hot session's
        # one-op-in-flight limit is modeled, the population is huge so
        # a uniform probe lands idle almost surely.
        for _ in range(3):
            if need <= 0:
                break
            cand = np.unique(self.np_rng.integers(
                0, self.num_sessions, need * 2))
            cand = cand[~self.inflight[cand]]
            cand = np.setdiff1d(cand, free, assume_unique=False)
            take = cand[:need]
            if len(take):
                free = np.concatenate([free, take])
                self.redirected += len(take)
                need -= len(take)
        self.thinned += max(need, 0)
        if len(free):
            self._issue(free, now)

    def _issue(self, sessions: np.ndarray, now: float) -> None:
        ids = self.next_id[sessions]
        self.next_id[sessions] = ids + 1
        self.inflight[sessions] = True
        self.was_rejected[sessions] = False
        self.issue_t[sessions] = now
        self.issued += len(sessions)
        self._ship(sessions, ids)

    def _ship(self, sessions: np.ndarray, ids: np.ndarray) -> None:
        shards = self.shard_of[sessions]
        for shard in np.unique(shards):
            mask = shards == shard
            self._send_frame(int(shard), sessions[mask], ids[mask])

    def _send_frame(self, shard: int, sessions: np.ndarray,
                    ids: np.ndarray) -> None:
        n = len(sessions)
        entries = np.empty(n, dtype=_ENTRY_DTYPE)
        entries["pseudonym"] = sessions
        entries["id"] = ids
        entries["len"] = PAYLOAD_LEN
        pair = np.empty((n, 2), dtype="<i8")
        pair[:, 0] = sessions
        pair[:, 1] = ids
        entries["payload"] = pair.view("S%d" % PAYLOAD_LEN).ravel()
        payload = self._frame_prefix + _I32.pack(n) + entries.tobytes()
        # Python formatted the count word; the prefix is a cached
        # constant and the entries are one numpy tobytes.
        self.py_bytes_send += 5
        self.transport.send(self.address, self.batchers[shard], payload)

    # --- the reply column sinks ------------------------------------------

    def _parse_reply(self, data):
        parsed = parse_reply_array(data)
        if parsed is None:
            return None
        return _ReplyFrame(parsed.cols)

    def _parse_reply_batch(self, data):
        view = memoryview(data)
        frames = []
        for s, e in native.scan_batch(data, 2):
            if e - s < 5 or data[s] != _REPLY_ARRAY_TAG:
                return None
            # Zero-copy segment view: the native column scan reads it
            # in place, only the int64 column array survives the call.
            parsed = parse_reply_array(view[s:e])
            if parsed is None:
                return None
            frames.append(_ReplyFrame(parsed.cols))
        return _ReplyBatch(frames)

    def _on_reply_list(self, src, batch) -> None:
        for frame in batch.frames:
            self._on_replies(src, frame)

    def _on_replies(self, src, reply) -> None:
        now = time.monotonic()
        self.acked_frames += 1
        self.py_bytes_return += 5
        cols = reply.cols
        pseudonyms = cols[:, 0]
        ids = cols[:, 1]
        self._acked.append(np.ascontiguousarray(cols[:, :2]))
        fresh = self.inflight[pseudonyms] \
            & (ids == self.next_id[pseudonyms] - 1)
        p = pseudonyms[fresh]
        if not len(p):
            return
        self.inflight[p] = False
        latency = now - self.issue_t[p]
        self._completions.append((self.issue_t[p] - self.t0, latency,
                                  ~self.was_rejected[p]))

    def receive(self, src, message) -> None:
        # Objects that bypass the sinks: admission Rejected replies
        # (per-entry Python by nature -- charged in full), and decoded
        # reply arrays if a sink ever declines.
        entries = getattr(message, "entries", None)
        if entries is None:
            return
        retry_after_ms = getattr(message, "retry_after_ms", None)
        if retry_after_ms is not None:
            self.rejections += len(entries)
            self.py_bytes_return += 16 * len(entries)
            stale = [p for p, _cid in entries if self.inflight[p]]
            if stale:
                self.was_rejected[np.asarray(stale)] = True
                delay = retry_after_ms / 1000.0 \
                    + float(self.np_rng.random()) * 0.05
                # paxlint: disable=PAX104 -- deployed-only driver;
                # admission backoff honors wall-clock retry_after_ms.
                self.transport.loop.call_later(
                    delay, self._reissue, np.asarray(stale, np.int64))
            return
        # Decoded ClientReplyArray fallback.
        pseudonyms = np.fromiter(
            (e[0] for e in entries), np.int64, len(entries))
        ids = np.fromiter((e[1] for e in entries), np.int64, len(entries))
        cols = np.zeros((len(entries), 5), dtype=np.int64)
        cols[:, 0] = pseudonyms
        cols[:, 1] = ids
        self.py_bytes_return += 28 * len(entries)

        class _Cols:
            pass

        wrapped = _Cols()
        wrapped.cols = cols
        self._on_replies(src, wrapped)

    def _reissue(self, sessions: np.ndarray) -> None:
        sessions = sessions[self.inflight[sessions]]
        if not len(sessions):
            return
        self.resent += len(sessions)
        self._ship(sessions, self.next_id[sessions] - 1)

    # --- settle + stats ---------------------------------------------------

    def sweep_stale(self) -> None:
        """Resend every op in flight longer than ``resend_after_s``
        (the replica client table dedupes; a resend can never double-
        execute)."""
        now = time.monotonic()
        stale = np.nonzero(
            self.inflight
            & (now - self.issue_t > self.resend_after_s))[0]
        if len(stale):
            self.resent += len(stale)
            self._ship(stale, self.next_id[stale] - 1)

    def settle(self, settle_s: float) -> int:
        """No new arrivals; resend-sweep until every in-flight op
        concludes. Returns ops still pending at the deadline -- the
        silent-wedge count."""
        deadline = time.monotonic() + settle_s
        while time.monotonic() < deadline:
            if not self.inflight.any():
                return 0
            self.transport.loop.call_soon_threadsafe(self.sweep_stale)
            time.sleep(0.3)
        return int(self.inflight.sum())

    def acked_payloads(self) -> set:
        """Every acked command payload, regenerated from the reply
        columns (the tier never kept a per-op payload list)."""
        if not self._acked:
            return set()
        pairs = np.unique(np.concatenate(self._acked), axis=0)
        out = pairs.astype("<i8").tobytes()
        return {out[i:i + PAYLOAD_LEN]
                for i in range(0, len(out), PAYLOAD_LEN)}

    def stats(self, warm_s: float, duration_s: float) -> dict:
        if self._completions:
            offsets = np.concatenate([c[0] for c in self._completions])
            latencies = np.concatenate([c[1] for c in self._completions])
            admitted = np.concatenate([c[2] for c in self._completions])
        else:
            offsets = latencies = np.zeros(0)
            admitted = np.zeros(0, dtype=bool)
        lo, hi = warm_s, warm_s + duration_s
        measured = (offsets >= lo) & (offsets < hi)
        m_lat, m_adm = latencies[measured], admitted[measured]
        in_slo = int(((m_lat <= self.slo_deadline_s) & m_adm).sum())
        adm_lat = np.sort(m_lat[m_adm])
        sessions_touched = int((self.next_id > 0).sum())
        acked = sum(len(a) for a in self._acked)

        def q(v):
            if not len(adm_lat):
                return None
            return round(float(
                adm_lat[min(len(adm_lat) - 1, int(v * len(adm_lat)))]), 4)

        return {
            "issued": self.issued,
            "completed": int(measured.sum()),
            "in_slo_admitted": in_slo,
            "goodput_cmds_per_s": round(in_slo / duration_s, 2),
            "sessions_touched": sessions_touched,
            "redirected": self.redirected,
            "thinned": self.thinned,
            "resent": self.resent,
            "rejections": self.rejections,
            "acked_entries": acked,
            "reply_frames": self.acked_frames,
            "p50_admitted_s": q(0.50),
            "p99_admitted_s": q(0.99),
            "p999_admitted_s": q(0.999),
            "python_bytes_per_cmd_send":
                round(self.py_bytes_send / max(self.issued, 1), 4),
            "python_bytes_per_cmd_return":
                round(self.py_bytes_return / max(acked, 1), 4),
        }


# --- the sweep ---------------------------------------------------------------


def _ring_keys(num_sessions: int) -> list:
    """Session ring keys, computed once for the whole sweep: the same
    stable (client token, pseudonym) hash deployed clients use."""
    return [stable_key(0, p) for p in range(num_sessions)]


def run_arm(work_dir: str, *, num_live_shards: int, rate: float,
            duration_s: float, warm_s: float, settle_s: float,
            num_sessions: int, ring_keys: list, seed: int,
            admission_token_rate: float,
            py_bytes_bound: float) -> dict:
    """One sweep arm: fresh 15-role cluster, fresh WALs, the tier
    routing through the first ``num_live_shards`` ring shards."""
    t_wall = time.time()
    bench = BenchmarkDirectory(
        os.path.join(work_dir, f"batchers_{num_live_shards}"))
    wal_dir = bench.abspath("wal")
    raw, config, labels = launch_multipaxos_serving(
        bench, wal_dir=wal_dir,
        admission_token_rate=admission_token_rate)

    workload = OpenLoopWorkload(
        rate=rate, zipf_s=1.1, num_keys=num_sessions,
        diurnal_amplitude=0.3, diurnal_period_s=duration_s,
        diurnal_phase_s=-warm_s)
    transport = None
    try:
        transport = TcpTransport(("127.0.0.1", free_port()),
                                 FakeLogger(LogLevel.FATAL))
        transport.start()
        tier = ServingTier(
            transport.listen_address, transport,
            FakeLogger(LogLevel.FATAL),
            batcher_addresses=raw["ingest_batchers"],
            num_live_shards=num_live_shards,
            num_sessions=num_sessions, workload=workload,
            ring_keys=ring_keys, seed=seed)
        tier.run(duration_s, warm_s)
        pending = tier.settle(settle_s)
        stats = tier.stats(warm_s, duration_s)
    finally:
        if transport is not None:
            transport.stop()
        bench.cleanup()

    # WAL post-mortem, after cleanup: every role exited, logs on disk.
    chosen = wal_chosen_payloads_multipaxos(wal_dir, raw)
    acked = tier.acked_payloads()
    lost = len(acked - chosen)

    clauses = {
        "goodput_floor": clause(
            stats["goodput_cmds_per_s"],
            round(GOODPUT_FLOOR_FRACTION * rate, 2), "min"),
        "admitted_p99_ceiling_s": clause(
            stats["p99_admitted_s"], SLO_DEADLINE_S),
        "zero_acked_write_loss": clause(lost, 0, "zero"),
        "no_silent_wedge": clause(pending, 0, "zero"),
        "python_bytes_per_cmd_send": clause(
            stats["python_bytes_per_cmd_send"], py_bytes_bound),
        "python_bytes_per_cmd_return": clause(
            stats["python_bytes_per_cmd_return"], py_bytes_bound),
    }
    arm = {
        "live_batchers": num_live_shards,
        "offered_rate": rate,
        "num_roles": len(labels),
        "wall_seconds": round(time.time() - t_wall, 1),
        "stats": stats,
        "efficiency": round(
            stats["goodput_cmds_per_s"] / rate, 4),
        "events": {
            "acked_payloads": len(acked),
            "wal_chosen_payloads": len(chosen),
            "acked_not_chosen": lost,
            "control_plane_never_shed": (
                "structural (client-lane-only shedding; IngestCredit "
                "rides the control lane by construction -- "
                "tests/test_serve.py, "
                "tests/protocols/test_ingest_chaos.py)"),
        },
        "slo": clauses,
    }
    arm["gate_passed"] = all(c["passed"] for c in clauses.values())
    print(f"arm batchers={num_live_shards}: offered {rate:.0f}/s "
          f"goodput {stats['goodput_cmds_per_s']:.0f}/s "
          f"p99 {stats['p99_admitted_s']} "
          f"py-bytes/cmd {stats['python_bytes_per_cmd_send']:.3f}->"
          f"{stats['python_bytes_per_cmd_return']:.3f} "
          f"loss {lost} wedge {pending} "
          f"gate={'PASS' if arm['gate_passed'] else 'FAIL'}",
          flush=True)
    return arm


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(
        description="paxfan deployed serving gate (docs/SERVING.md)")
    parser.add_argument("--out", default=None)
    parser.add_argument("--smoke", action="store_true",
                        help="reduced 2-batcher CI gate (~2 min)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--work_dir", default=None)
    parser.add_argument("--base_rate", type=float, default=None,
                        help="per-shard offered rate (cmds/s)")
    args = parser.parse_args(argv)

    if args.smoke:
        arms_n = (1, 2)
        num_sessions = 1 << 17
        base_rate = args.base_rate or 250.0
        duration_s, warm_s, settle_s = 6.0, 1.0, 10.0
        py_bytes_bound = 0.8
        scaling_floor = SCALING_FLOOR_SMOKE
    else:
        arms_n = (1, 2, 4)
        num_sessions = 1_100_000
        base_rate = args.base_rate or 550.0
        duration_s, warm_s, settle_s = 18.0, 2.0, 12.0
        py_bytes_bound = 0.35
        scaling_floor = SCALING_FLOOR
    work_dir = args.work_dir or os.path.join(
        "deployed_serving_work", str(int(time.time())))
    # Admission sized above the sweep peak: armed, admitting in steady
    # state, shedding (with explicit Rejected) on genuine overload.
    admission_token_rate = base_rate * max(arms_n) * 2.5

    print(f"precomputing {num_sessions} session ring keys...",
          flush=True)
    ring_keys = _ring_keys(num_sessions)

    arms: dict = {}
    for n in arms_n:
        # One retry on a lost startup race (deployed_twin policy):
        # fresh directory, fresh ports.
        for attempt in (1, 2):
            try:
                arms[str(n)] = run_arm(
                    os.path.join(work_dir, f"attempt{attempt}"),
                    num_live_shards=n, rate=base_rate * n,
                    duration_s=duration_s, warm_s=warm_s,
                    settle_s=settle_s, num_sessions=num_sessions,
                    ring_keys=ring_keys, seed=args.seed + n,
                    admission_token_rate=admission_token_rate,
                    py_bytes_bound=py_bytes_bound)
                break
            except RuntimeError as e:
                print(f"arm batchers={n} attempt {attempt} "
                      f"failed: {e}", flush=True)
                if attempt == 2:
                    raise

    top = str(max(arms_n))
    goodputs = {k: arms[k]["stats"]["goodput_cmds_per_s"]
                for k in arms}
    scaling = round(goodputs[top] / max(goodputs["1"], 1e-9), 2)
    sweep_clause = clause(scaling, scaling_floor, "min")
    gates = {
        "efficiency_by_batchers": {k: arms[k]["efficiency"]
                                   for k in arms},
        "goodput_cmds_per_s_by_batchers": goodputs,
        "scaling_ratio_max_over_1": scaling,
        "admitted_p99_s_worst": max(
            (arms[k]["stats"]["p99_admitted_s"] or 0.0)
            for k in arms),
        "python_bytes_per_cmd_send_worst": max(
            arms[k]["stats"]["python_bytes_per_cmd_send"]
            for k in arms),
        "python_bytes_per_cmd_return_worst": max(
            arms[k]["stats"]["python_bytes_per_cmd_return"]
            for k in arms),
        "zero_acked_loss": all(
            arms[k]["slo"]["zero_acked_write_loss"]["passed"]
            for k in arms),
        "sweep_scaling": sweep_clause,
    }
    gates["gate_passed"] = (
        all(arms[k]["gate_passed"] for k in arms)
        and sweep_clause["passed"])
    result = {
        "benchmark": "deployed_serving_lt",
        "methodology": (
            "SoA open-loop session tier (1M+-pseudonym population, "
            "Zipf session heat, diurnal ramp; busy hot sessions "
            "redirect arrivals to uniform idle sessions so offered "
            "load never self-throttles) over real TCP against a "
            "15-role multipaxos cluster (3 leaders, 3 proxy leaders, "
            "3 WAL-backed acceptors, 2 replicas, 4 ingest batchers; "
            "every role its own OS process), routed through the "
            "paxfan consistent batcher ring with a first-N liveness "
            "overlay as the sweep knob; each arm offers base_rate x N "
            "and a fresh cluster + fresh WALs. Commands ship as "
            "pre-encoded tag-115 arrays (Python formats the count "
            "word per frame); replies land via the native tag-118 "
            "column scan (parse_reply_array) -- per-cmd Python bytes "
            "counted per the ingest_lt convention, rejected entries "
            "and batch copies charged in full. Zero-acked-loss is a "
            "WAL post-mortem: every acked (pseudonym, id) payload "
            "must hold a same-(slot, round) acceptor-majority of "
            "durable WalVote/WalVoteRun records."),
        "smoke": bool(args.smoke),
        "seed": args.seed,
        "host_cpus": os.cpu_count(),
        "num_sessions": num_sessions,
        "base_rate": base_rate,
        "slo_deadline_s": SLO_DEADLINE_S,
        "arms": arms,
        "gates": gates,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    print(f"sweep scaling {scaling}x (floor {scaling_floor}x); "
          f"gate_passed={gates['gate_passed']}")
    return result


if __name__ == "__main__":
    raise SystemExit(0 if main()["gates"]["gate_passed"] else 1)
