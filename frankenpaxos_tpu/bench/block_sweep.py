"""Block-size frontier sweep for the device pipeline (bench.py).

Sweeps the per-drain block size of the steady-state MultiPaxos pipeline
(`bench.pipeline.run_steps`) at the 1M-slot window and records, per
block size, committed cmds/s and per-drain latency. The committed JSON
(`bench_results/block_sweep.json`) justifies the BLOCK constant in
`bench.py`: pick the highest-throughput point whose per-drain latency
stays under the 50us BASELINE.json target.

Run: python -m frankenpaxos_tpu.bench.block_sweep
"""

from __future__ import annotations

import json
import pathlib
import time

import jax

from frankenpaxos_tpu.bench.pipeline import (
    drain_latency_distribution,
    make_state,
    run_steps,
)
from frankenpaxos_tpu.quorums import SimpleMajority

WINDOW = 1 << 20
NUM_ACCEPTORS = 3
BLOCKS = [1 << 12, 1 << 13, 1 << 14, 1 << 15, 1 << 16, 1 << 17]
TARGET_US = 50.0


def measure(block: int, iters: int, repeats: int = 3) -> dict:
    """One block size, ``repeats`` timed runs after one warm/compile
    run. Per-run numbers are recorded and the point is summarized by
    its WORST run: on a host with tunnel jitter, the frontier choice
    must be robust, not lucky (VERDICT r3 weak #5)."""
    masks, thresholds, combine_any = (
        SimpleMajority(range(NUM_ACCEPTORS)).write_spec().as_arrays())
    masks_t = tuple(tuple(int(x) for x in row) for row in masks)
    thresholds_t = tuple(int(t) for t in thresholds)

    state = make_state(WINDOW, NUM_ACCEPTORS)
    state = run_steps(state, iters, block, masks_t, thresholds_t,
                      combine_any)
    jax.block_until_ready(state.committed)
    warm_committed = int(state.committed)

    runs = []
    for _ in range(repeats):
        state = make_state(WINDOW, NUM_ACCEPTORS)
        jax.block_until_ready(state.votes)
        t0 = time.perf_counter()
        state = run_steps(state, iters, block, masks_t, thresholds_t,
                          combine_any)
        committed = int(state.committed)  # fetch orders after compute
        elapsed = time.perf_counter() - t0
        assert committed == warm_committed, "nondeterministic pipeline"
        assert abs(committed - iters * block) <= 2 * block, (
            committed, iters * block)
        runs.append({
            "elapsed_s": round(elapsed, 4),
            "cmds_per_sec": round(committed / elapsed, 1),
            "drain_latency_us": round(elapsed / iters * 1e6, 2),
        })
    worst = min(runs, key=lambda r: r["cmds_per_sec"])
    # True per-drain distribution at this block size (chunked
    # host-timed dispatches; see pipeline.drain_latency_distribution).
    dist = drain_latency_distribution(
        (masks_t, thresholds_t, combine_any), NUM_ACCEPTORS, WINDOW,
        block, worst["drain_latency_us"], time_budget_s=8.0,
        target_samples=256)
    return {
        "block_slots": block,
        "iters": iters,
        "committed": warm_committed,
        "runs": runs,
        "cmds_per_sec": worst["cmds_per_sec"],
        "drain_latency_us": max(r["drain_latency_us"] for r in runs),
        **{k: dist[k] for k in ("p50_drain_latency_us",
                                "p99_drain_latency_us",
                                "latency_samples",
                                "drains_per_sample")},
    }


def main() -> None:
    rows = []
    for block in BLOCKS:
        # Keep total committed work roughly constant across points so
        # each measurement lasts long enough to swamp the one-time
        # dispatch + result-fetch RTT through the accelerator tunnel
        # (~0.1s), which otherwise dominates sub-second runs.
        iters = max(2048, (1 << 30) // block)
        row = measure(block, iters)
        rows.append(row)
        print(json.dumps(row))

    eligible = [r for r in rows if r["drain_latency_us"] < TARGET_US]
    best = max(eligible or rows, key=lambda r: r["cmds_per_sec"])
    out = {
        "suite": "block_sweep",
        "window_slots": WINDOW,
        "num_acceptors": NUM_ACCEPTORS,
        "target_drain_latency_us": TARGET_US,
        "device": str(jax.devices()[0]),
        "rows": rows,
        "chosen_block": best["block_slots"],
        "target_met": bool(eligible),
        "round_history_cmds_per_sec": {
            "r01": 815e6, "r02": 549e6, "r03": 1.64e9},
        "note": ("each point is 3 quiet runs after a warm run; "
                 "cmds_per_sec / drain_latency_us summarize the WORST "
                 "run, so bench.py's BLOCK (the highest worst-case "
                 "throughput under the 50us latency target) is robust "
                 "to run noise, not tuned to a lucky run. "
                 "round_history records the r01-r03 headline swing "
                 "(815M -> 549M -> 1.64B cmds/s) this methodology "
                 "addresses."
                 if eligible else
                 "WARNING: no block size met the latency target on this "
                 "run; chosen_block is the fastest point regardless."),
    }
    path = pathlib.Path(__file__).resolve().parents[2] / "bench_results"
    path.mkdir(exist_ok=True)
    (path / "block_sweep.json").write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({"chosen_block": best["block_slots"],
                      "written": str(path / "block_sweep.json")}))


if __name__ == "__main__":
    main()
