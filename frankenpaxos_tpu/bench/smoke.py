"""Deployment smoke test: MultiPaxos over real localhost processes.

The analog of benchmarks/multipaxos/smoke.py + scripts/benchmark_smoke.sh.

Usage: python -m frankenpaxos_tpu.bench.smoke [--duration 2.0]
"""

from __future__ import annotations

import argparse
import json
import tempfile

from frankenpaxos_tpu.bench.harness import SuiteDirectory
from frankenpaxos_tpu.bench.multipaxos_suite import (
    MultiPaxosInput,
    run_benchmark,
)


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--duration", type=float, default=2.0)
    parser.add_argument("--num_clients", type=int, default=2)
    parser.add_argument("--suite_dir", default=None)
    args = parser.parse_args(argv)

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_smoke_")
    suite = SuiteDirectory(root, "multipaxos_smoke")
    stats = run_benchmark(
        suite.benchmark_directory(),
        MultiPaxosInput(duration_s=args.duration,
                        num_clients=args.num_clients))
    print(json.dumps(stats, indent=2))
    assert stats["num_requests"] > 0, "smoke benchmark made no progress"
    return stats


if __name__ == "__main__":
    main()
