"""Deployment smoke over real localhost processes, any protocol.

The analog of scripts/benchmark_smoke.sh (all 18 reference protocols,
benchmark_smoke.sh:5-18) + benchmarks/multipaxos/smoke.py.

Usage::

    python -m frankenpaxos_tpu.bench.smoke --protocol all
    python -m frankenpaxos_tpu.bench.smoke --protocol multipaxos --bench
"""

from __future__ import annotations

import argparse
import json
import tempfile

from frankenpaxos_tpu.bench.deploy_suite import run_protocol_smoke
from frankenpaxos_tpu.bench.harness import BenchmarkDirectory, SuiteDirectory
from frankenpaxos_tpu.deploy import PROTOCOL_NAMES


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--protocol", default="all",
                        choices=["all", *PROTOCOL_NAMES])
    parser.add_argument("--bench", action="store_true",
                        help="run the measured multipaxos benchmark "
                             "instead of the one-command smoke")
    parser.add_argument("--duration", type=float, default=2.0)
    parser.add_argument("--num_clients", type=int, default=2)
    parser.add_argument("--suite_dir", default=None)
    args = parser.parse_args(argv)

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_smoke_")

    if args.bench:
        if args.protocol not in ("all", "multipaxos"):
            raise SystemExit(
                "--bench currently supports only --protocol multipaxos")
        from frankenpaxos_tpu.bench.multipaxos_suite import (
            MultiPaxosInput,
            run_benchmark,
        )

        suite = SuiteDirectory(root, "multipaxos_bench")
        stats = run_benchmark(
            suite.benchmark_directory(),
            MultiPaxosInput(duration_s=args.duration,
                            num_clients=args.num_clients))
        print(json.dumps(stats, indent=2))
        assert stats["num_requests"] > 0, "benchmark made no progress"
        return stats

    names = PROTOCOL_NAMES if args.protocol == "all" else [args.protocol]
    results, failures = {}, []
    for name in names:
        bench = BenchmarkDirectory(f"{root}/{name}")
        try:
            results[name] = run_protocol_smoke(bench, name)
            print(f"{name}: ok "
                  f"(ready {results[name]['ready_s']}s, "
                  f"latency {results[name]['latency_ms']} ms)")
        except Exception as e:  # noqa: BLE001 - report, then fail at end
            failures.append(name)
            print(f"{name}: FAILED: {e}")
    print(json.dumps(results, indent=2))
    if failures:
        raise SystemExit(f"smoke failed for: {failures}")
    return results


if __name__ == "__main__":
    main()
