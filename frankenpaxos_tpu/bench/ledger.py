"""Committed perf-trajectory ledger over the ``bench_results/`` artifacts.

Every latency/throughput bench in this repo (``*_lt.py``) writes a JSON
artifact whose *gate medians* -- the paired-A/B ratios and bounded
overheads the bench's own pass/fail logic keys on -- are the numbers we
actually defend PR over PR. This module lifts those headlines into one
append-only ledger, ``bench_results/LEDGER.json``, so the performance
trajectory is a committed, reviewable object rather than something
reconstructed from git archaeology:

  * ``--update`` extracts every known artifact's headline rows (value +
    kind + direction + explicit tolerance band + methodology tag) and
    appends a history entry per row when the artifact changed. Rows are
    keyed (bench, metric); history is never rewritten.
  * ``--check`` re-extracts the same headlines from FRESH artifacts (a
    reduced/smoke re-run, typically in CI or pre-commit) and compares
    them against the last committed trajectory point within the row's
    tolerance band. Exit 1 on any out-of-band regression.

Comparison discipline -- the part that keeps the check honest:

  * Tolerances are explicit per row and wide enough for shared-host
    noise (the ``*_lt`` methodology notes record 15-30% variance for
    absolute numbers; ratio headlines are steadier, which is why they
    are the headlines). A smoke-vs-full mismatch WIDENS the band by
    ``SMOKE_EXTRA_REL`` instead of silently comparing unlike runs.
  * Environment labels (``host_mesh``, ``degraded``, ``mode``,
    ``mesh_shape``) gate comparability: a row recorded on a forced host
    mesh or a degraded run is never compared against a hardware row --
    the check reports a labeled SKIP, not a pass.
  * A methodology drift (the bench changed how it measures) is a
    labeled SKIP too: the committed point is stale by construction and
    the fix is ``--update``, not a tolerance fudge.
  * ``info`` rows (host-variance-dominated absolutes like protocol_lt
    throughputs, crossover widths) ride the trajectory for plotting but
    are never gated.

CLI::

  python -m frankenpaxos_tpu.bench.ledger --update [--tag pr19]
  python -m frankenpaxos_tpu.bench.ledger --check --fresh /tmp/fresh

CI wiring: the ``perf-ledger`` job re-runs the smoke-capable benches
into a scratch dir and runs ``--check`` against the committed ledger.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import sys
from typing import Any, Optional

LEDGER_VERSION = 1
DEFAULT_RESULTS_DIR = "bench_results"
DEFAULT_LEDGER = os.path.join(DEFAULT_RESULTS_DIR, "LEDGER.json")

# Labels that must match exactly for two rows to be comparable. A row
# measured on a forced host mesh (multichip_lt without real devices) or
# in degraded mode is a different experiment from its hardware twin.
COMPARABILITY_LABELS = ("host_mesh", "degraded", "mode", "mesh_shape")

# Extra relative slack added when one side of a comparison is a smoke
# run and the other is not (reduced reps => noisier medians).
SMOKE_EXTRA_REL = 0.25


# --------------------------------------------------------------------------
# Headline declarations
# --------------------------------------------------------------------------
#
# Each entry: (dotted path, kind, direction, tolerance).
#   * path       -- dotted into the artifact; one ``*`` segment expands
#                   to every key at that level (sorted), yielding one
#                   row per key (e.g. per in-flight width).
#   * kind       -- ratio | throughput | latency | pct | bool | count
#   * direction  -- "higher" (regression = fresh below band),
#                   "lower" (regression = fresh above band),
#                   "bool" (regression = committed True, fresh False),
#                   "info" (recorded, never gated).
#   * tolerance  -- {"rel": r} relative band, {"abs": a} absolute band
#                   (same unit as the value; used for pct/latency rows
#                   where relative bands misbehave near zero), or None
#                   for bool/info rows.

HEADLINES: dict[str, list[tuple[str, str, str, Optional[dict]]]] = {
    "depset_lt": [
        ("gates.throughput_ratio_at_ge_1024.*", "ratio", "higher", {"rel": 0.35}),
        ("gates.oracle_bit_identical", "bool", "bool", None),
        ("gates.gate_passed", "bool", "bool", None),
    ],
    "transport_lt": [
        ("gates.throughput_ratio_at_ge_256.*", "ratio", "higher", {"rel": 0.35}),
        ("gates.syscall_reduction_at_1024", "ratio", "higher", {"rel": 0.25}),
        ("gates.gate_passed", "bool", "bool", None),
    ],
    "ingest_lt": [
        ("gates.throughput_ratio_at_ge_1024.*", "ratio", "higher", {"rel": 0.35}),
        ("gates.overhead_pct", "pct", "lower", {"abs": 2.0}),
        ("gates.gate_passed", "bool", "bool", None),
    ],
    # The paxfan deployed serving gate: efficiency rows are scale-free
    # (goodput / offered per arm) so the CI smoke sweep (arms 1-2 at
    # reduced rates) stays comparable against committed full rows; the
    # arm-4 row is simply absent from smoke artifacts.
    "deployed_serving_lt": [
        ("gates.efficiency_by_batchers.*", "ratio", "higher", {"rel": 0.25}),
        ("gates.scaling_ratio_max_over_1", "ratio", "info", None),
        ("gates.admitted_p99_s_worst", "latency", "lower", {"rel": 1.0}),
        ("gates.python_bytes_per_cmd_send_worst", "count", "lower",
         {"abs": 0.5}),
        ("gates.python_bytes_per_cmd_return_worst", "count", "lower",
         {"abs": 0.5}),
        ("gates.gate_passed", "bool", "bool", None),
    ],
    "multipaxos_lt": [
        ("sim_ab_pipeline.*.tpu_over_dict_ratio", "ratio", "higher", {"rel": 0.35}),
        ("sim_ab_pipeline.*.run_over_dict_ratio", "ratio", "higher", {"rel": 0.35}),
        ("crossover_inflight", "count", "info", None),
        ("tracker_crossover_width", "count", "info", None),
    ],
    "mencius_lt": [
        ("sim_ab_pipeline.*.coalesced_over_per_message_ratio", "ratio",
         "higher", {"rel": 0.35}),
        ("crossover_inflight", "count", "info", None),
    ],
    "wal_lt": [
        ("sim_ab_pipeline.*.wal_on_over_off_ratio", "ratio", "higher",
         {"rel": 0.35}),
    ],
    "reconfig_lt": [
        ("sim_ab_pipeline.*.tagged_over_plain_ratio", "ratio", "higher",
         {"rel": 0.35}),
        ("sim_handover.handover_wall_s_median", "latency", "lower",
         {"rel": 0.5}),
        ("deployed_handover.steady_latency_median_s", "latency", "info", None),
        ("deployed_handover.handover_spike_latency_s", "latency", "info", None),
    ],
    "overload_lt": [
        ("gate.peak_1x_goodput", "throughput", "higher", {"rel": 0.4}),
        ("gate.p99_1x_s", "latency", "lower", {"rel": 0.5}),
        ("admission_overhead.off_overhead_pct_worst_width", "pct", "lower",
         {"abs": 2.0}),
        ("gate.gate_passed", "bool", "bool", None),
        ("admission_overhead.gate_passed", "bool", "bool", None),
    ],
    "geo_lt": [
        ("gates.home_p50_below_quarter_wan_rtt.value", "latency", "lower",
         {"rel": 0.5}),
        ("gates.steal_latency_within_3_wan_rtt.value", "latency", "lower",
         {"rel": 0.5}),
        ("gates.flat_vs_multipaxos_at_noise_floor.value", "ratio", "higher",
         {"rel": 0.25}),
        ("gates.flat_geo_layer_overhead_bounded.value", "ratio", "higher",
         {"rel": 0.25}),
        ("hot_objects.speedup_p50", "ratio", "info", None),
        ("gates.all_passed", "bool", "bool", None),
    ],
    "global_lt": [
        ("scenario_overhead.ratio_wave_over_legacy_median", "ratio", "lower",
         {"rel": 0.1}),
        ("scenario_overhead.overhead_pct", "pct", "lower", {"abs": 3.0}),
        ("matrix.gate_passed", "bool", "bool", None),
        ("gate_passed", "bool", "bool", None),
    ],
    "multichip_lt": [
        ("arms.window_1m.speedup", "ratio", "higher", {"rel": 0.35}),
        ("arms.window_8m.speedup", "ratio", "higher", {"rel": 0.35}),
        ("per_shard_latency.worst_shard_p50_us", "latency", "lower",
         {"rel": 0.5}),
        ("gates_pass", "bool", "bool", None),
    ],
    "protocol_lt": [
        # Host-variance-dominated absolutes (see the artifact's note):
        # trajectory only, never gated.
        ("protocols.*.throughput_p90_1s", "throughput", "info", None),
        ("protocols.*.latency_median_ms", "latency", "info", None),
    ],
    "trace_overhead": [
        ("off_overhead_pct_worst_width", "pct", "lower", {"abs": 2.0}),
        ("gate_passed", "bool", "bool", None),
    ],
    "telemetry_overhead": [
        ("off_overhead_pct_worst_width", "pct", "lower", {"abs": 2.0}),
        ("on_overhead_pct_worst_width", "pct", "info", None),
        ("gate_passed", "bool", "bool", None),
    ],
}


@dataclasses.dataclass(frozen=True)
class Row:
    """One extracted headline (pre-history)."""

    bench: str
    metric: str
    kind: str
    direction: str
    tolerance: Optional[dict]
    labels: dict
    methodology_sha: str
    value: Any


def _resolve(artifact: dict, path: str) -> list[tuple[str, Any]]:
    """Dotted path -> [(concrete_path, value)]; ``*`` expands dict keys."""
    parts = path.split(".")
    results: list[tuple[list[str], Any]] = [([], artifact)]
    for part in parts:
        nxt: list[tuple[list[str], Any]] = []
        for prefix, node in results:
            if not isinstance(node, dict):
                continue
            if part == "*":
                for key in sorted(node, key=str):
                    nxt.append((prefix + [key], node[key]))
            elif part in node:
                nxt.append((prefix + [part], node[part]))
        results = nxt
    out = []
    for prefix, value in results:
        if isinstance(value, (int, float, bool)) and not isinstance(
                value, complex):
            out.append((".".join(prefix), value))
    return out


def _methodology_sha(artifact: dict) -> str:
    text = artifact.get("methodology") or artifact.get("sim_ab_methodology")
    if not text:
        return "none"
    return hashlib.sha256(str(text).encode()).hexdigest()[:10]


def _labels(artifact: dict) -> dict:
    labels = {}
    for key in ("host_mesh", "degraded", "mode", "smoke"):
        if key in artifact:
            labels[key] = artifact[key]
    shape = artifact.get("mesh_shape")
    if isinstance(shape, dict):
        labels["mesh_shape"] = "x".join(
            str(shape[k]) for k in sorted(shape))
    return labels


def extract_rows(bench: str, artifact: dict) -> list[Row]:
    """All declared headline rows present in ``artifact``."""
    rows = []
    sha = _methodology_sha(artifact)
    labels = _labels(artifact)
    for path, kind, direction, tolerance in HEADLINES.get(bench, []):
        for concrete, value in _resolve(artifact, path):
            rows.append(Row(bench=bench, metric=concrete, kind=kind,
                            direction=direction, tolerance=tolerance,
                            labels=labels, methodology_sha=sha, value=value))
    return rows


# --------------------------------------------------------------------------
# Ledger file
# --------------------------------------------------------------------------

def load_ledger(path: str) -> dict:
    if os.path.exists(path):
        with open(path) as f:
            ledger = json.load(f)
        if ledger.get("version") != LEDGER_VERSION:
            raise ValueError(
                f"ledger {path} has version {ledger.get('version')!r}, "
                f"this tool writes version {LEDGER_VERSION}")
        return ledger
    return {
        "version": LEDGER_VERSION,
        "note": ("append-only perf trajectory; rows keyed (bench, metric); "
                 "maintained by frankenpaxos_tpu.bench.ledger"),
        "rows": [],
    }


def _artifact_sha(raw: bytes) -> str:
    return hashlib.sha256(raw).hexdigest()[:10]


def _find_row(ledger: dict, bench: str, metric: str) -> Optional[dict]:
    for row in ledger["rows"]:
        if row["bench"] == bench and row["metric"] == metric:
            return row
    return None


def update_ledger(ledger: dict, results_dir: str, tag: str) -> dict:
    """Extract headlines from every known artifact under ``results_dir``
    and append a history point per row when the artifact changed.
    Returns ``{"appended": n, "unchanged": n, "benches": [...]}``.
    """
    appended = unchanged = 0
    benches = []
    for bench in sorted(HEADLINES):
        path = os.path.join(results_dir, f"{bench}.json")
        if not os.path.exists(path):
            continue
        with open(path, "rb") as f:
            raw = f.read()
        sha = _artifact_sha(raw)
        artifact = json.loads(raw)
        benches.append(bench)
        for row in extract_rows(bench, artifact):
            entry = _find_row(ledger, bench, row.metric)
            if entry is None:
                entry = {"bench": bench, "metric": row.metric,
                         "kind": row.kind, "direction": row.direction,
                         "tolerance": row.tolerance, "labels": row.labels,
                         "methodology_sha": row.methodology_sha,
                         "history": []}
                ledger["rows"].append(entry)
            # Declared policy (kind/direction/tolerance) follows the
            # tool, not the file: update in place so edits here take
            # effect on the next --update without hand-editing JSON.
            entry["kind"] = row.kind
            entry["direction"] = row.direction
            entry["tolerance"] = row.tolerance
            entry["labels"] = row.labels
            entry["methodology_sha"] = row.methodology_sha
            history = entry["history"]
            if history and history[-1].get("artifact_sha") == sha:
                unchanged += 1
                continue
            history.append({"value": row.value, "tag": tag,
                            "artifact_sha": sha,
                            "source": f"{bench}.json"})
            appended += 1
    ledger["rows"].sort(key=lambda r: (r["bench"], r["metric"]))
    return {"appended": appended, "unchanged": unchanged, "benches": benches}


def save_ledger(ledger: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(ledger, f, indent=2, sort_keys=True)
        f.write("\n")


# --------------------------------------------------------------------------
# Check
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CheckResult:
    bench: str
    metric: str
    status: str            # pass | fail | skip | new | info
    reason: str
    committed: Any = None
    fresh: Any = None


def _band(committed: float, tolerance: dict, direction: str,
          smoke_mismatch: bool) -> tuple[float, str]:
    """(threshold, description) for the failing side of the band."""
    if "rel" in tolerance:
        rel = tolerance["rel"] + (SMOKE_EXTRA_REL if smoke_mismatch else 0.0)
        if direction == "higher":
            return committed * (1.0 - rel), f"-{rel:.0%} rel"
        return committed * (1.0 + rel), f"+{rel:.0%} rel"
    abs_tol = tolerance["abs"]
    if direction == "higher":
        return committed - abs_tol, f"-{abs_tol} abs"
    return committed + abs_tol, f"+{abs_tol} abs"


def check_row(entry: dict, fresh: Row) -> CheckResult:
    """Compare one fresh headline against its committed trajectory."""
    bench, metric = entry["bench"], entry["metric"]
    committed = entry["history"][-1]["value"] if entry["history"] else None
    if committed is None:
        return CheckResult(bench, metric, "new", "no committed history",
                           fresh=fresh.value)
    if fresh.direction == "info":
        return CheckResult(bench, metric, "info", "trajectory-only row",
                           committed, fresh.value)
    for key in COMPARABILITY_LABELS:
        have, want = fresh.labels.get(key), entry["labels"].get(key)
        if have != want:
            return CheckResult(
                bench, metric, "skip",
                f"label {key!r} mismatch (committed={want!r}, "
                f"fresh={have!r}): not comparable", committed, fresh.value)
    if fresh.methodology_sha != entry.get("methodology_sha"):
        return CheckResult(
            bench, metric, "skip",
            "methodology drift (bench measurement changed; re-run --update)",
            committed, fresh.value)
    smoke_mismatch = (fresh.labels.get("smoke", False)
                      != entry["labels"].get("smoke", False))
    if fresh.direction == "bool":
        if smoke_mismatch:
            # A reduced run's gate verdict is NOT the committed gate
            # (different widths/blocks); the numeric rows -- with their
            # smoke-widened bands -- carry the regression coverage.
            return CheckResult(
                bench, metric, "skip",
                "smoke/full mismatch: reduced-run gate is not the "
                "committed gate", committed, fresh.value)
        if bool(committed) and not bool(fresh.value):
            return CheckResult(bench, metric, "fail",
                               "committed True, fresh False",
                               committed, fresh.value)
        return CheckResult(bench, metric, "pass", "bool holds",
                           committed, fresh.value)
    threshold, band = _band(float(committed), entry["tolerance"],
                            fresh.direction, smoke_mismatch)
    value = float(fresh.value)
    if fresh.direction == "higher" and value < threshold:
        return CheckResult(bench, metric, "fail",
                           f"{value:.4g} < band floor {threshold:.4g} "
                           f"({band} of {float(committed):.4g})",
                           committed, fresh.value)
    if fresh.direction == "lower" and value > threshold:
        return CheckResult(bench, metric, "fail",
                           f"{value:.4g} > band ceiling {threshold:.4g} "
                           f"({band} of {float(committed):.4g})",
                           committed, fresh.value)
    return CheckResult(bench, metric, "pass", f"within {band}",
                       committed, fresh.value)


def check_against_ledger(ledger: dict, fresh_dir: str,
                         benches: Optional[list[str]] = None
                         ) -> list[CheckResult]:
    """Compare every fresh artifact in ``fresh_dir`` against the ledger.

    Only benches with a fresh artifact are checked -- the point is that
    a reduced CI re-run covers what it can re-run, explicitly.
    """
    results = []
    for bench in sorted(benches or HEADLINES):
        path = os.path.join(fresh_dir, f"{bench}.json")
        if not os.path.exists(path):
            continue
        with open(path) as f:
            artifact = json.load(f)
        fresh_rows = extract_rows(bench, artifact)
        if not fresh_rows:
            results.append(CheckResult(bench, "(none)", "skip",
                                       "no headline rows in fresh artifact"))
            continue
        for row in fresh_rows:
            entry = _find_row(ledger, bench, row.metric)
            if entry is None:
                results.append(CheckResult(bench, row.metric, "new",
                                           "not in committed ledger",
                                           fresh=row.value))
                continue
            results.append(check_row(entry, row))
    return results


def _print_report(results: list[CheckResult], out=sys.stdout) -> dict:
    counts: dict[str, int] = {}
    for r in results:
        counts[r.status] = counts.get(r.status, 0) + 1
        marker = {"pass": "ok  ", "fail": "FAIL", "skip": "skip",
                  "new": "new ", "info": "info"}[r.status]
        line = f"  [{marker}] {r.bench}:{r.metric}"
        if r.status in ("fail", "skip"):
            line += f" -- {r.reason}"
        elif r.status == "pass":
            line += f" ({r.fresh!r} vs {r.committed!r}, {r.reason})"
        print(line, file=out)
    print(f"ledger check: {counts}", file=out)
    return counts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m frankenpaxos_tpu.bench.ledger",
        description=__doc__.split("\n\n")[0])
    parser.add_argument("--ledger", default=DEFAULT_LEDGER)
    parser.add_argument("--results", default=DEFAULT_RESULTS_DIR,
                        help="artifact dir for --update")
    parser.add_argument("--update", action="store_true",
                        help="append current artifact headlines to the ledger")
    parser.add_argument("--tag", default="untagged",
                        help="trajectory tag for --update (e.g. a PR name)")
    parser.add_argument("--check", action="store_true",
                        help="compare fresh artifacts against the ledger")
    parser.add_argument("--fresh", default=None,
                        help="dir of fresh artifacts for --check "
                             "(default: --results)")
    parser.add_argument("--report", default=None,
                        help="also write the check report as JSON here")
    args = parser.parse_args(argv)

    if args.update == args.check:
        parser.error("exactly one of --update / --check required")

    if args.update:
        ledger = load_ledger(args.ledger)
        stats = update_ledger(ledger, args.results, args.tag)
        save_ledger(ledger, args.ledger)
        print(f"ledger update: {stats['appended']} point(s) appended, "
              f"{stats['unchanged']} unchanged, benches: "
              f"{', '.join(stats['benches'])}")
        return 0

    if not os.path.exists(args.ledger):
        print(f"no ledger at {args.ledger}; run --update first",
              file=sys.stderr)
        return 2
    ledger = load_ledger(args.ledger)
    results = check_against_ledger(ledger, args.fresh or args.results)
    counts = _print_report(results)
    if args.report:
        with open(args.report, "w") as f:
            json.dump({"counts": counts,
                       "results": [dataclasses.asdict(r) for r in results]},
                      f, indent=2)
            f.write("\n")
    return 1 if counts.get("fail") else 0


if __name__ == "__main__":
    raise SystemExit(main())
