"""Generic deployment smoke: any protocol, every role its own process.

The analog of scripts/benchmark_smoke.sh (which runs
``benchmarks.<proto>.smoke`` for 18 protocols over SSH-to-localhost,
benchmark_smoke.sh:5-18): compute a localhost placement from the
deployment registry, launch every role via the CLI over real TCP, drive
a few commands from an in-process client, and assert replies arrive.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time

from frankenpaxos_tpu.bench.harness import (
    BenchmarkDirectory,
    free_port,
    LocalHost,
)
from frankenpaxos_tpu.deploy import DeployCtx, get_protocol
from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport


def role_process_env() -> dict:
    """Environment for role subprocesses: drop the TPU plugin's
    sitecustomize from PYTHONPATH (it costs ~2s of import per process
    and CPU-pinned roles never need the accelerator)."""
    env = os.environ.copy()
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and os.path.basename(p.rstrip("/")) != ".axon_site"]
    env["PYTHONPATH"] = os.pathsep.join(parts)
    # Force cpu: the parent may carry JAX_PLATFORMS=axon, which would
    # make every role process hunt for the (stripped) TPU plugin.
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    return env


def launch_roles(bench: BenchmarkDirectory, protocol_name: str,
                 config_path: str, config, *, state_machine: str,
                 overrides: "dict[str, str] | None" = None,
                 prometheus: bool = False, supernode: bool = False,
                 profiled: bool = False,
                 ready_timeout_s: float = 120.0,
                 wal_dir: "str | None" = None,
                 trace_dir: "str | None" = None,
                 trace_sample: float = 1.0,
                 extra_role_args: "dict | None" = None,
                 host=None) -> list:
    """Start every role of ``protocol_name`` as a subprocess and wait
    until each reports it is listening.

    With ``prometheus=True`` each role gets a ``/metrics`` endpoint on a
    fresh port; the ``{label: port}`` map lands in
    ``bench.prometheus_ports`` and a generated scrape config in
    ``prometheus.json`` (benchmarks/prometheus.py:10-60 semantics).

    With ``supernode=True`` all roles run colocated in ONE process (the
    coupled baseline, SuperNode.scala:22+).

    With ``profiled=True`` every role runs under cProfile (the
    benchmarks/perf_util.py:37 perf-wrap analog for Python roles); the
    role's SIGTERM handler exits cleanly so ``{label}.prof`` dumps at
    kill time -- render it with ``write_profile_reports``.

    ``host`` (default a LocalHost) is the machine the roles launch on:
    pass a ``bench.remote.RemoteHost`` to deploy through its shell
    (ssh, or the loopback stand-in) -- the reference's SSH deployment
    seam (benchmarks/host.py:36-50). Config/log paths pass through
    unchanged on shared filesystems; a RemoteHost with
    ``staging_dir``/``local_root`` set ships them for disjoint
    filesystems (see bench/remote.py).

    ``wal_dir`` turns on per-role durability (``--wal_dir``, wal/):
    WAL-capable roles log to <wal_dir>/<label> and recover on
    relaunch -- the seam the chaos driver (bench/chaos.py) uses to
    SIGKILL and resurrect roles mid-benchmark.

    ``extra_role_args`` maps a role label to extra CLI args appended
    to THAT role's command only (paxchaos: per-acceptor
    ``--fault_fsync`` arming from ``faults.fsync_fault_args``); the
    args are recorded in the launch spec, so a chaos relaunch keeps
    the role's fault arming.

    ``trace_dir`` turns on paxtrace (``--trace``, obs/): every role
    emits spans to <trace_dir>/<label>.trace.jsonl and keeps its
    crash flight-recorder ring in <trace_dir>/<label>.flight --
    ``bench/chaos.py`` snapshots the ring of a SIGKILL'd role for the
    post-mortem. ``trace_sample`` is the root sampling rate.

    Every launched command is recorded in ``bench.role_commands`` so a
    role can be relaunched verbatim (same ports, same wal_dir) after a
    kill.
    """
    protocol = get_protocol(protocol_name)
    host = host or LocalHost()
    # TPU-backed roles need the accelerator plugin; everything else gets
    # the stripped fast-start environment.
    needs_tpu = any(v == "tpu" for v in (overrides or {}).values())
    env = None if needs_tpu else role_process_env()
    # Explicit wait-for-listen handshake (local deployments): the
    # launcher listens on an ephemeral port; each role connects back
    # and reports its label AFTER binding its listeners, constructing
    # its actors, and starting its metrics endpoint. This replaces the
    # old sleep-and-grep of role logs for "listening", which raced log
    # flushing under load (the deployment startup race behind the
    # flaky read/write-benchmark test). Remote hosts keep the log-grep
    # path through host.grep_ready: their roles can't necessarily dial
    # back to a listener on this machine's loopback.
    handshake = type(host) is LocalHost
    ready_server = None
    ready_args: list = []
    if handshake:
        ready_server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ready_server.bind(("127.0.0.1", 0))
        ready_server.listen(128)
        ready_args = ["--ready_addr",
                      f"127.0.0.1:{ready_server.getsockname()[1]}"]
    labels = []
    prometheus_ports: dict[str, int] = {}
    if supernode:
        launch_plan = [("supernode", 0)]
    else:
        launch_plan = [(role_name, index)
                       for role_name, role in protocol.roles.items()
                       for index in range(len(role.addresses(config)))]
    bench.role_commands = {}
    for role_name, index in launch_plan:
        label = f"{role_name}_{index}"
        labels.append(label)
        cmd = [sys.executable]
        if profiled:
            cmd += ["-m", "cProfile", "-o", bench.abspath(f"{label}.prof")]
        cmd += ["-m", "frankenpaxos_tpu.cli",
                "--protocol", protocol_name, "--role", role_name,
                "--index", str(index), "--config", config_path,
                "--state_machine", state_machine,
                "--seed", str(index)] + ready_args
        if prometheus:
            prometheus_ports[label] = free_port()
            cmd += ["--prometheus_port",
                    str(prometheus_ports[label])]
        if wal_dir:
            cmd += ["--wal_dir", wal_dir]
        if trace_dir:
            cmd += ["--trace", trace_dir,
                    "--trace_sample", str(trace_sample)]
        for key, value in (overrides or {}).items():
            cmd.append(f"--options.{key}={value}")
        cmd += (extra_role_args or {}).get(label, [])
        bench.role_commands[label] = (cmd, env)
        bench.popen(host, label, cmd, env=env)
    bench.prometheus_ports = prometheus_ports
    bench.trace_dir = trace_dir
    if prometheus:
        from frankenpaxos_tpu.bench.metrics import scrape_config

        bench.write_json("prometheus.json",
                         scrape_config(prometheus_ports))

    try:
        pending = _wait_ready(bench, host, labels, ready_server,
                              ready_timeout_s)
        if pending and type(host) is LocalHost:
            # THE unified readiness retry (every deployment entry point
            # -- smoke, benchmarks, LT suites, sweeps -- comes through
            # here): a role that lost the startup scheduling lottery on
            # a loaded host gets killed and relaunched VERBATIM (same
            # ports, same wal_dir) once, with a fresh full deadline.
            # Callers that want fresh ports on top of this (a stolen
            # free_port) keep their own whole-placement retry.
            for label in sorted(pending):
                print(f"role {label} not ready after "
                      f"{ready_timeout_s:.0f}s; relaunching it")
                bench.labeled_procs[label].kill()
                log = bench.abspath(f"{label}.log")
                if os.path.exists(log):
                    os.replace(log, f"{log}.attempt1")
                cmd, cmd_env = bench.role_commands[label]
                bench.popen(host, label, cmd, env=cmd_env)
            pending = _wait_ready(bench, host, sorted(pending),
                                  ready_server, ready_timeout_s)
    finally:
        if ready_server is not None:
            ready_server.close()
    if pending:
        bench.cleanup()
        raise RuntimeError(
            f"{protocol_name} roles never became ready: {sorted(pending)}")
    return labels


def _wait_ready(bench: BenchmarkDirectory, host, labels: list,
                ready_server, ready_timeout_s: float) -> set:
    """Wait for every role to become ready; returns the labels that
    never did. With ``ready_server`` set, readiness is the role's own
    connect-back handshake (and a role process that EXITS before
    reporting fails immediately instead of burning the full timeout);
    otherwise fall back to polling role logs for "listening"."""
    deadline = time.time() + ready_timeout_s
    pending = set(labels)
    if ready_server is not None:
        ready_server.settimeout(0.25)
        while pending and time.time() < deadline:
            dead = [label for label in sorted(pending)
                    if not bench.labeled_procs[label].running()]
            if dead:
                bench.cleanup()
                raise RuntimeError(
                    f"role process(es) exited before becoming ready: "
                    f"{dead}; see {bench.path}/<label>.log")
            try:
                conn, _ = ready_server.accept()
            except socket.timeout:
                continue
            try:
                conn.settimeout(5)
                with conn, conn.makefile() as f:
                    pending.discard(f.readline().strip())
            except OSError:
                # A half-open/reset connection reads as "not ready yet";
                # the deadline still bounds the wait.
                pass
        return pending
    while pending and time.time() < deadline:
        # Through the host (one round-trip for ALL pending labels) so
        # remote logs -- possibly on a disjoint filesystem, see
        # bench/remote.py RemoteHost -- are readable.
        ready = host.grep_ready(
            [bench.abspath(f"{label}.log") for label in pending],
            "listening")
        pending -= {label for label in pending
                    if bench.abspath(f"{label}.log") in ready}
        time.sleep(0.1)
    return pending


def run_protocol_smoke(bench: BenchmarkDirectory, protocol_name: str, *,
                       f: int = 1, num_commands: int = 3,
                       state_machine: str = "AppendLog",
                       overrides: "dict[str, str] | None" = None,
                       command_timeout_s: float = 30.0,
                       host=None, prometheus: bool = False,
                       trace_dir: "str | None" = None) -> dict:
    """Deploy ``protocol_name`` over localhost TCP and commit
    ``num_commands`` commands through it. ``host`` launches the roles
    on another machine (see ``launch_roles``)."""
    protocol = get_protocol(protocol_name)
    raw = protocol.cluster(f, lambda: ["127.0.0.1", free_port()])
    config_path = bench.write_json("config.json", raw)
    config = protocol.load_config(raw)

    # Leaders' very first Phase1as can race slower-starting acceptor
    # processes; a fast resend rides that out without a long stall.
    overrides = {"resend_phase1as_period_s": "0.5", **(overrides or {})}

    t0 = time.time()
    labels = launch_roles(bench, protocol_name, config_path, config,
                          state_machine=state_machine,
                          overrides=overrides, host=host,
                          prometheus=prometheus, trace_dir=trace_dir)
    ready_s = time.time() - t0

    # In-process client over real TCP. A short resend period rides out
    # any leader still finishing Phase1/matchmaking/elections. The
    # try/finally starts HERE so a failed client-transport bind still
    # kills the role processes.
    transport = None
    try:
        logger = FakeLogger(LogLevel.FATAL)
        transport = TcpTransport(("127.0.0.1", free_port()), logger)
        transport.start()
        ctx = DeployCtx(config=config, transport=transport, logger=logger,
                        overrides={"resend_period_s": "0.5",
                                   "repropose_period_s": "0.5",
                                   "ping_period_s": "0.5"},
                        seed=0xC11E47, state_machine=state_machine)
        client = protocol.make_client(ctx, transport.listen_address)
        latencies = []
        for tag in range(num_commands):
            done = threading.Event()
            start = time.perf_counter()
            transport.loop.call_soon_threadsafe(
                protocol.drive, client, tag, lambda *_: done.set())
            if not done.wait(timeout=command_timeout_s):
                raise RuntimeError(
                    f"{protocol_name}: command {tag} never completed "
                    f"(roles: {labels})")
            latencies.append(time.perf_counter() - start)
    finally:
        if transport is not None:
            transport.stop()
        bench.cleanup()

    return {
        "protocol": protocol_name,
        "num_roles": len(labels),
        "num_commands": num_commands,
        "ready_s": round(ready_s, 3),
        "latency_ms": [round(x * 1000, 3) for x in latencies],
    }


def write_profile_reports(bench: BenchmarkDirectory,
                          top: int = 25) -> "dict[str, str]":
    """Render each role's cProfile dump (from ``profiled=True``) to a
    cumulative-time text report, the flamegraph-summary analog of
    benchmarks/perf_util.py. Returns {label: report_path}."""
    import glob
    import io
    import pstats

    reports = {}
    for prof in glob.glob(bench.abspath("*.prof")):
        label = os.path.basename(prof)[:-len(".prof")]
        out = io.StringIO()
        try:
            stats = pstats.Stats(prof, stream=out)
        except Exception as e:  # noqa: BLE001 - truncated dump (SIGKILL)
            print(f"skipping unreadable profile {prof}: {e!r}")
            continue
        stats.sort_stats("cumulative").print_stats(top)
        path = bench.abspath(f"{label}.profile.txt")
        with open(path, "w") as f:
            f.write(out.getvalue())
        reports[label] = path
    return reports
