"""The WAL group-commit batching cliff (paxchaos, "Paxos in the Cloud").

"The Performance of Paxos in the Cloud" (PAPERS.md) shows deployed
Paxos throughput living or dying on how many log records amortize one
fsync: below the knee of the batch-size curve every record pays a
whole (sometimes stalled) fsync and throughput falls off a cliff;
past it the fsync amortizes away and the curve plateaus. This bench
drives a REAL FileStorage WAL through that curve under the fsync
fault hook (``wal/faults.FsyncStallStorage``, count-cadence BLOCKING
stalls -- the deployed storage-fault arm), locates the knee, and
GATES that the configured operating point sits on the plateau side of
it: a regression that moves the knee past the operating point (a
heavier record codec, an extra fsync on the commit path, a lost
buffering layer) fails CI before it ships as a silent 10x deployed
throughput loss.

Two arms per run: fault-on (the gated one -- stalls amplify exactly
the per-sync cost the knee measures, pushing it right) and a
fault-off reference curve. Committed artifact:
``bench_results/batching_cliff.json``.

Usage::

    python -m frankenpaxos_tpu.bench.batching_cliff \
        --out bench_results/batching_cliff.json
    python -m frankenpaxos_tpu.bench.batching_cliff --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import tempfile
import time

from frankenpaxos_tpu.wal import FileStorage, FsyncStallStorage, Wal
from frankenpaxos_tpu.wal.records import WalVote

#: The operating point the gate protects: WAL group commit is
#: per-DRAIN (one sync per event-loop drain), and a LOADED role's
#: drain batches its whole event-loop pass -- easily 100+ records --
#: so the knee must sit at or below this batch size for production
#: group commits to run on the amortized side of the cliff.
OPERATING_BATCH = 128

#: The knee: the smallest batch size reaching this fraction of the
#: largest batch's throughput. 0.4 is chosen to be HOST-ROBUST: in
#: the fsync-dominated limit rps is linear in batch size, so
#: rps(128)/rps(256) -> 0.5 > 0.4 on arbitrarily slow storage --
#: the knee can only blow past 128 if something per-RECORD got
#: fsync-expensive, which is exactly the regression to catch.
KNEE_FRACTION = 0.4

BATCH_SIZES = (1, 2, 4, 8, 16, 32, 64, 128, 256)

#: Fault cadence: a blocking stall every 25th sync (in-process there
#: is no cross-process alignment to preserve, so the count cadence is
#: the right shape -- it scales stall exposure with SYNC COUNT, which
#: is exactly the cliff's mechanism: small batches -> more syncs ->
#: more stalls per record).
STALL_EVERY = 25
STALL_S = 0.002

PAYLOAD = b"x" * 64


def _quantile(sorted_values: list, q: float) -> float:
    return sorted_values[min(len(sorted_values) - 1,
                             int(q * len(sorted_values)))]


def run_arm(root: str, *, records: int, fault: bool,
            batch_sizes=BATCH_SIZES, seed: int = 0) -> dict:
    curve: dict = {}
    for batch in batch_sizes:
        directory = os.path.join(
            root, f"b{batch}_{'on' if fault else 'off'}")
        storage = FileStorage(directory)
        if fault:
            storage = FsyncStallStorage(
                storage, seed=seed, label=f"b{batch}",
                stall_every=STALL_EVERY, stall_s=STALL_S,
                blocking=True)
        wal = Wal(storage, segment_bytes=64 << 20,
                  compact_every_bytes=256 << 20)
        latencies: list = []
        n = 0
        t0 = time.perf_counter()
        while n < records:
            t_batch = time.perf_counter()
            for i in range(batch):
                wal.append(WalVote(slot=n + i, round=1,
                                   value=PAYLOAD))
            wal.sync()
            latencies.append(time.perf_counter() - t_batch)
            n += batch
        total = time.perf_counter() - t0
        stalls = len(storage.stalls) if fault else 0
        wal.close()
        latencies.sort()
        curve[batch] = {
            "records_per_s": round(n / total, 1),
            "syncs": len(latencies),
            "stalls": stalls,
            "p50_commit_s": round(_quantile(latencies, 0.5), 6),
            "p99_commit_s": round(_quantile(latencies, 0.99), 6),
        }
    return curve


def find_knee(curve: dict) -> dict:
    plateau = max(row["records_per_s"] for row in curve.values())
    knee = next(batch for batch in sorted(curve)
                if curve[batch]["records_per_s"]
                >= KNEE_FRACTION * plateau)
    floor = curve[min(curve)]["records_per_s"]
    return {
        "plateau_records_per_s": plateau,
        "knee_batch": knee,
        "knee_fraction": KNEE_FRACTION,
        "cliff_depth": round(plateau / floor, 1),
    }


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None)
    parser.add_argument("--smoke", action="store_true",
                        help="reduced record count (CI/test sizing)")
    parser.add_argument("--records", type=int, default=None)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    records = args.records or (1024 if args.smoke else 4096)
    root = tempfile.mkdtemp(prefix="fpx_batching_cliff_")
    t0 = time.time()
    try:
        arms = {
            "fault_on": run_arm(root, records=records, fault=True,
                                seed=args.seed),
            "fault_off": run_arm(root, records=records, fault=False,
                                 seed=args.seed),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)
    knees = {arm: find_knee(curve) for arm, curve in arms.items()}
    on = knees["fault_on"]
    gates = {
        # The operating point sits on the plateau side of the knee,
        # UNDER the fault: production drains never pay the cliff.
        "knee_at_or_below_operating_point": {
            "knee_batch": on["knee_batch"],
            "operating_batch": OPERATING_BATCH,
            "passed": on["knee_batch"] <= OPERATING_BATCH,
        },
        # The cliff is real (else the bench measures nothing -- and a
        # per-record fsync regression FLATTENS the curve, failing
        # here): the plateau clears the single-record floor by a wide
        # margin.
        "cliff_exists": {
            "cliff_depth": on["cliff_depth"],
            "bound": 10.0,
            "passed": on["cliff_depth"] >= 10.0,
        },
    }
    result = {
        "benchmark": "batching_cliff",
        "host_cpus": os.cpu_count(),
        "records_per_batch_size": records,
        "stall_every": STALL_EVERY,
        "stall_s": STALL_S,
        "curves": arms,
        "knees": knees,
        "gates": gates,
        "gate_passed": all(g["passed"] for g in gates.values()),
        "seconds": round(time.time() - t0, 1),
        "methodology": (
            "append B WalVote records + one group-commit sync per "
            "batch against a real FileStorage (blocking "
            "FsyncStallStorage every 25th sync on the fault-on arm); "
            "knee = smallest B reaching 40% of the largest batch's "
            "records/s; gate: knee <= the per-drain operating point "
            "(128) so production group commits run on the amortized "
            "side, plus a >=10x cliff-depth floor that a per-record "
            "fsync regression would flatten."),
    }
    print(json.dumps({
        "gate_passed": result["gate_passed"],
        "knee_on": on["knee_batch"],
        "knee_off": knees["fault_off"]["knee_batch"],
        "cliff_depth_on": on["cliff_depth"],
        "plateau_on": on["plateau_records_per_s"],
        "seconds": result["seconds"],
    }, indent=2))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    return result


if __name__ == "__main__":
    raise SystemExit(0 if main()["gate_passed"] else 1)
