"""paxworld global serving bench: the gated scenario matrix.

Runs the fused paxgeo x paxload scenario matrix (scenarios/matrix.py)
and writes ``bench_results/global_lt.json`` -- one SLO row per
scenario (goodput floor, admitted p99/p999 ceilings, zero acked-write
loss, control plane never shed, bounded recovery, plus per-scenario
extras), each deterministic per seed (the golden test pins the
delivery-history digest). ``--csv`` additionally writes the flat
per-scenario SLO clause table the CI ``global-smoke`` job uploads.

Also records ``scenario_overhead``: the overload_lt alternating-chunk
+ GC-off paired A/B proving the paxworld loadgen port -- budgeted
delivery through the wave engine (``deliver_all_coalesced`` /
``Actor.receive_batch``) instead of the legacy per-message
``_deliver`` loop -- costs nothing when faults/geo are off (<3% gate;
in practice the wave path is the faster one). The fsync-stall fault
hook has zero WAL hot-path cost BY CONSTRUCTION: it is a wrapping
storage object (wal/faults.py) that only exists when a scenario arms
it -- the unwrapped path contains no flag, attribute, or import.

Usage::

    python -m frankenpaxos_tpu.bench.global_lt \
        --out bench_results/global_lt.json
    python -m frankenpaxos_tpu.bench.global_lt --smoke \
        --out global_lt_smoke.json --csv global_lt_smoke.csv
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import statistics
import time

#: Overhead A/B shape (the overload_lt calibration,
#: docs/BENCH_HISTORY.md): ~24 ticks per interleave chunk, 24 timed
#: chunks per block, 4 warm-up chunks discarded, median over blocks.
OVERHEAD_CHUNK_TICKS = 24
OVERHEAD_CHUNKS = 24
OVERHEAD_WARMUP_CHUNKS = 4
OVERHEAD_BLOCKS = 7


def _legacy_patch():
    """(enter, exit) pinning the PRE-PAXWORLD ``_deliver_budgeted``
    body (verbatim: per-message ``transport._deliver`` with per-4096
    snapshot waves and explicit drains) onto SimOverloadDriver, so the
    A/B measures exactly the wave-engine port."""
    from frankenpaxos_tpu.serve.loadgen import SimOverloadDriver

    def legacy_deliver_budgeted(self) -> None:
        transport = self.sim.transport
        while self.budget > 0 and transport.messages:
            wave = transport.messages[:4096]
            touched: list = []
            seen: set = set()
            for message in wave:
                if self.budget <= 0:
                    break
                before = len(self.completions)
                actor = transport._deliver(message)
                after = len(self.completions)
                self.budget -= self.msg_cost \
                    + (after - before) * self.cmd_cost
                if actor is not None and id(actor) not in seen:
                    seen.add(id(actor))
                    touched.append(actor)
            for actor in touched:
                transport._drain(actor)

    original = SimOverloadDriver._deliver_budgeted

    def enter():
        SimOverloadDriver._deliver_budgeted = legacy_deliver_budgeted

    def exit():
        SimOverloadDriver._deliver_budgeted = original

    return enter, exit


def _make_driver(seed: int):
    from frankenpaxos_tpu.bench.workload import OpenLoopWorkload
    from frankenpaxos_tpu.serve.loadgen import SimOverloadDriver
    from tests.protocols.multipaxos_harness import make_multipaxos

    sim = make_multipaxos(f=1, coalesced=True, seed=seed)
    workload = OpenLoopWorkload(rate=2000.0, zipf_s=1.1,
                                num_keys=1 << 12)
    return SimOverloadDriver(sim, workload, num_sessions=1 << 16,
                             capacity_cmds_per_s=500.0,
                             msg_cost_s=0.0001, seed=seed)


def measure_overhead_block(seed: int = 0) -> float:
    """One chunk-interleaved A/B block: two persistent drivers (the
    shipped wave-engine delivery loop vs the verbatim legacy
    per-message body) ticked alternately with GC disabled, arm order
    flipped every chunk; returns the wave/legacy time ratio."""
    import gc

    enter, exit = _legacy_patch()
    drivers = {}
    for arm in ("wave", "legacy"):
        if arm == "legacy":
            enter()
        try:
            drivers[arm] = _make_driver(seed)
            for _ in range(OVERHEAD_CHUNK_TICKS):
                drivers[arm].tick()
        finally:
            if arm == "legacy":
                exit()
    total = {"wave": 0.0, "legacy": 0.0}
    gc.collect()
    gc.disable()
    try:
        for k in range(OVERHEAD_WARMUP_CHUNKS + OVERHEAD_CHUNKS):
            order = (("wave", "legacy") if k % 2
                     else ("legacy", "wave"))
            for arm in order:
                if arm == "legacy":
                    enter()
                try:
                    t0 = time.perf_counter()
                    for _ in range(OVERHEAD_CHUNK_TICKS):
                        drivers[arm].tick()
                    elapsed = time.perf_counter() - t0
                finally:
                    if arm == "legacy":
                        exit()
                if k >= OVERHEAD_WARMUP_CHUNKS:
                    total[arm] += elapsed
    finally:
        gc.enable()
    return total["wave"] / total["legacy"]


def scenario_overhead(blocks: int = OVERHEAD_BLOCKS) -> dict:
    ratios = sorted(measure_overhead_block(seed=b)
                    for b in range(blocks))
    median = statistics.median(ratios)
    overhead_pct = round((median - 1.0) * 100, 2)
    return {
        "ratio_wave_over_legacy_median": round(median, 4),
        "ratio_range": [round(ratios[0], 4), round(ratios[-1], 4)],
        "overhead_pct": overhead_pct,
        "gate": ("wave-engine loadgen delivery (faults/geo off) must "
                 "cost < 3% vs the legacy per-message loop"),
        "estimator": ("median of chunk-interleaved gc-disabled block "
                      "ratios (overload_lt methodology)"),
        "fsync_hook_hot_path": (
            "zero by construction: wal/faults.py is a wrapping "
            "storage only instantiated when a scenario arms it"),
        "gate_passed": overhead_pct < 3.0,
    }


def write_csv(path: str, matrix: dict) -> None:
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["scenario", "clause", "value", "bound",
                         "kind", "passed"])
        for row in matrix["rows"]:
            for name, c in row["slo"].items():
                writer.writerow([row["scenario"], name, c["value"],
                                 c["bound"], c["kind"], c["passed"]])


def main(argv=None) -> dict:
    from frankenpaxos_tpu.scenarios import FULL, SMOKE, run_matrix

    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None)
    parser.add_argument("--csv", default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--only", default=None,
                        help="substring filter on scenario names")
    parser.add_argument("--smoke", action="store_true",
                        help="reduced scale for the CI global-smoke "
                             "job (~3 min incl. the overhead A/B)")
    parser.add_argument("--skip_overhead", action="store_true")
    args = parser.parse_args(argv)

    t0 = time.time()
    scale = SMOKE if args.smoke else FULL
    matrix = run_matrix(seed=args.seed, scale=scale, only=args.only)
    for row in matrix["rows"]:
        print(json.dumps({
            "scenario": row["scenario"],
            "gate_passed": row["gate_passed"],
            "goodput": row["stats"]["goodput_cmds_per_s"],
            "wall_seconds": row["wall_seconds"],
        }), flush=True)

    result = {
        "benchmark": "global_lt",
        "host_cpus": os.cpu_count(),
        "matrix": matrix,
        "methodology": (
            "scenarios/matrix.py: the SoA open-loop load tier "
            "(serve/loadgen.GeoOverloadDriver) drives WPaxos/CRAQ "
            "over GeoSimTransport WAN topologies on ONE virtual "
            "clock; delivery rides the paxsim wave engine under the "
            "overload CPU-budget model; faults (zone SIGKILL, "
            "region partition, fsync stalls via wal/faults.py) are "
            "seeded and byte-deterministic -- the golden test pins "
            "the delivery-history digest per seed."),
    }
    if not args.skip_overhead:
        result["scenario_overhead"] = scenario_overhead()
    result["seconds"] = round(time.time() - t0, 1)
    result["gate_passed"] = matrix["gate_passed"] and result.get(
        "scenario_overhead", {}).get("gate_passed", True)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    if args.csv:
        write_csv(args.csv, matrix)
    print(json.dumps({
        "gate_passed": result["gate_passed"],
        "scenarios": {r["scenario"]: r["gate_passed"]
                      for r in matrix["rows"]},
        "overhead_pct": result.get("scenario_overhead", {}).get(
            "overhead_pct"),
        "seconds": result["seconds"],
    }, indent=2))
    return result


if __name__ == "__main__":
    raise SystemExit(0 if main()["gate_passed"] else 1)
