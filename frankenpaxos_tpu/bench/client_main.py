"""Benchmark client process: closed-loop workload driver.

The analog of the reference's ClientMain + BenchmarkUtil
(jvm/.../multipaxos/ClientMain.scala, BenchmarkUtil.scala:9-160): run
``--num_clients`` closed loops (one per pseudonym) against a deployed
cluster for ``--duration`` seconds, drawing ops from a ReadWriteWorkload,
and write one CSV row per completed op:
``kind,start_unix_s,latency_s`` (benchmark.py:310-335's recorder shape).

Ops are chained on the transport's event loop -- each completion issues
the pseudonym's next op -- so one process drives many concurrent closed
loops without a thread per client.

Usage::

    python -m frankenpaxos_tpu.bench.client_main --config cluster.json \
        --workload '{"name": "uniform_read_write", "read_fraction": 0.9}' \
        --duration 5 --num_clients 20 --out client_data.csv
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time

from frankenpaxos_tpu.bench.harness import free_port
from frankenpaxos_tpu.bench.workload import (
    READ_METHODS,
    StringWorkload,
    workload_from_dict,
    WRITE,
    WriteOnlyWorkload,
)
from frankenpaxos_tpu.deploy import DeployCtx, get_protocol
from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport
from frankenpaxos_tpu.serve.backoff import RETRY_EXHAUSTED



def _closed_loops(transport, num_loops: int, duration_s: float,
                  warmup_s: float, issue_op) -> list:
    """Shared closed-loop machinery: run ``num_loops`` callback-chained
    loops on the transport's event loop for ``duration_s`` (after a
    ``warmup_s`` settling window), recording one row per completed op.

    ``issue_op(i, finished)`` issues loop ``i``'s next op and arranges
    for ``finished(kind)`` on completion. Reissues are rescheduled via
    call_soon rather than recursed: a protocol that answers
    synchronously (an already-chosen single-decree value) would
    otherwise blow the stack.
    """
    rows: list = []
    done = threading.Event()
    stop_at = time.time() + warmup_s + duration_s
    measure_from = time.time() + warmup_s
    live = {"count": num_loops}

    def issue(i: int) -> None:
        now = time.time()
        if now >= stop_at:
            live["count"] -= 1
            if live["count"] == 0:
                done.set()
            return
        t0 = time.perf_counter()

        def finished(kind: str) -> None:
            if now >= measure_from:
                rows.append((kind, now, time.perf_counter() - t0))
            transport.loop.call_soon(issue, i)

        issue_op(i, finished)

    for i in range(num_loops):
        transport.loop.call_soon_threadsafe(issue, i)
    done.wait(timeout=warmup_s + duration_s + 30)
    transport.stop()
    return rows


def run(protocol_name: str, config_raw: dict, workload, *,
        num_clients: int, duration_s: float, read_consistency: str,
        seed: int = 0, warmup_s: float = 0.25,
        overrides: dict | None = None) -> list:
    """Drive the workload against multipaxos (pseudonym-keyed write/read
    client loops); returns [(kind, start_unix_s, latency_s)]."""
    protocol = get_protocol(protocol_name)
    config = protocol.load_config(config_raw)
    logger = FakeLogger(LogLevel.FATAL)
    transport = TcpTransport(("127.0.0.1", free_port()), logger)
    transport.start()
    ctx = DeployCtx(config=config, transport=transport, logger=logger,
                    overrides=overrides or {}, seed=seed)
    client = protocol.make_client(ctx, transport.listen_address)
    read_method = READ_METHODS[read_consistency]
    rngs = [random.Random((seed << 20) + p) for p in range(num_clients)]

    def issue_op(pseudonym: int, finished) -> None:
        kind, command = workload.get(rngs[pseudonym])
        op = (client.write if kind == WRITE
              else getattr(client, read_method))
        # Retry-budget give-ups are labeled, never counted as acks --
        # a backoff-dominated RETRY_EXHAUSTED sample would otherwise
        # inflate throughput and corrupt the latency percentiles.
        op(pseudonym, command,
           lambda reply: finished(
               "giveup" if reply is RETRY_EXHAUSTED else kind))

    return _closed_loops(transport, num_clients, duration_s, warmup_s,
                         issue_op)


def run_open_loop(protocol_name: str, config_raw: dict, workload, *,
                  num_sessions: int, duration_s: float,
                  read_consistency: str = "linearizable", seed: int = 0,
                  warmup_s: float = 0.5,
                  overrides: dict | None = None) -> list:
    """OPEN-loop driver (paxload): ops issue on the arrival process's
    schedule, independent of completions -- the load shape overload
    needs (a closed loop self-throttles and can never offer more than
    the cluster absorbs). ``workload`` is the SHARED
    :class:`~frankenpaxos_tpu.bench.workload.OpenLoopWorkload`, the
    same definition the sim tier draws from (serve/loadgen.py), so
    "10x offered load" means the same arrival process, key skew, and
    mix on both arms.

    Sessions are a pseudonym pool: an arrival binds a free pseudonym;
    when none is free the arrival is dropped-at-the-source and counted
    (``thinned`` rows are not latencies -- the row kind says what
    happened: write/read kinds, ``giveup`` for RETRY_EXHAUSTED
    conclusions). Returns [(kind, start_unix_s, latency_s)] plus one
    ``("thinned", t, count)`` tail row when any arrivals were thinned.
    """
    import numpy as np

    protocol = get_protocol(protocol_name)
    config = protocol.load_config(config_raw)
    logger = FakeLogger(LogLevel.FATAL)
    transport = TcpTransport(("127.0.0.1", free_port()), logger)
    transport.start()
    ctx = DeployCtx(config=config, transport=transport, logger=logger,
                    overrides=overrides or {}, seed=seed)
    client = protocol.make_client(ctx, transport.listen_address)
    read_method = READ_METHODS[read_consistency]
    np_rng = np.random.default_rng(seed)
    rng = random.Random(seed)
    rows: list = []
    done = threading.Event()
    idle = list(range(num_sessions))
    thinned = {"count": 0}
    dt = 0.02
    t_start = time.time()
    measure_from = t_start + warmup_s
    stop_at = t_start + warmup_s + duration_s

    # Absolute fire schedule: each window draws arrivals for exactly dt
    # of the arrival process, and a window that runs long is followed by
    # catch-up windows back-to-back, so offered load stays rate*duration
    # even when per-window work inflates the period (otherwise the
    # driver would self-throttle at exactly the loads it exists for).
    sched = {"t": t_start}

    def window() -> None:
        now = time.time()
        if now >= stop_at:
            done.set()
            return
        for _ in range(workload.arrival_count(np_rng, sched["t"] - t_start,
                                              dt)):
            if not idle:
                thinned["count"] += 1
                continue
            pseudonym = idle.pop()
            kind, command = workload.get(rng)
            t0 = time.perf_counter()

            def finished(result, pseudonym=pseudonym, kind=kind,
                         t0=t0, issued=now) -> None:
                idle.append(pseudonym)
                label = ("giveup" if result is RETRY_EXHAUSTED
                         else kind)
                if issued >= measure_from:
                    rows.append((label, issued,
                                 time.perf_counter() - t0))

            op = (client.write if kind == WRITE
                  else getattr(client, read_method))
            op(pseudonym, command, finished)
        flush = getattr(client, "flush_writes", None)
        if flush is not None:
            flush()
        sched["t"] += dt
        transport.loop.call_later(max(0.0, sched["t"] - time.time()), window)

    transport.loop.call_soon_threadsafe(window)
    done.wait(timeout=warmup_s + duration_s + 30)
    transport.stop()
    if thinned["count"]:
        rows.append(("thinned", time.time(), float(thinned["count"])))
    return rows


def run_skewed(protocol_name: str, config_raw: dict, *,
               point_fraction: float, num_clients: int,
               duration_s: float, seed: int = 0,
               warmup_s: float = 0.25, num_keys: int = 16) -> list:
    """Point-skewed KV write loops for the conflict-sensitivity sweep
    (vldb21_compartmentalized/compartmentalized_skew, craq_skew):
    ``point_fraction`` of writes hit ONE hot key, the rest uniform --
    the knob that changes EPaxos fast-path conflict rates and CRAQ
    chain contention. Commands are protocol-appropriate: CRAQ's native
    chain KV write; pickled SetRequests against a KeyValueStore for
    epaxos/multipaxos."""
    import pickle

    from frankenpaxos_tpu.statemachine import SetRequest

    protocol = get_protocol(protocol_name)
    config = protocol.load_config(config_raw)
    logger = FakeLogger(LogLevel.FATAL)
    transport = TcpTransport(("127.0.0.1", free_port()), logger)
    transport.start()
    ctx = DeployCtx(config=config, transport=transport, logger=logger,
                    overrides={}, seed=seed)
    client = protocol.make_client(ctx, transport.listen_address)
    rngs = [random.Random((seed << 20) + p) for p in range(num_clients)]
    tags = {"next": 0}

    def issue_op(i: int, finished) -> None:
        rng = rngs[i]
        key = ("point" if rng.random() < point_fraction
               else str(rng.randrange(num_keys)))
        tags["next"] += 1
        value = "v%d" % tags["next"]
        done = lambda *reply: finished(  # noqa: E731
            "giveup" if reply and reply[0] is RETRY_EXHAUSTED else "write")
        if protocol_name == "craq":
            client.write(i, key, value, done)
        elif protocol_name == "epaxos":
            client.propose(i, pickle.dumps(SetRequest(((key, value),))),
                           done)
        else:  # multipaxos
            client.write(i, pickle.dumps(SetRequest(((key, value),))),
                         done)

    return _closed_loops(transport, num_clients, duration_s, warmup_s,
                         issue_op)


def run_drive(protocol_name: str, config_raw: dict, *,
              num_clients: int, duration_s: float, seed: int = 0,
              warmup_s: float = 0.25,
              client_overrides: dict | None = None) -> list:
    """Protocol-agnostic closed loops: one client actor per loop (each
    on its own port via the transport's multi-bind), driven through the
    registry's ``drive`` entry -- works for every protocol the smoke
    deploys. Returns [("write", start_unix_s, latency_s)].

    ``client_overrides`` adds ``--options.*``-style client constructor
    overrides (e.g. ``{"coalesce_writes": "true"}`` for run-pipeline
    clients)."""
    protocol = get_protocol(protocol_name)
    config = protocol.load_config(config_raw)
    logger = FakeLogger(LogLevel.FATAL)
    transport = TcpTransport(("127.0.0.1", free_port()), logger)
    transport.start()
    clients = []
    for i in range(num_clients):
        ctx = DeployCtx(config=config, transport=transport, logger=logger,
                        overrides={"resend_period_s": "1.0",
                                   "repropose_period_s": "1.0",
                                   **(client_overrides or {})},
                        seed=(seed << 8) + i)
        address = (transport.listen_address if i == 0
                   else ("127.0.0.1", free_port()))
        clients.append(protocol.make_client(ctx, address))

    tags = {"next": 0}

    def issue_op(i: int, finished) -> None:
        tag = tags["next"]
        tags["next"] += 1
        protocol.drive(clients[i], tag, lambda *reply: finished(
            "giveup" if reply and reply[0] is RETRY_EXHAUSTED else "write"))

    return _closed_loops(transport, num_clients, duration_s, warmup_s,
                         issue_op)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--protocol", default="multipaxos")
    parser.add_argument("--config", required=True)
    parser.add_argument("--workload", default=None,
                        help="JSON workload spec (bench/workload.py)")
    parser.add_argument("--num_clients", type=int, default=1)
    parser.add_argument("--duration", type=float, required=True)
    parser.add_argument("--read_consistency", default="linearizable")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--client_options", default=None,
                        help="JSON dict of ClientOptions overrides "
                             "(e.g. {\"coalesce_writes\": \"true\"})")
    parser.add_argument("--point_skew", type=float, default=None,
                        help="point-skewed KV write loops with this "
                             "hot-key fraction (conflict sweep)")
    parser.add_argument("--open_loop", action="store_true",
                        help="OPEN-loop arrivals from the shared "
                             "OpenLoopWorkload (paxload): the "
                             "--workload spec must be "
                             '{"name": "open_loop", "rate": ...}')
    parser.add_argument("--num_sessions", type=int, default=1024,
                        help="open-loop pseudonym pool size")
    parser.add_argument("--out", required=True)
    args = parser.parse_args(argv)

    with open(args.config) as f:
        config_raw = json.load(f)

    if args.open_loop:
        from frankenpaxos_tpu.bench.workload import OpenLoopWorkload

        workload = (workload_from_dict(json.loads(args.workload))
                    if args.workload else OpenLoopWorkload())
        assert isinstance(workload, OpenLoopWorkload), \
            "--open_loop needs an open_loop workload spec"
        rows = run_open_loop(args.protocol, config_raw, workload,
                             num_sessions=args.num_sessions,
                             duration_s=args.duration,
                             read_consistency=args.read_consistency,
                             seed=args.seed,
                             overrides=(json.loads(args.client_options)
                                        if args.client_options
                                        else None))
    elif args.point_skew is not None:
        rows = run_skewed(args.protocol, config_raw,
                          point_fraction=args.point_skew,
                          num_clients=args.num_clients,
                          duration_s=args.duration, seed=args.seed)
    elif args.protocol != "multipaxos" and args.workload is None:
        # Generic closed loops via the registry's drive() -- any
        # protocol the smoke can deploy can be benchmarked.
        rows = run_drive(args.protocol, config_raw,
                         num_clients=args.num_clients,
                         duration_s=args.duration, seed=args.seed,
                         client_overrides=(json.loads(args.client_options)
                                           if args.client_options
                                           else None))
    else:
        workload = (workload_from_dict(json.loads(args.workload))
                    if args.workload
                    else WriteOnlyWorkload(StringWorkload(size_mean=8)))
        rows = run(args.protocol, config_raw, workload,
                   num_clients=args.num_clients,
                   duration_s=args.duration,
                   read_consistency=args.read_consistency,
                   seed=args.seed,
                   overrides=(json.loads(args.client_options)
                              if args.client_options else None))
    with open(args.out, "w") as f:
        f.write("kind,start_unix_s,latency_s\n")
        for kind, start, latency in rows:
            f.write(f"{kind},{start!r},{latency!r}\n")
    print(f"wrote {len(rows)} ops to {args.out}", flush=True)


if __name__ == "__main__":
    main()
