"""Coupled (SuperNode) vs compartmentalized baseline.

The reference's headline result (BASELINE.md, eurosys fig1/fig2):
compartmentalized MultiPaxos/Mencius beats the coupled all-roles-in-one-
process deployment ~4-8x because each decoupled stage gets its own
core. This benchmark runs both modes and reports the ratio.

Usage::

    python -m frankenpaxos_tpu.bench.coupled --duration 3 \
        --out bench_results/coupled_vs_compartmentalized.json
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile

from frankenpaxos_tpu.bench.harness import SuiteDirectory
from frankenpaxos_tpu.bench.multipaxos_suite import (
    MultiPaxosInput,
    run_benchmark,
)


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--client_procs", type=int, default=4)
    parser.add_argument("--num_clients", type=int, default=10)
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--suite_dir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_coupled_")
    suite = SuiteDirectory(root, "coupled_vs_compartmentalized")

    rows = {}
    for mode, supernode in (("compartmentalized", False), ("coupled", True)):
        stats = run_benchmark(
            suite.benchmark_directory(),
            MultiPaxosInput(num_clients=args.num_clients,
                            client_procs=args.client_procs,
                            duration_s=args.duration,
                            supernode=supernode))
        rows[mode] = {
            "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
            "latency_median_ms": stats.get("latency.median_ms"),
            "num_requests": stats["num_requests"],
        }
        print(json.dumps({mode: rows[mode]}))

    comp = rows["compartmentalized"]["throughput_p90_1s"]
    coup = rows["coupled"]["throughput_p90_1s"]
    ratio = comp / coup if comp and coup else None
    result = {
        "benchmark": "coupled_vs_compartmentalized",
        "host_cpus": os.cpu_count(),
        "note": ("the reference's 4-8x compartmentalization win comes "
                 "from giving each decoupled stage its own core; on a "
                 "single-core host both modes share one CPU, so the "
                 "ratio mostly reflects scheduling overhead, not the "
                 "architectural ceiling."),
        "client_procs": args.client_procs,
        "num_clients": args.num_clients,
        "duration_s": args.duration,
        "modes": rows,
        "compartmentalized_over_coupled": ratio,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
