"""Coupled (SuperNode) vs compartmentalized baseline.

The reference's headline result (BASELINE.md, eurosys fig1/fig2):
compartmentalized MultiPaxos/Mencius beats the coupled all-roles-in-one-
process deployment ~4-8x because each decoupled stage gets its own
core. This benchmark runs both modes and reports the ratio.

Usage::

    python -m frankenpaxos_tpu.bench.coupled --duration 3 \
        --out bench_results/coupled_vs_compartmentalized.json
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile

from frankenpaxos_tpu.bench.harness import SuiteDirectory
from frankenpaxos_tpu.bench.multipaxos_suite import (
    MultiPaxosInput,
    run_benchmark,
)


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--client_procs", type=int, default=4)
    parser.add_argument("--num_clients", type=int, default=10)
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--suite_dir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_coupled_")
    suite = SuiteDirectory(root, "coupled_vs_compartmentalized")

    rows = {}
    for mode, supernode in (("compartmentalized", False), ("coupled", True)):
        stats = run_benchmark(
            suite.benchmark_directory(),
            MultiPaxosInput(num_clients=args.num_clients,
                            client_procs=args.client_procs,
                            duration_s=args.duration,
                            supernode=supernode))
        rows[mode] = {
            "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
            "latency_median_ms": stats.get("latency.median_ms"),
            "num_requests": stats["num_requests"],
            "role_cpu_seconds": stats.get("role_cpu_seconds", {}),
        }
        print(json.dumps({mode: rows[mode]}))

    comp = rows["compartmentalized"]["throughput_p90_1s"]
    coup = rows["coupled"]["throughput_p90_1s"]
    ratio = comp / coup if comp and coup else None

    # Per-stage CPU accounting -> the projected decoupling win. On one
    # core the stages timeshare, so wall-clock cannot show the 4-8x;
    # but the measured per-role CPU split says exactly how much work
    # runs CONCURRENTLY once each stage owns a core: the pipeline's
    # wall time shrinks from sum(stage cpu) to max(stage cpu), i.e.
    # projected speedup = total / max (Amdahl on the stage graph,
    # DistributionScheme.scala:151-162's point).
    comp_cpu = rows["compartmentalized"]["role_cpu_seconds"]
    projection = None
    if comp_cpu:
        total = sum(comp_cpu.values())
        bottleneck_label = max(comp_cpu, key=comp_cpu.get)
        bottleneck = comp_cpu[bottleneck_label]
        if bottleneck > 0:
            projection = {
                "total_role_cpu_s": round(total, 3),
                "bottleneck_stage": bottleneck_label,
                "bottleneck_cpu_s": round(bottleneck, 3),
                "parallelizable_fraction": round(
                    1 - bottleneck / total, 3),
                "projected_stage_speedup": round(total / bottleneck, 2),
                "projected_compartmentalized_over_coupled": round(
                    (ratio or 1.0) * total / bottleneck, 2),
            }
            print(json.dumps({"projection": projection}))

    result = {
        "benchmark": "coupled_vs_compartmentalized",
        "host_cpus": os.cpu_count(),
        "note": ("the reference's 4-8x compartmentalization win comes "
                 "from giving each decoupled stage its own core; on a "
                 "single-core host both modes share one CPU, so the "
                 "measured ratio mostly reflects scheduling overhead. "
                 "role_cpu_seconds records each stage's actual CPU "
                 "time; `projection` derives what decoupling buys "
                 "once stages stop timesharing (wall time -> the "
                 "bottleneck stage alone)."),
        "client_procs": args.client_procs,
        "num_clients": args.num_clients,
        "duration_s": args.duration,
        "modes": rows,
        "compartmentalized_over_coupled": ratio,
        "projection": projection,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
