"""Paper-experiment sweep families + plots.

The reference commits parameterized sweeps and plot scripts per paper
(benchmarks/{eurosys,nsdi,vldb20_matchmaker,vldb21_compartmentalized,
vldb21_evelyn}/: fig1_multipaxos_lt_plot.py and friends). This is the
analog: named families sweep offered load over deployed clusters, write
tidy CSVs, and render the paper's latency-throughput figures with
matplotlib.

Families (reference analog in parens):

  * ``eurosys_fig1`` -- compartmentalized vs coupled MultiPaxos vs
    unreplicated LT curves (eurosys/fig1_multipaxos_lt_plot.py).
  * ``eurosys_fig2`` -- the same shape for Mencius
    (eurosys/fig2_mencius_lt_plot.py).
  * ``matchmaker_lt`` -- MatchmakerMultiPaxos LT (vldb20_matchmaker).
  * ``read_scale``   -- read throughput vs replica count at a
    read-heavy mix (vldb21_evelyn; wraps bench/read_scale.py's
    mechanism).
  * ``nsdi_fig1``    -- EPaxos vs MultiPaxos vs SimpleBPaxos LT
    (nsdi/fig1_lt_*_results.csv), the generalized-protocol half of
    the baseline table.
  * ``nsdi_fig2``    -- SimpleBPaxos vs coupled ("super") BPaxos
    ablation (nsdi/fig2_ablation_superbpaxos_results.csv,
    benchmarks/simplebpaxos/nsdi_fig2_ablation.py:1-112).

Usage::

    python -m frankenpaxos_tpu.bench.sweeps --family eurosys_fig1 \
        --out_dir bench_results/sweeps

NOTE: this host has one core, so absolute numbers mostly reflect
scheduling, not the architectural ceiling (see bench/coupled.py's
note); the sweeps exist so multi-core/multi-host runs have
infrastructure to inherit.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import tempfile

from frankenpaxos_tpu.bench.harness import SuiteDirectory

#: (client_procs, clients_per_proc) load points, smallest first.
DEFAULT_POINTS = ((1, 2), (2, 5), (4, 5))


def _add_stage_projection(row: dict, stats: dict) -> dict:
    """Attach role_cpu_s/bottleneck_stage/projected_stage_speedup to a
    sweep row (the ONE wiring of the shared projection helper; on this
    1-CPU host wall-clock cannot show decoupling wins, so every family
    carries the real-core projection instead)."""
    from frankenpaxos_tpu.bench.harness import BenchmarkDirectory

    row.update(BenchmarkDirectory.stage_projection(
        stats.get("role_cpu_seconds") or {}))
    return row


def _lt_row(series: str, procs: int, loops: int, stats: dict) -> dict:
    row = {
        "series": series,
        "num_client_procs": procs,
        "num_clients_per_proc": loops,
        "num_clients": procs * loops,
        "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
        "latency_median_ms": stats.get("latency.median_ms"),
        "num_requests": stats.get("num_requests"),
    }
    return _add_stage_projection(row, stats)


def _protocol_series(suite, series: str, protocol: str, points,
                     duration_s: float, supernode: bool = False) -> list:
    from frankenpaxos_tpu.bench.protocol_suite import (
        run_protocol_benchmark,
    )

    rows = []
    for procs, loops in points:
        # One retry per point: a role process occasionally loses the
        # startup race on a loaded single-core host; a lost point must
        # not abort the whole family.
        for attempt in (1, 2):
            try:
                stats = run_protocol_benchmark(
                    suite.benchmark_directory(), protocol,
                    client_procs=procs, clients_per_proc=loops,
                    duration_s=duration_s, supernode=supernode)
                rows.append(_lt_row(series, procs, loops, stats))
                break
            except RuntimeError as e:
                print(f"point ({series}, {procs}x{loops}) attempt "
                      f"{attempt} failed: {e}")
        else:
            rows.append(_lt_row(series, procs, loops, {}))
        print(json.dumps(rows[-1]))
    return rows


def eurosys_fig(protocol: str, suite: SuiteDirectory, points,
                duration_s: float) -> list:
    """Compartmentalized vs coupled vs unreplicated (fig1/fig2 shape)."""
    rows = []
    rows += _protocol_series(suite, protocol, protocol, points,
                             duration_s)
    rows += _protocol_series(suite, f"coupled_{protocol}", protocol,
                             points, duration_s, supernode=True)
    rows += _protocol_series(suite, "unreplicated", "unreplicated",
                             points, duration_s)
    return rows


def matchmaker_lt(suite: SuiteDirectory, points,
                  duration_s: float) -> list:
    return _protocol_series(suite, "matchmakermultipaxos",
                            "matchmakermultipaxos", points, duration_s)


def read_scale(suite: SuiteDirectory, points, duration_s: float) -> list:
    """Read throughput vs replica count at a 95% read mix (the Evelyn
    scaling claim: reads scale with replicas, writes don't pay). The
    sweep axis is the replica count; the offered load is the LARGEST
    of the requested load points (reads must saturate to show the
    scaling)."""
    from frankenpaxos_tpu.bench.multipaxos_suite import (
        MultiPaxosInput,
        run_benchmark,
    )
    from frankenpaxos_tpu.bench.workload import UniformReadWriteWorkload

    procs, loops = max(points, key=lambda p: p[0] * p[1])
    rows = []
    for num_replicas in (2, 3, 4):
        stats = run_benchmark(
            suite.benchmark_directory(),
            MultiPaxosInput(
                num_clients=loops, client_procs=procs,
                duration_s=duration_s,
                num_replicas=num_replicas,
                workload=UniformReadWriteWorkload(num_keys=16,
                                                  read_fraction=0.95),
                read_consistency="eventual", state_machine="KeyValueStore"))
        rows.append({
            "series": "eventual_reads",
            "num_client_procs": procs,
            "num_clients_per_proc": loops,
            "num_replicas": num_replicas,
            "read_throughput_p90_1s": stats.get(
                "read.start_throughput_1s.p90"),
            "write_throughput_p90_1s": stats.get(
                "write.start_throughput_1s.p90"),
            "latency_median_ms": stats.get("latency.median_ms"),
            "num_requests": stats.get("num_requests"),
        })
        print(json.dumps(rows[-1]))
    return rows


def nsdi_fig1(suite: SuiteDirectory, points, duration_s: float) -> list:
    """EPaxos vs MultiPaxos vs SimpleBPaxos latency-throughput (the
    NSDI'21 fig1 comparison)."""
    rows = []
    for protocol in ("epaxos", "multipaxos", "simplebpaxos"):
        rows += _protocol_series(suite, protocol, protocol, points,
                                 duration_s)
    return rows


def nsdi_fig2(suite: SuiteDirectory, points, duration_s: float) -> list:
    """SimpleBPaxos vs coupled ("super") BPaxos: the NSDI'21 fig2
    ablation -- all five roles colocated in one process vs
    compartmentalized."""
    rows = _protocol_series(suite, "simplebpaxos", "simplebpaxos",
                            points, duration_s)
    rows += _protocol_series(suite, "superbpaxos", "simplebpaxos",
                             points, duration_s, supernode=True)
    return rows


def eurosys_fig4(suite: SuiteDirectory, points,
                 duration_s: float) -> list:
    """The batching ablation (eurosys/fig4_multipaxos_ablation_plot.py,
    vldb21_compartmentalized/batched_ablation/): batch size as the
    swept axis -- including unbatched -- for compartmentalized and
    coupled MultiPaxos. The reference counts batching as a ~4x lever
    (BASELINE.md)."""
    from frankenpaxos_tpu.bench.multipaxos_suite import (
        MultiPaxosInput,
        run_benchmark,
    )

    # High offered load relative to the batch sizes: with 2 batchers a
    # size-B batch needs ~2B outstanding requests to fill without
    # waiting on the partial-flush timer, so the swept axis measures
    # BATCHING, not the timer. 40 closed loops cover up to B=10.
    procs, loops = (4, 10)
    rows = []
    for supernode in (False, True):
        series = "coupled" if supernode else "compartmentalized"
        for batch_size in (0, 2, 5, 10):
            for attempt in (1, 2):
                try:
                    stats = run_benchmark(
                        suite.benchmark_directory(),
                        MultiPaxosInput(
                            num_clients=loops, client_procs=procs,
                            duration_s=duration_s,
                            num_batchers=2 if batch_size else 0,
                            batch_size=batch_size or 1,
                            batch_flush_period_s=0.01,
                            supernode=supernode))
                    break
                except RuntimeError as e:
                    print(f"fig4 ({series}, {batch_size}) attempt "
                          f"{attempt} failed: {e}")
                    stats = {}
            row = {
                "series": series,
                "batch_size": batch_size,
                "num_clients": procs * loops,
                "throughput_p90_1s": stats.get(
                    "start_throughput_1s.p90"),
                "latency_median_ms": stats.get("latency.median_ms"),
                "num_requests": stats.get("num_requests"),
            }
            rows.append(_add_stage_projection(row, stats))
            print(json.dumps(rows[-1]))
    return rows


def evelyn(suite: SuiteDirectory, points, duration_s: float) -> list:
    """The vldb21_evelyn characteristic experiments: read throughput as
    a function of read FRACTION x replica count.

      * ``lt_surprise`` shape: at a fixed replica count, sweeping the
        read fraction shows write contention capping read scaling (the
        paper's surprise: 90% reads is NOT ~10x the write ceiling).
      * ``no_scale_fraction`` / ``scale_load`` shape: at each read
        fraction, adding replicas scales reads but not writes.
    """
    from frankenpaxos_tpu.bench.multipaxos_suite import (
        MultiPaxosInput,
        run_benchmark,
    )
    from frankenpaxos_tpu.bench.workload import UniformReadWriteWorkload

    procs, loops = max(points, key=lambda p: p[0] * p[1])
    rows = []
    for num_replicas in (2, 4):
        for read_fraction in (0.0, 0.5, 0.9, 1.0):
            for attempt in (1, 2):
                try:
                    stats = run_benchmark(
                        suite.benchmark_directory(),
                        MultiPaxosInput(
                            num_clients=loops, client_procs=procs,
                            duration_s=duration_s,
                            num_replicas=num_replicas,
                            workload=UniformReadWriteWorkload(
                                num_keys=16,
                                read_fraction=read_fraction),
                            read_consistency="eventual",
                            state_machine="KeyValueStore"))
                    break
                except RuntimeError as e:
                    print(f"evelyn ({num_replicas}, {read_fraction}) "
                          f"attempt {attempt} failed: {e}")
                    stats = {}
            row = {
                "series": f"replicas_{num_replicas}",
                "num_replicas": num_replicas,
                "read_fraction": read_fraction,
                "read_throughput_p90_1s": stats.get(
                    "read.start_throughput_1s.p90"),
                "write_throughput_p90_1s": stats.get(
                    "write.start_throughput_1s.p90"),
                "throughput_p90_1s": stats.get(
                    "start_throughput_1s.p90"),
                "latency_median_ms": stats.get("latency.median_ms"),
            }
            rows.append(_add_stage_projection(row, stats))
            print(json.dumps(rows[-1]))
    # Shape caveat IN the artifact: on this 1-CPU host more replica
    # processes timeshare one core, so replicas_4 can read SLOWER than
    # replicas_2 -- the opposite of the paper's scaling claim. The
    # stage_projection columns carry what real cores would do
    # (projected_stage_speedup once each stage owns a core).
    rows.append({
        "series": "note",
        "note": ("replicas_4 < replicas_2 inversions are 1-CPU "
                 "contention (all role processes share one core); "
                 "see role_cpu_s/bottleneck_stage/"
                 "projected_stage_speedup for the real-core "
                 "projection"),
    })
    return rows


def skew(suite: SuiteDirectory, points, duration_s: float) -> list:
    """Conflict-rate sensitivity (vldb21_compartmentalized/
    compartmentalized_skew/, craq_skew/): a PointSkewed read-write
    workload swept over the skew point mass, for the protocols whose
    behavior actually changes with conflicts (EPaxos fast-path
    conflicts, CRAQ chain contention) against conflict-insensitive
    MultiPaxos."""
    from frankenpaxos_tpu.bench.protocol_suite import (
        run_protocol_benchmark,
    )

    procs, loops = max(points, key=lambda p: p[0] * p[1])
    rows = []
    for protocol in ("multipaxos", "epaxos", "craq"):
        for point_fraction in (0.0, 0.5, 0.9):
            for attempt in (1, 2):
                try:
                    stats = run_protocol_benchmark(
                        suite.benchmark_directory(), protocol,
                        client_procs=procs, clients_per_proc=loops,
                        duration_s=duration_s,
                        point_skew=point_fraction)
                    break
                except RuntimeError as e:
                    print(f"skew ({protocol}, {point_fraction}) attempt "
                          f"{attempt} failed: {e}")
                    stats = {}
            row = {
                "series": protocol,
                "point_skew": point_fraction,
                "num_clients": procs * loops,
                "throughput_p90_1s": stats.get(
                    "start_throughput_1s.p90"),
                "latency_median_ms": stats.get("latency.median_ms"),
                "num_requests": stats.get("num_requests"),
            }
            rows.append(_add_stage_projection(row, stats))
            print(json.dumps(rows[-1]))
    return rows


def plot_param_sweep(rows: list, path: str, x_key: str, title: str,
                     y_keys=("throughput_p90_1s",)) -> None:
    """Generic swept-parameter figure: x = the swept axis, y =
    throughput (thousands/s), one line per series (the fig4/evelyn/
    skew plot shape)."""
    import matplotlib

    matplotlib.use("pdf")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(1, 1, figsize=(6.4, 4.8))
    markers = ("o-", "^-", "s-", "d-", "v-", "x-")
    i = 0
    rows = [r for r in rows if r["series"] != "note"]  # metadata rows
    for series in dict.fromkeys(row["series"] for row in rows):
        pts = sorted((r for r in rows if r["series"] == series),
                     key=lambda r: r.get(x_key, 0))
        for y_key in y_keys:
            label = series if len(y_keys) == 1 else \
                f"{series}:{y_key.split('_')[0]}"
            ax.plot([r.get(x_key, 0) for r in pts],
                    [(r.get(y_key) or 0) / 1000 for r in pts],
                    markers[i % len(markers)], label=label, linewidth=2)
            i += 1
    ax.set_xlabel(x_key)
    ax.set_ylabel("Throughput (thousands of commands per second)")
    ax.set_title(title)
    ax.legend(loc="best")
    ax.grid()
    fig.savefig(path, bbox_inches="tight")


def vldb20_reconfig(suite: SuiteDirectory, points,
                    duration_s: float) -> list:
    """Throughput THROUGH live reconfigurations -- the vldb20 matchmaker
    paper's headline capability (benchmarks/vldb20_matchmaker/
    leader_reconfiguration/, matchmaker_reconfiguration/;
    Reconfigurer.scala:98-155): drive steady closed-loop load, trigger
    reconfigurations at fixed timestamps, and record a 1-second
    throughput timeline showing the dip and recovery.

      * matchmakermultipaxos: an ACCEPTOR-set change (Reconfigure to
        the deployed reconfigurer, which hands every leader a new
        quorum system to matchmake into its next round) -- the paper's
        core experiment.
      * horizontal: a chunk reconfiguration (Reconfigure chosen INTO
        the log, starting a new active chunk).
      * multipaxos (the paxepoch arm, reconfig/): LIVE member swaps --
        each non-kill event launches a fresh replacement acceptor
        process and drives the leader's epoch-change flow
        (EpochCommit -> durable old-quorum acks -> watermark-bounded
        handover).
      * PLUS one process-failure event per protocol: the chaos driver
        SIGKILLs an acceptor mid-run (no relaunch), THEN the
        protocol's repair path runs: the matchmaker reconfigures to a
        quorum system over the survivors, and the paxepoch arm
        reconfigures the dead member out for a replacement -- so the
        kill rows carry MEASURED recovery_seconds where PR 3's study
        could only report a does-not-recover lower bound.

    Every event gets a generous post-event window so its
    ``recovery_seconds`` is measured, not truncated by the end of the
    run (VERDICT r5 item 6).
    """
    import sys
    import threading
    import time as _time

    from frankenpaxos_tpu.bench.chaos import sigkill_role
    from frankenpaxos_tpu.bench.deploy_suite import (
        launch_roles,
        role_process_env,
    )
    from frankenpaxos_tpu.bench.harness import LocalHost, free_port
    from frankenpaxos_tpu.deploy import get_protocol
    from frankenpaxos_tpu.quorums import SimpleMajority
    from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
    from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER
    from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport

    total_s = max(32.0, duration_s)
    # 4 events, ~6s of recovery window each (the last before a 7s
    # tail): 3 reconfigurations + the kill.
    reconfig_at = [total_s * 0.25, total_s * 0.42, total_s * 0.60,
                   total_s * 0.78]
    KILL_EVENT = len(reconfig_at) - 1  # the 4th event is the SIGKILL

    def trigger_messages(protocol_name, config, k):
        if protocol_name == "multipaxos":
            # paxepoch (reconfig/): non-kill events swap one acceptor
            # for a fresh replacement process through the epoch-change
            # flow -- handled inline in fire_reconfigs (they must
            # launch processes, not just send a message).
            return []
        if protocol_name == "matchmakermultipaxos":
            from frankenpaxos_tpu.protocols.matchmakermultipaxos import (
                Reconfigure,
                ReconfigureMatchmakers,
                initial_matchmaker_configuration,
            )
            from frankenpaxos_tpu.quorums import quorum_system_to_dict

            if k == 1:
                # The heavier MATCHMAKER-set change: the full Stop ->
                # Bootstrap -> MatchPhase1/2 -> MatchChosen epoch
                # migration under load (Reconfigurer.scala:283-720).
                # Epoch 0 is the live epoch for the first such change.
                return [(tuple(config.reconfigurer_addresses[0]),
                         ReconfigureMatchmakers(
                             matchmaker_configuration=(
                                 initial_matchmaker_configuration(
                                     config.f)),
                             new_matchmaker_indices=tuple(range(
                                 2 * config.f + 1))))]
            qs = quorum_system_to_dict(SimpleMajority(
                range(len(config.acceptor_addresses))))
            return [(tuple(config.reconfigurer_addresses[0]),
                     Reconfigure(qs))]
        from frankenpaxos_tpu.protocols.horizontal import Reconfigure
        from frankenpaxos_tpu.quorums import quorum_system_to_dict

        qs = quorum_system_to_dict(SimpleMajority(
            range(len(config.acceptor_addresses))))
        return [(tuple(addr), Reconfigure(qs))
                for addr in config.leader_addresses]

    rows = []
    procs_n, loops = max(points, key=lambda p: p[0] * p[1])
    # "multipaxos" is the paxepoch arm (reconfig/): the same
    # kill_acceptor chaos event, REPAIRED live -- reconfigure the dead
    # member out and a fresh replacement process in -- so its
    # recovery_seconds is a measured number where the epoch-frozen
    # stack could only report a lower bound (PR 3's finding).
    for protocol_name in ("matchmakermultipaxos", "horizontal",
                          "multipaxos"):
        bench = suite.benchmark_directory()
        protocol = get_protocol(protocol_name)
        raw = protocol.cluster(1, lambda: ["127.0.0.1", free_port()])
        config_path = bench.write_json("config.json", raw)
        config = protocol.load_config(raw)
        overrides = {"resend_phase1as_period_s": "0.5"}
        if protocol_name == "multipaxos":
            # Prompt watermark gossip + hole recovery keep the
            # handover windows tight (docs/RECONFIG.md).
            overrides.update({
                "send_chosen_watermark_every_n_entries": "1",
                "recover_log_entry_min_period_s": "0.5",
                "recover_log_entry_max_period_s": "1.0"})
        launch_roles(bench, protocol_name, config_path, config,
                     state_machine="AppendLog", overrides=overrides)
        host = LocalHost()
        env = role_process_env()
        client_procs = []
        t_start = _time.time()
        for i in range(procs_n):
            out_csv = bench.abspath(f"client_{i}_data.csv")
            client_procs.append((out_csv, bench.popen(
                host, f"client_{i}", [
                    sys.executable, "-m",
                    "frankenpaxos_tpu.bench.client_main",
                    "--protocol", protocol_name,
                    "--config", config_path,
                    "--num_clients", str(loops),
                    "--duration", str(total_s),
                    "--seed", str(i + 1), "--out", out_csv], env=env)))

        fired: list[float] = []
        # paxepoch arm state: the live member labels + rewritten raw.
        epoch_state = {"raw": raw,
                       "labels": ["acceptor_0", "acceptor_1",
                                  "acceptor_2"]}

        def fire_epoch_swap(transport, member: int) -> None:
            """One paxepoch event: a fresh replacement process for
            group-0 member ``member`` + the leader-driven change."""
            from frankenpaxos_tpu.bench.chaos import (
                launch_replacement_acceptor,
                reconfigure_acceptors,
            )

            members, label = launch_replacement_acceptor(
                bench, epoch_state["raw"], group=0, member=member,
                state_machine="AppendLog", overrides=overrides)
            new_raw = dict(epoch_state["raw"])
            new_raw["acceptors"] = [[list(a) for a in members]]
            epoch_state["raw"] = new_raw
            epoch_state["labels"][member] = label
            reconfigure_acceptors(transport,
                                  config.leader_addresses, members)

        def fire_reconfigs():
            logger = FakeLogger(LogLevel.FATAL)
            transport = TcpTransport(("127.0.0.1", free_port()), logger)
            transport.start()
            try:
                for k, at in enumerate(reconfig_at):
                    _time.sleep(max(0.0, t_start + at - _time.time()))
                    if k == KILL_EVENT:
                        if protocol_name == "multipaxos":
                            # Kill a CURRENT member, then repair live:
                            # reconfigure it out, replacement in.
                            sigkill_role(bench,
                                         epoch_state["labels"][2])
                            fire_epoch_swap(transport, member=2)
                        else:
                            # The chaos event: kill -9 the last
                            # acceptor mid-run (the WAL chaos driver's
                            # kill schedule applied to this bench) --
                            # then the protocol's own repair: the
                            # matchmaker reconfigures to a quorum
                            # system over the SURVIVORS (the paper's
                            # acceptor-replacement flow), turning PR
                            # 3's does-not-recover lower bound into a
                            # measured recovery.
                            acceptors = sorted(
                                label for label in bench.labeled_procs
                                if label.startswith("acceptor_"))
                            sigkill_role(bench, acceptors[-1])
                            if protocol_name == "matchmakermultipaxos":
                                from frankenpaxos_tpu.protocols \
                                    .matchmakermultipaxos import (
                                        Reconfigure as MMPReconfigure,
                                    )
                                from frankenpaxos_tpu.quorums import (
                                    quorum_system_to_dict,
                                )

                                survivors = range(
                                    len(config.acceptor_addresses) - 1)
                                transport.send(
                                    transport.listen_address,
                                    tuple(config
                                          .reconfigurer_addresses[0]),
                                    DEFAULT_SERIALIZER.to_bytes(
                                        MMPReconfigure(
                                            quorum_system_to_dict(
                                                SimpleMajority(
                                                    survivors)))))
                    elif protocol_name == "multipaxos":
                        # Non-kill paxepoch events: live member swaps
                        # under load (alternate members 0 and 1; 2
                        # stays for the kill event).
                        fire_epoch_swap(transport, member=k % 2)
                    else:
                        for dst, message in trigger_messages(
                                protocol_name, config, k):
                            transport.send(
                                transport.listen_address, dst,
                                DEFAULT_SERIALIZER.to_bytes(message))
                    fired.append(_time.time())
                _time.sleep(0.5)  # let the last frame flush
            finally:
                transport.stop()

        trigger = threading.Thread(target=fire_reconfigs, daemon=True)
        trigger.start()
        starts = []
        failed = None
        try:
            for out_csv, proc in client_procs:
                code = proc.wait(timeout=total_s + 90)
                if code != 0:
                    failed = f"client exited {code}; see {bench.path}"
                    break
                with open(out_csv) as f:
                    next(f)
                    for line in f:
                        _, start, _lat = line.strip().split(",")
                        starts.append(float(start))
        finally:
            trigger.join(timeout=total_s + 10)
            bench.cleanup()
        if failed:
            print(json.dumps({"series": protocol_name, "error": failed}))
            continue

        # 1-second buckets from the first recorded op.
        t0 = min(starts) if starts else t_start
        buckets: dict[int, int] = {}
        for s in starts:
            buckets[int(s - t0)] = buckets.get(int(s - t0), 0) + 1
        reconfig_seconds = [int(f - t0) for f in fired]
        for second in range(int(total_s)):
            rows.append({
                "series": protocol_name,
                "second": second,
                "throughput": buckets.get(second, 0),
                "reconfig": second in reconfig_seconds,
            })
        # Dip/recovery summary: steady = median of pre-reconfig seconds.
        import statistics as _st

        pre = [buckets.get(s, 0) for s in range(1, reconfig_seconds[0])] \
            if reconfig_seconds else []
        steady = _st.median(pre) if pre else 0
        for k, rs in enumerate(reconfig_seconds):
            window_end = (reconfig_seconds[k + 1]
                          if k + 1 < len(reconfig_seconds)
                          else int(total_s))
            window = [buckets.get(s, 0)
                      for s in range(rs, min(rs + 3, int(total_s)))]
            dip = min(window) if window else 0
            # Recovery bounded by the event's own window (the next
            # event or end of run): every event gets a measured value
            # -- if throughput never returns to 80% of steady within
            # its window, report the window length as the honest
            # lower bound instead of an empty cell.
            recovery = next(
                (s - rs for s in range(rs, window_end)
                 if buckets.get(s, 0) >= 0.8 * steady), None)
            rows.append({
                "series": f"{protocol_name}_summary",
                "second": rs,
                "reconfig_index": k,
                "event": ("kill_acceptor" if k == KILL_EVENT
                          else "reconfigure"),
                "steady_cmds_per_sec": steady,
                "dip_cmds_per_sec": dip,
                "recovery_seconds": (recovery if recovery is not None
                                     else window_end - rs),
                "recovery_is_lower_bound": recovery is None,
            })
        print(json.dumps([r for r in rows
                          if r["series"] == f"{protocol_name}_summary"]))
    return rows


def plot_reconfig_timeline(rows: list, path: str) -> None:
    """Throughput vs time with reconfiguration instants marked (the
    vldb20 leader_reconfiguration figure shape)."""
    import matplotlib

    matplotlib.use("pdf")
    import matplotlib.pyplot as plt

    series = [s for s in dict.fromkeys(r["series"] for r in rows)
              if not s.endswith("_summary")]
    if not series:
        return  # every protocol's clients failed; nothing to plot
    fig, axes = plt.subplots(len(series), 1, figsize=(6.4, 3.2 * len(series)),
                             squeeze=False)
    for ax, name in zip(axes[:, 0], series):
        pts = [r for r in rows if r["series"] == name]
        ax.plot([r["second"] for r in pts],
                [r["throughput"] for r in pts], "o-", linewidth=2,
                markersize=3)
        for r in pts:
            if r.get("reconfig"):
                ax.axvline(r["second"], color="red", linestyle="--",
                           linewidth=1)
        ax.set_ylabel("cmds/s (1s buckets)")
        ax.set_title(f"{name}: throughput through reconfigurations")
        ax.grid()
    axes[-1, 0].set_xlabel("Seconds")
    fig.savefig(path, bbox_inches="tight")


FAMILIES = {
    "eurosys_fig1": lambda suite, points, d: eurosys_fig(
        "multipaxos", suite, points, d),
    "eurosys_fig2": lambda suite, points, d: eurosys_fig(
        "mencius", suite, points, d),
    "matchmaker_lt": matchmaker_lt,
    "read_scale": read_scale,
    "nsdi_fig1": nsdi_fig1,
    "nsdi_fig2": nsdi_fig2,
    "vldb20_reconfig": vldb20_reconfig,
    "eurosys_fig4": eurosys_fig4,
    "evelyn": evelyn,
    "skew": skew,
}


def write_csv(rows: list, path: str) -> None:
    fields = sorted({key for row in rows for key in row},
                    key=lambda k: (k != "series", k))
    with open(path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=fields)
        writer.writeheader()
        writer.writerows(rows)


def plot_lt(rows: list, path: str, title: str) -> None:
    """Reference plot shape (fig1_multipaxos_lt_plot.py:22-49):
    throughput (thousands cmds/s) on x, median latency (ms) on y, one
    line per series."""
    import matplotlib

    matplotlib.use("pdf")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(1, 1, figsize=(6.4, 4.8))
    markers = {series: marker for series, marker in zip(
        dict.fromkeys(row["series"] for row in rows),
        ("o-", "^-", "s-", "d-", "v-"))}
    for series in dict.fromkeys(row["series"] for row in rows):
        pts = sorted((row for row in rows if row["series"] == series),
                     key=lambda row: row.get("num_clients", 0))
        xs = [(row.get("throughput_p90_1s") or 0) / 1000 for row in pts]
        ys = [row.get("latency_median_ms") or 0 for row in pts]
        ax.plot(xs, ys, markers[series], label=series, linewidth=2)
    ax.set_xlabel("Throughput (thousands of commands per second)")
    ax.set_ylabel("Median latency (ms)")
    ax.set_title(title)
    ax.legend(loc="best")
    ax.grid()
    fig.savefig(path, bbox_inches="tight")


def plot_read_scale(rows: list, path: str) -> None:
    import matplotlib

    matplotlib.use("pdf")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(1, 1, figsize=(6.4, 4.8))
    xs = [row["num_replicas"] for row in rows]
    ax.plot(xs, [(row["read_throughput_p90_1s"] or 0) / 1000
                 for row in rows], "o-", label="reads", linewidth=2)
    ax.plot(xs, [(row["write_throughput_p90_1s"] or 0) / 1000
                 for row in rows], "^-", label="writes", linewidth=2)
    ax.set_xlabel("Number of replicas")
    ax.set_ylabel("Throughput (thousands of commands per second)")
    ax.set_title("read scaling (vldb21_evelyn shape)")
    ax.legend(loc="best")
    ax.grid()
    fig.savefig(path, bbox_inches="tight")


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--family", default="all",
                        choices=["all", *FAMILIES])
    parser.add_argument("--points", type=str, default=None,
                        help="comma-separated procsxloops load points")
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--out_dir", default="bench_results/sweeps")
    parser.add_argument("--suite_dir", default=None)
    args = parser.parse_args(argv)

    points = DEFAULT_POINTS
    if args.points:
        points = tuple(tuple(int(x) for x in part.split("x"))
                       for part in args.points.split(","))
    os.makedirs(args.out_dir, exist_ok=True)
    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_sweeps_")
    names = list(FAMILIES) if args.family == "all" else [args.family]

    out = {}
    for name in names:
        suite = SuiteDirectory(root, name)
        rows = FAMILIES[name](suite, points, args.duration)
        csv_path = os.path.join(args.out_dir, f"{name}.csv")
        pdf_path = os.path.join(args.out_dir, f"{name}.pdf")
        write_csv(rows, csv_path)
        if name == "read_scale":
            plot_read_scale(rows, pdf_path)
        elif name == "vldb20_reconfig":
            plot_reconfig_timeline(rows, pdf_path)
        elif name == "eurosys_fig4":
            plot_param_sweep(rows, pdf_path, "batch_size",
                             "batching ablation (eurosys fig4 shape)")
        elif name == "evelyn":
            plot_param_sweep(
                rows, pdf_path, "read_fraction",
                "read fraction x replicas (vldb21_evelyn shapes)",
                y_keys=("read_throughput_p90_1s",
                        "write_throughput_p90_1s"))
        elif name == "skew":
            plot_param_sweep(rows, pdf_path, "point_skew",
                             "conflict-rate sensitivity (skew sweeps)")
        else:
            plot_lt(rows, pdf_path, name)
        out[name] = {"rows": len(rows), "csv": csv_path,
                     "plot": pdf_path}
        print(json.dumps({name: out[name]}))
    return out


if __name__ == "__main__":
    main()
