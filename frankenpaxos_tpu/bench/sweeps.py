"""Paper-experiment sweep families + plots.

The reference commits parameterized sweeps and plot scripts per paper
(benchmarks/{eurosys,nsdi,vldb20_matchmaker,vldb21_compartmentalized,
vldb21_evelyn}/: fig1_multipaxos_lt_plot.py and friends). This is the
analog: named families sweep offered load over deployed clusters, write
tidy CSVs, and render the paper's latency-throughput figures with
matplotlib.

Families (reference analog in parens):

  * ``eurosys_fig1`` -- compartmentalized vs coupled MultiPaxos vs
    unreplicated LT curves (eurosys/fig1_multipaxos_lt_plot.py).
  * ``eurosys_fig2`` -- the same shape for Mencius
    (eurosys/fig2_mencius_lt_plot.py).
  * ``matchmaker_lt`` -- MatchmakerMultiPaxos LT (vldb20_matchmaker).
  * ``read_scale``   -- read throughput vs replica count at a
    read-heavy mix (vldb21_evelyn; wraps bench/read_scale.py's
    mechanism).
  * ``nsdi_fig1``    -- EPaxos vs MultiPaxos vs SimpleBPaxos LT
    (nsdi/fig1_lt_*_results.csv), the generalized-protocol half of
    the baseline table.
  * ``nsdi_fig2``    -- SimpleBPaxos vs coupled ("super") BPaxos
    ablation (nsdi/fig2_ablation_superbpaxos_results.csv,
    benchmarks/simplebpaxos/nsdi_fig2_ablation.py:1-112).

Usage::

    python -m frankenpaxos_tpu.bench.sweeps --family eurosys_fig1 \
        --out_dir bench_results/sweeps

NOTE: this host has one core, so absolute numbers mostly reflect
scheduling, not the architectural ceiling (see bench/coupled.py's
note); the sweeps exist so multi-core/multi-host runs have
infrastructure to inherit.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import tempfile

from frankenpaxos_tpu.bench.harness import SuiteDirectory

#: (client_procs, clients_per_proc) load points, smallest first.
DEFAULT_POINTS = ((1, 2), (2, 5), (4, 5))


def _lt_row(series: str, procs: int, loops: int, stats: dict) -> dict:
    return {
        "series": series,
        "num_client_procs": procs,
        "num_clients_per_proc": loops,
        "num_clients": procs * loops,
        "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
        "latency_median_ms": stats.get("latency.median_ms"),
        "num_requests": stats.get("num_requests"),
    }


def _protocol_series(suite, series: str, protocol: str, points,
                     duration_s: float, supernode: bool = False) -> list:
    from frankenpaxos_tpu.bench.protocol_suite import (
        run_protocol_benchmark,
    )

    rows = []
    for procs, loops in points:
        # One retry per point: a role process occasionally loses the
        # startup race on a loaded single-core host; a lost point must
        # not abort the whole family.
        for attempt in (1, 2):
            try:
                stats = run_protocol_benchmark(
                    suite.benchmark_directory(), protocol,
                    client_procs=procs, clients_per_proc=loops,
                    duration_s=duration_s, supernode=supernode)
                rows.append(_lt_row(series, procs, loops, stats))
                break
            except RuntimeError as e:
                print(f"point ({series}, {procs}x{loops}) attempt "
                      f"{attempt} failed: {e}")
        else:
            rows.append(_lt_row(series, procs, loops, {}))
        print(json.dumps(rows[-1]))
    return rows


def eurosys_fig(protocol: str, suite: SuiteDirectory, points,
                duration_s: float) -> list:
    """Compartmentalized vs coupled vs unreplicated (fig1/fig2 shape)."""
    rows = []
    rows += _protocol_series(suite, protocol, protocol, points,
                             duration_s)
    rows += _protocol_series(suite, f"coupled_{protocol}", protocol,
                             points, duration_s, supernode=True)
    rows += _protocol_series(suite, "unreplicated", "unreplicated",
                             points, duration_s)
    return rows


def matchmaker_lt(suite: SuiteDirectory, points,
                  duration_s: float) -> list:
    return _protocol_series(suite, "matchmakermultipaxos",
                            "matchmakermultipaxos", points, duration_s)


def read_scale(suite: SuiteDirectory, points, duration_s: float) -> list:
    """Read throughput vs replica count at a 95% read mix (the Evelyn
    scaling claim: reads scale with replicas, writes don't pay). The
    sweep axis is the replica count; the offered load is the LARGEST
    of the requested load points (reads must saturate to show the
    scaling)."""
    from frankenpaxos_tpu.bench.multipaxos_suite import (
        MultiPaxosInput,
        run_benchmark,
    )
    from frankenpaxos_tpu.bench.workload import UniformReadWriteWorkload

    procs, loops = max(points, key=lambda p: p[0] * p[1])
    rows = []
    for num_replicas in (2, 3, 4):
        stats = run_benchmark(
            suite.benchmark_directory(),
            MultiPaxosInput(
                num_clients=loops, client_procs=procs,
                duration_s=duration_s,
                num_replicas=num_replicas,
                workload=UniformReadWriteWorkload(num_keys=16,
                                                  read_fraction=0.95),
                read_consistency="eventual", state_machine="KeyValueStore"))
        rows.append({
            "series": "eventual_reads",
            "num_client_procs": procs,
            "num_clients_per_proc": loops,
            "num_replicas": num_replicas,
            "read_throughput_p90_1s": stats.get(
                "read.start_throughput_1s.p90"),
            "write_throughput_p90_1s": stats.get(
                "write.start_throughput_1s.p90"),
            "latency_median_ms": stats.get("latency.median_ms"),
            "num_requests": stats.get("num_requests"),
        })
        print(json.dumps(rows[-1]))
    return rows


def nsdi_fig1(suite: SuiteDirectory, points, duration_s: float) -> list:
    """EPaxos vs MultiPaxos vs SimpleBPaxos latency-throughput (the
    NSDI'21 fig1 comparison)."""
    rows = []
    for protocol in ("epaxos", "multipaxos", "simplebpaxos"):
        rows += _protocol_series(suite, protocol, protocol, points,
                                 duration_s)
    return rows


def nsdi_fig2(suite: SuiteDirectory, points, duration_s: float) -> list:
    """SimpleBPaxos vs coupled ("super") BPaxos: the NSDI'21 fig2
    ablation -- all five roles colocated in one process vs
    compartmentalized."""
    rows = _protocol_series(suite, "simplebpaxos", "simplebpaxos",
                            points, duration_s)
    rows += _protocol_series(suite, "superbpaxos", "simplebpaxos",
                             points, duration_s, supernode=True)
    return rows


FAMILIES = {
    "eurosys_fig1": lambda suite, points, d: eurosys_fig(
        "multipaxos", suite, points, d),
    "eurosys_fig2": lambda suite, points, d: eurosys_fig(
        "mencius", suite, points, d),
    "matchmaker_lt": matchmaker_lt,
    "read_scale": read_scale,
    "nsdi_fig1": nsdi_fig1,
    "nsdi_fig2": nsdi_fig2,
}


def write_csv(rows: list, path: str) -> None:
    fields = sorted({key for row in rows for key in row},
                    key=lambda k: (k != "series", k))
    with open(path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=fields)
        writer.writeheader()
        writer.writerows(rows)


def plot_lt(rows: list, path: str, title: str) -> None:
    """Reference plot shape (fig1_multipaxos_lt_plot.py:22-49):
    throughput (thousands cmds/s) on x, median latency (ms) on y, one
    line per series."""
    import matplotlib

    matplotlib.use("pdf")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(1, 1, figsize=(6.4, 4.8))
    markers = {series: marker for series, marker in zip(
        dict.fromkeys(row["series"] for row in rows),
        ("o-", "^-", "s-", "d-", "v-"))}
    for series in dict.fromkeys(row["series"] for row in rows):
        pts = sorted((row for row in rows if row["series"] == series),
                     key=lambda row: row.get("num_clients", 0))
        xs = [(row.get("throughput_p90_1s") or 0) / 1000 for row in pts]
        ys = [row.get("latency_median_ms") or 0 for row in pts]
        ax.plot(xs, ys, markers[series], label=series, linewidth=2)
    ax.set_xlabel("Throughput (thousands of commands per second)")
    ax.set_ylabel("Median latency (ms)")
    ax.set_title(title)
    ax.legend(loc="best")
    ax.grid()
    fig.savefig(path, bbox_inches="tight")


def plot_read_scale(rows: list, path: str) -> None:
    import matplotlib

    matplotlib.use("pdf")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(1, 1, figsize=(6.4, 4.8))
    xs = [row["num_replicas"] for row in rows]
    ax.plot(xs, [(row["read_throughput_p90_1s"] or 0) / 1000
                 for row in rows], "o-", label="reads", linewidth=2)
    ax.plot(xs, [(row["write_throughput_p90_1s"] or 0) / 1000
                 for row in rows], "^-", label="writes", linewidth=2)
    ax.set_xlabel("Number of replicas")
    ax.set_ylabel("Throughput (thousands of commands per second)")
    ax.set_title("read scaling (vldb21_evelyn shape)")
    ax.legend(loc="best")
    ax.grid()
    fig.savefig(path, bbox_inches="tight")


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--family", default="all",
                        choices=["all", *FAMILIES])
    parser.add_argument("--points", type=str, default=None,
                        help="comma-separated procsxloops load points")
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--out_dir", default="bench_results/sweeps")
    parser.add_argument("--suite_dir", default=None)
    args = parser.parse_args(argv)

    points = DEFAULT_POINTS
    if args.points:
        points = tuple(tuple(int(x) for x in part.split("x"))
                       for part in args.points.split(","))
    os.makedirs(args.out_dir, exist_ok=True)
    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_sweeps_")
    names = list(FAMILIES) if args.family == "all" else [args.family]

    out = {}
    for name in names:
        suite = SuiteDirectory(root, name)
        rows = FAMILIES[name](suite, points, args.duration)
        csv_path = os.path.join(args.out_dir, f"{name}.csv")
        pdf_path = os.path.join(args.out_dir, f"{name}.pdf")
        write_csv(rows, csv_path)
        if name == "read_scale":
            plot_read_scale(rows, pdf_path)
        else:
            plot_lt(rows, pdf_path, name)
        out[name] = {"rows": len(rows), "csv": csv_path,
                     "plot": pdf_path}
        print(json.dumps({name: out[name]}))
    return out


if __name__ == "__main__":
    main()
