"""VERBATIM pre-paxpulse pipeline core, pinned as the overhead baseline.

This module is a frozen copy of the ``bench/pipeline.py`` hot path as it
stood the commit BEFORE the paxpulse telemetry plane landed (PR 19). It
exists for exactly one purpose: the paired overhead A/B in
``bench/telemetry_overhead.py`` gates the telemetry-OFF arm of the live
pipeline against this copy at the <3% noise floor, proving that carrying
an optional (``None``-when-disabled) ``telemetry`` leaf in
``PipelineState`` compiles out completely. The same pinning idiom as
``runtime/sim_legacy.py``: the baseline arm must be immune to later
edits of the live module, or the gate silently measures nothing.

Do NOT edit the function bodies here; they are the measurement. If the
live pipeline's semantics intentionally change, re-pin a fresh copy and
say so in the bench artifact's methodology string.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class PipelineState(NamedTuple):
    votes: jax.Array      # [n, window] uint8
    chosen: jax.Array     # [window] bool
    commands: jax.Array   # [window] int32 proposed command ids
    results: jax.Array    # [window] int32 state-machine outputs
    sm_state: jax.Array   # [] int32: the replica's running register
    committed: jax.Array  # [] int32 committed commands
    exec_wm: jax.Array    # [] int32 executed watermark (global slots)


def make_state(window: int, num_acceptors: int) -> PipelineState:
    return PipelineState(
        votes=jnp.zeros((num_acceptors, window), jnp.uint8),
        chosen=jnp.zeros((window,), jnp.bool_),
        commands=jnp.zeros((window,), jnp.int32),
        results=jnp.zeros((window,), jnp.int32),
        sm_state=jnp.int32(0),
        committed=jnp.int32(0),
        exec_wm=jnp.int32(0),
    )


def _arrivals(i: jax.Array, lanes: jax.Array, accs: jax.Array,
              salt: int) -> jax.Array:
    """Deterministic pseudo-random [len(accs), len(lanes)] uint8 arrival
    mask, keyed by logical (block-lane, global-acceptor) coordinates so
    every mesh sharding generates the same votes for the same slot."""
    h = (lanes[None, :] * 1103515245 + accs[:, None] * 12820163
         + (i + salt) * 22695477) >> 7
    return ((h & 7) < 7).astype(jnp.uint8)  # ~87.5% arrive this drain


def _psum(x, axis: Optional[str]):
    return x if axis is None else jax.lax.psum(x, axis)


def _axis_index(axis: Optional[str]) -> jax.Array:
    return jnp.int32(0) if axis is None else jax.lax.axis_index(axis)


def local_block(block_size: int, slot_shards: int) -> tuple:
    """``(b_local, pad)``: the per-shard lane count (the global block
    rounded UP over the slot shards) and the number of pad lanes the
    rounding adds to the padded global block."""
    b_local = -(-block_size // slot_shards)
    return b_local, b_local * slot_shards - block_size


def steady_state_step(state: PipelineState, i: jax.Array, *,
                      block_size: int, masks: np.ndarray,
                      thresholds, combine_any: bool,
                      group_axis: Optional[str] = None,
                      slot_axis: Optional[str] = None,
                      group_shards: int = 1,
                      slot_shards: int = 1) -> PipelineState:
    """One event-loop drain: new proposals + straggler completion
    (the pinned pre-paxpulse body; see the live module for docs)."""
    n_local, w_local = state.votes.shape
    b_local, block_pad = local_block(block_size, slot_shards)
    assert w_local % b_local == 0, (
        f"local window {w_local} must hold whole {b_local}-slot blocks")
    masks_d = jnp.asarray(masks, dtype=jnp.int32)          # [G, n_global]
    thresholds_d = jnp.asarray(np.asarray(thresholds, dtype=np.int32))
    assert thresholds_d.shape == (masks_d.shape[0],), (
        f"{thresholds_d.shape} thresholds for {masks_d.shape[0]} mask "
        f"groups")
    assert masks_d.shape[1] == group_shards * n_local, (
        f"masks cover {masks_d.shape[1]} acceptors but the mesh holds "
        f"{group_shards} x {n_local}")
    num_blocks = w_local // b_local
    start_new = (i % num_blocks) * b_local
    start_old = ((i - 1) % num_blocks) * b_local

    from frankenpaxos_tpu.ops.quorum import _fused_grid_hit, grid_layout

    grid = grid_layout(masks, thresholds, combine_any)
    if grid is not None and group_axis is not None \
            and (grid[3] is not None or n_local % grid[2] != 0):
        grid = None

    if slot_axis is None:
        lanes_new = jnp.arange(b_local, dtype=jnp.int32)
    else:
        lanes_new = (_axis_index(slot_axis) * b_local
                     + jnp.arange(b_local, dtype=jnp.int32))
    if group_axis is None:
        accs = jnp.arange(n_local, dtype=jnp.int32)
        masks_local = masks_d
    else:
        group_idx = _axis_index(group_axis)
        accs = group_idx * n_local + jnp.arange(n_local, dtype=jnp.int32)
        masks_local = jax.lax.dynamic_slice(
            masks_d, (0, group_idx * n_local),
            (masks_d.shape[0], n_local))

    lane_valid = lanes_new < block_size if block_pad else None

    def _mask_arrivals(arr):
        if lane_valid is None:
            return arr
        return arr & lane_valid[None, :].astype(jnp.uint8)

    proposed = lanes_new * 7 + i * 13 + 1
    if lane_valid is not None:
        proposed = jnp.where(lane_valid, proposed, 0)
    commands = jax.lax.dynamic_update_slice(state.commands, proposed,
                                            (start_new,))

    def quorum_pass(votes, chosen, committed, start, arrivals):
        block = jax.lax.dynamic_slice(votes, (0, start),
                                      (n_local, b_local)) | arrivals
        votes = jax.lax.dynamic_update_slice(votes, block, (0, start))
        if grid is not None and group_axis is None:
            hit = _fused_grid_hit(block, grid)
        elif grid is not None:
            kind, _, g_cols, _ = grid
            local_rows = []
            for r in range(block.shape[0] // g_cols):
                row = block[r * g_cols]
                for c in range(1, g_cols):
                    cell = block[r * g_cols + c]
                    row = (row | cell) if kind == "write" else (row & cell)
                local_rows.append(row)
            if kind == "write":
                missing = sum((jnp.uint8(1) - row for row in local_rows),
                              jnp.zeros((b_local,), jnp.uint8))
                hit = _psum(missing.astype(jnp.int32), group_axis) == 0
            else:
                full = sum(local_rows,
                           jnp.zeros((b_local,), jnp.uint8))
                hit = _psum(full.astype(jnp.int32), group_axis) > 0
        else:
            counts = _psum(masks_local @ block.astype(jnp.int32),
                           group_axis)                   # [G, b_local]
            satisfied = counts >= thresholds_d[:, None]
            hit = satisfied.any(0) if combine_any else satisfied.all(0)
        if lane_valid is not None:
            hit = hit & lane_valid
        old = jax.lax.dynamic_slice(chosen, (start,), (b_local,))
        newly = hit & ~old
        chosen = jax.lax.dynamic_update_slice(chosen, hit | old, (start,))
        committed = committed + _psum(newly.sum(dtype=jnp.int32), slot_axis)
        return votes, chosen, committed

    arr1 = _mask_arrivals(_arrivals(i, lanes_new, accs, salt=0))
    votes, chosen, committed = quorum_pass(
        state.votes, state.chosen, state.committed, start_new, arr1)
    arr2 = _mask_arrivals(1 - _arrivals(i - 1, lanes_new, accs, salt=0))
    votes, chosen, committed = quorum_pass(
        votes, chosen, committed, start_old, arr2)

    cmds_old = jax.lax.dynamic_slice(commands, (start_old,), (b_local,))
    block_results = cmds_old * 3 + 7
    if lane_valid is not None:
        block_results = jnp.where(lane_valid, block_results, 0)
    results = jax.lax.dynamic_update_slice(state.results, block_results,
                                           (start_old,))
    sm_state = state.sm_state + _psum(cmds_old.sum(dtype=jnp.int32),
                                      slot_axis)
    exec_wm = jnp.where(i >= 1, i.astype(jnp.int32) * block_size, 0)

    start_gc = ((i - 2) % num_blocks) * b_local
    votes = jax.lax.dynamic_update_slice(
        votes, jnp.zeros((n_local, b_local), jnp.uint8), (0, start_gc))
    chosen = jax.lax.dynamic_update_slice(
        chosen, jnp.zeros((b_local,), jnp.bool_), (start_gc,))

    return PipelineState(votes, chosen, commands, results, sm_state,
                         committed, exec_wm)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6),
                   donate_argnums=(0,))
def run_steps_from(state: PipelineState, start: jax.Array, iters: int,
                   block_size: int, masks_t: tuple, thresholds_t: tuple,
                   combine_any: bool) -> PipelineState:
    """The pinned chunked runner (traced start, one executable)."""
    masks = np.asarray(masks_t, dtype=np.int32)
    thresholds = np.asarray(thresholds_t, dtype=np.int32)

    def body(i, s):
        return steady_state_step(s, i, block_size=block_size, masks=masks,
                                 thresholds=thresholds,
                                 combine_any=combine_any)

    return jax.lax.fori_loop(start, start + iters, body, state)
