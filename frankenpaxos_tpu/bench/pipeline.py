"""The fully device-resident MultiPaxos steady-state pipeline.

This is the north-star benchmark configuration (BASELINE.json): the
steady-state Phase2 write path of compartmentalized MultiPaxos --
propose -> acceptor votes -> quorum check -> chosen -> replica execute ->
GC -- expressed as one jitted step over a ``[acceptors, window]`` vote
board with a 1M-slot in-flight window, iterated under ``lax.fori_loop``
with donated state. No host round-trips on the hot path.

The SAME ``steady_state_step`` function serves both single-chip execution
(axes ``None``) and multi-chip ``shard_map`` execution over a
``(group, slot)`` mesh: acceptor rows shard over ``group`` (quorum counts
ride a psum over ICI), the slot window shards over ``slot`` (committed /
sm-state counters psum over it). Global semantics are identical across
mesh shapes because vote arrivals and proposed commands are functions of
the *logical* (block-lane, acceptor) coordinates, which partition the
same way under every sharding.

Mapping to the reference's roles (SURVEY.md section 3.1):

  * Leader.processClientRequestBatch (Leader.scala:331-408): slot
    assignment is the contiguous block frontier; proposed command ids are
    written into the window.
  * Acceptor.handlePhase2a (Acceptor.scala:184-220): vote arrivals land
    as a dense ``[n, B]`` bitmask OR'd into the board. Arrival patterns
    are hash-derived per (iteration, acceptor, block-lane): ~87% of votes
    arrive in the drain after proposal, the rest one drain later --
    modeling cross-drain vote straggling.
  * ProxyLeader.handlePhase2b (ProxyLeader.scala:217-258): the quorum
    predicate matmul over the touched blocks; newly-chosen = hit & ~chosen.
  * Replica.executeLog (Replica.scala:394-453): chosen commands apply to
    a device state register; the executed watermark trails the fully
    chosen block; replies are counted.
  * BufferMap GC (BufferMap.scala:55-62): executed blocks are zeroed so
    the ring can wrap.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from frankenpaxos_tpu.ops.telemetry import (
    drain_update,
    make_telemetry,
    quorum_pass_update,
    TELEMETRY_PARTITION,
    TelemetryState,
)


class PipelineState(NamedTuple):
    votes: jax.Array      # [n, window] uint8
    chosen: jax.Array     # [window] bool
    commands: jax.Array   # [window] int32 proposed command ids
    results: jax.Array    # [window] int32 state-machine outputs
    sm_state: jax.Array   # [] int32: the replica's running register
    committed: jax.Array  # [] int32 committed commands
    exec_wm: jax.Array    # [] int32 executed watermark (global slots)
    # paxpulse device counters (ops/telemetry.py) -- None means the
    # telemetry plane is OFF and every accumulation site compiles out
    # (the pytree simply has no leaves there), keeping the traced ops
    # byte-identical to the pre-paxpulse pipeline.
    telemetry: Optional[TelemetryState] = None


def make_state(window: int, num_acceptors: int, *,
               telemetry: bool = False,
               slot_shards: int = 1) -> PipelineState:
    return PipelineState(
        votes=jnp.zeros((num_acceptors, window), jnp.uint8),
        chosen=jnp.zeros((window,), jnp.bool_),
        commands=jnp.zeros((window,), jnp.int32),
        results=jnp.zeros((window,), jnp.int32),
        sm_state=jnp.int32(0),
        committed=jnp.int32(0),
        exec_wm=jnp.int32(0),
        telemetry=(make_telemetry(num_acceptors, slot_shards)
                   if telemetry else None),
    )


def _arrivals(i: jax.Array, lanes: jax.Array, accs: jax.Array,
              salt: int) -> jax.Array:
    """Deterministic pseudo-random [len(accs), len(lanes)] uint8 arrival
    mask, keyed by logical (block-lane, global-acceptor) coordinates so
    every mesh sharding generates the same votes for the same slot."""
    h = (lanes[None, :] * 1103515245 + accs[:, None] * 12820163
         + (i + salt) * 22695477) >> 7
    return ((h & 7) < 7).astype(jnp.uint8)  # ~87.5% arrive this drain


def _psum(x, axis: Optional[str]):
    return x if axis is None else jax.lax.psum(x, axis)


def _axis_index(axis: Optional[str]) -> jax.Array:
    return jnp.int32(0) if axis is None else jax.lax.axis_index(axis)


def local_block(block_size: int, slot_shards: int) -> tuple:
    """``(b_local, pad)``: the per-shard lane count (the global block
    rounded UP over the slot shards) and the number of pad lanes the
    rounding adds to the padded global block. A non-divisible split
    (e.g. a 1M-slot block over 3 slot shards) pads the last lanes of
    every block; the padded lanes are masked out of proposals, votes,
    commits, and execution inside :func:`steady_state_step`, so the
    committed results stay bit-identical to the unpadded host oracle."""
    b_local = -(-block_size // slot_shards)
    return b_local, b_local * slot_shards - block_size


def padded_window(window: int, block_size: int, slot_shards: int) -> int:
    """The padded GLOBAL window for a sharded run: every shard holds
    whole rounded-up ``b_local`` blocks, so the global window grows by
    ``pad`` lanes per block when the block does not divide over the
    slot shards (and is unchanged when it does)."""
    if window % block_size:
        raise ValueError(
            f"window {window} must hold whole {block_size}-slot blocks")
    b_local, _ = local_block(block_size, slot_shards)
    return (window // block_size) * b_local * slot_shards


def gathered_layout(slot_shards: int, w_local: int, b_local: int,
                    block_size: int) -> tuple:
    """``(logical, valid)`` for each physical column of the gathered
    sharded window (shard windows concatenated): ``logical[c]`` is the
    unsharded slot id the column holds and ``valid[c]`` is False for
    pad columns (lane >= block_size under a rounded-up split), whose
    logical id is meaningless. Within shard ``s``, local column ``j``
    holds block ``j // b_local`` at block-lane
    ``s * b_local + (j % b_local)``; the unsharded layout is
    block-major."""
    cols = np.arange(slot_shards * w_local)
    s, j = cols // w_local, cols % w_local
    bi, lane = j // b_local, s * b_local + (j % b_local)
    return bi * block_size + lane, lane < block_size


def steady_state_step(state: PipelineState, i: jax.Array, *,
                      block_size: int, masks: np.ndarray,
                      thresholds, combine_any: bool,
                      group_axis: Optional[str] = None,
                      slot_axis: Optional[str] = None,
                      group_shards: int = 1,
                      slot_shards: int = 1) -> PipelineState:
    """One event-loop drain: new proposals + straggler completion.

    Each block gets exactly two passes (drain t: most votes; drain t+1:
    the stragglers), so the window holds ~2 blocks of in-flight
    vote-collection at the frontier plus the chosen/executing tail
    behind it.

    The quorum predicate is the general factored form
    (quorums/spec.py): ``masks`` is ``[G, N]`` over the global
    acceptors, ``thresholds`` is ``[G]``, and per-slot satisfaction
    combines over the G mask groups with any (``combine_any=True``) or
    all. SimpleMajority is G=1; a Grid write spec is one mask per row
    with threshold 1 combined with ALL ("one vote in every row",
    quorums/Grid.scala:5-57).

    ``block_size`` and ``masks`` are GLOBAL (whole-mesh) quantities; when
    called inside ``shard_map``, ``state`` holds this shard's local view
    and ``group_axis``/``slot_axis`` name the mesh axes (with their
    static sizes in ``group_shards``/``slot_shards``).
    """
    n_local, w_local = state.votes.shape
    # A block that does not divide over the slot shards rounds the
    # local block UP; the pad lanes (global lane >= block_size) are
    # masked out of every effect below, so the committed semantics are
    # those of the unpadded global block (make_sharded_state sizes the
    # padded window to match).
    b_local, block_pad = local_block(block_size, slot_shards)
    assert w_local % b_local == 0, (
        f"local window {w_local} must hold whole {b_local}-slot blocks")
    masks_d = jnp.asarray(masks, dtype=jnp.int32)          # [G, n_global]
    thresholds_d = jnp.asarray(np.asarray(thresholds, dtype=np.int32))
    assert thresholds_d.shape == (masks_d.shape[0],), (
        f"{thresholds_d.shape} thresholds for {masks_d.shape[0]} mask "
        f"groups")
    assert masks_d.shape[1] == group_shards * n_local, (
        f"masks cover {masks_d.shape[1]} acceptors but the mesh holds "
        f"{group_shards} x {n_local}")
    num_blocks = w_local // b_local
    start_new = (i % num_blocks) * b_local
    start_old = ((i - 1) % num_blocks) * b_local

    # Grid specs take the fused col-OR/row-AND reduction instead of the
    # mask matmul (ops/quorum.grid_layout): pure boolean ops, no int32
    # widening, bit-identical hits. Under group sharding the fused path
    # engages only when every shard holds WHOLE rows (row-major
    # universe, local columns a multiple of the row length); rows that
    # straddle shards fall back to the psum'd matmul.
    from frankenpaxos_tpu.ops.quorum import _fused_grid_hit, grid_layout

    grid = grid_layout(masks, thresholds, combine_any)
    if grid is not None and group_axis is not None \
            and (grid[3] is not None or n_local % grid[2] != 0):
        grid = None

    # Logical coordinates: lane within the global block, global acceptor.
    # The unsharded case avoids the (traced-index) slice/offset ops so
    # XLA sees pure iota inputs and fuses everything into the matmul.
    if slot_axis is None:
        lanes_new = jnp.arange(b_local, dtype=jnp.int32)
    else:
        lanes_new = (_axis_index(slot_axis) * b_local
                     + jnp.arange(b_local, dtype=jnp.int32))
    if group_axis is None:
        accs = jnp.arange(n_local, dtype=jnp.int32)
        masks_local = masks_d
    else:
        group_idx = _axis_index(group_axis)
        accs = group_idx * n_local + jnp.arange(n_local, dtype=jnp.int32)
        masks_local = jax.lax.dynamic_slice(
            masks_d, (0, group_idx * n_local),
            (masks_d.shape[0], n_local))

    # Pad-lane mask for non-divisible splits; the divisible (and the
    # unsharded) case stays mask-free so the hot path traces the exact
    # same ops as before. Lane coordinates are block-relative, so ONE
    # mask covers the new block, the straggler block, and execution.
    lane_valid = lanes_new < block_size if block_pad else None

    def _mask_arrivals(arr):
        if lane_valid is None:
            return arr
        return arr & lane_valid[None, :].astype(jnp.uint8)

    # --- Leader: assign slots, propose command ids --------------------------
    proposed = lanes_new * 7 + i * 13 + 1
    if lane_valid is not None:
        proposed = jnp.where(lane_valid, proposed, 0)
    commands = jax.lax.dynamic_update_slice(state.commands, proposed,
                                            (start_new,))

    def quorum_pass(votes, chosen, committed, tel, start, arrivals):
        block = jax.lax.dynamic_slice(votes, (0, start),
                                      (n_local, b_local)) | arrivals
        votes = jax.lax.dynamic_update_slice(votes, block, (0, start))
        if grid is not None and group_axis is None:
            hit = _fused_grid_hit(block, grid)
        elif grid is not None:
            # Sharded: this shard holds whole rows (see the gate
            # above; perm is None there). Per-row unrolled elementwise
            # chains like _fused_grid_hit's, combined ACROSS shards by
            # psum-ing missing/full row counts.
            kind, _, g_cols, _ = grid
            local_rows = []
            for r in range(block.shape[0] // g_cols):
                row = block[r * g_cols]
                for c in range(1, g_cols):
                    cell = block[r * g_cols + c]
                    row = (row | cell) if kind == "write" else (row & cell)
                local_rows.append(row)
            if kind == "write":
                # ALL rows present <=> zero missing rows mesh-wide.
                missing = sum((jnp.uint8(1) - row for row in local_rows),
                              jnp.zeros((b_local,), jnp.uint8))
                hit = _psum(missing.astype(jnp.int32), group_axis) == 0
            else:
                full = sum(local_rows,
                           jnp.zeros((b_local,), jnp.uint8))
                hit = _psum(full.astype(jnp.int32), group_axis) > 0
        else:
            counts = _psum(masks_local @ block.astype(jnp.int32),
                           group_axis)                   # [G, b_local]
            satisfied = counts >= thresholds_d[:, None]
            hit = satisfied.any(0) if combine_any else satisfied.all(0)
        if lane_valid is not None:
            # Pad lanes never accrue votes, but a degenerate spec could
            # still "hit" them; keep them permanently unchosen.
            hit = hit & lane_valid
        old = jax.lax.dynamic_slice(chosen, (start,), (b_local,))
        newly = hit & ~old
        chosen = jax.lax.dynamic_update_slice(chosen, hit | old, (start,))
        # Post-group-psum ``newly`` is replicated over group; summing the
        # slot shards yields the global count, replicated everywhere.
        committed = committed + _psum(newly.sum(dtype=jnp.int32), slot_axis)
        if tel is not None:
            # paxpulse: at choose time, how many GLOBAL votes had landed
            # on each lane? (Only traced on the telemetry-on arm.)
            votes_count = _psum(block.astype(jnp.int32).sum(0),
                                group_axis)
            tel = quorum_pass_update(tel, votes_count=votes_count,
                                     newly=newly, slot_axis=slot_axis)
        return votes, chosen, committed, tel

    # --- Acceptors + ProxyLeader: pass 1 on the new block -------------------
    arr1 = _mask_arrivals(_arrivals(i, lanes_new, accs, salt=0))
    votes, chosen, committed, tel = quorum_pass(
        state.votes, state.chosen, state.committed, state.telemetry,
        start_new, arr1)
    # --- pass 2: stragglers complete the previous block ---------------------
    arr2 = _mask_arrivals(1 - _arrivals(i - 1, lanes_new, accs, salt=0))
    votes, chosen, committed, tel = quorum_pass(
        votes, chosen, committed, tel, start_old, arr2)

    # --- Replica: execute the now fully-chosen previous block ---------------
    cmds_old = jax.lax.dynamic_slice(commands, (start_old,), (b_local,))
    block_results = cmds_old * 3 + 7
    if lane_valid is not None:
        block_results = jnp.where(lane_valid, block_results, 0)
    results = jax.lax.dynamic_update_slice(state.results, block_results,
                                           (start_old,))
    sm_state = state.sm_state + _psum(cmds_old.sum(dtype=jnp.int32),
                                      slot_axis)
    exec_wm = jnp.where(i >= 1, i.astype(jnp.int32) * block_size, 0)

    # --- GC: release the block executed long ago so the ring can wrap -------
    # (Early iterations "GC" still-zero wrap-around blocks: harmless.)
    start_gc = ((i - 2) % num_blocks) * b_local
    votes = jax.lax.dynamic_update_slice(
        votes, jnp.zeros((n_local, b_local), jnp.uint8), (0, start_gc))
    chosen = jax.lax.dynamic_update_slice(
        chosen, jnp.zeros((b_local,), jnp.bool_), (start_gc,))

    # paxpulse once-per-drain counters: proposal fill, pad-lane waste,
    # and the end-of-drain watermark lag (slots proposed but unchosen --
    # with ring reuse, cumulative proposals are (i+1) * block_size).
    # The lag expression stays under the guard so the telemetry-off
    # trace is the pre-paxpulse program to the op.
    if tel is not None:
        tel = drain_update(tel, proposed_block=proposed,
                           lane_valid=lane_valid,
                           lag=(i.astype(jnp.int32) + 1) * block_size
                           - committed,
                           slot_axis=slot_axis)

    return PipelineState(votes, chosen, commands, results, sm_state,
                         committed, exec_wm, tel)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5),
                   donate_argnums=(0,))
def run_steps(state: PipelineState, iters: int, block_size: int,
              masks_t: tuple, thresholds_t: tuple,
              combine_any: bool) -> PipelineState:
    """``iters`` drains in one dispatch (the bench hot loop)."""
    masks = np.asarray(masks_t, dtype=np.int32)
    thresholds = np.asarray(thresholds_t, dtype=np.int32)

    def body(i, s):
        return steady_state_step(s, i, block_size=block_size, masks=masks,
                                 thresholds=thresholds,
                                 combine_any=combine_any)

    return jax.lax.fori_loop(0, iters, body, state)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6),
                   donate_argnums=(0,))
def run_steps_from(state: PipelineState, start: jax.Array, iters: int,
                   block_size: int, masks_t: tuple, thresholds_t: tuple,
                   combine_any: bool) -> PipelineState:
    """:func:`run_steps` with a TRACED start iteration: chunked A/B
    arms resume the drain counter where the previous chunk left off
    (ring positions and arrival hashes continue instead of replaying
    drain 0), and every chunk reuses one compiled executable."""
    masks = np.asarray(masks_t, dtype=np.int32)
    thresholds = np.asarray(thresholds_t, dtype=np.int32)

    def body(i, s):
        return steady_state_step(s, i, block_size=block_size, masks=masks,
                                 thresholds=thresholds,
                                 combine_any=combine_any)

    return jax.lax.fori_loop(start, start + iters, body, state)


def drain_latency_distribution(spec_arrays, num_acceptors: int,
                               window: int, block_size: int,
                               mean_drain_us: float,
                               time_budget_s: float = 20.0,
                               target_samples: int = 1024) -> dict:
    """A TRUE per-drain latency distribution: host-timed dispatches of
    ``chunk`` drains each, p50/p99 over >= dozens-to-1k samples.

    The fused ``fori_loop`` throughput run can only report a mean (no
    per-drain observation exists inside the loop); this replaces that
    proxy for the latency figure. The chunk size ADAPTS to the
    device-link round-trip: every host-timed sample costs one
    dispatch+fetch RTT, so the chunk must be wide enough that compute
    dominates link jitter (on a local TPU the null RTT is ~0.1 ms and
    128-drain chunks work; through a tunnel with ~120 +- 50 ms RTTs the
    chunk self-scales up). The measured null-RTT p50 is subtracted
    from each sample; link jitter beyond that is attributed to the
    drain, making the reported p99 an honest UPPER bound. All
    methodology inputs are returned alongside the percentiles."""
    import time

    masks_t, thresholds_t, combine_any = spec_arrays

    # Null dispatch+fetch RTT: same sync pattern as a timed sample.
    noop = jax.jit(lambda x: x + 1)
    x = jnp.int32(0)
    for _ in range(3):
        x = noop(x)
        _ = int(x)
    null = []
    for _ in range(30):
        t0 = time.perf_counter()
        x = noop(x)
        _ = int(x)
        null.append(time.perf_counter() - t0)
    null_p50_us = float(np.percentile(null, 50) * 1e6)
    null_p90_us = float(np.percentile(null, 90) * 1e6)

    # Chunk so compute >= 8x the null p90 (link jitter), floor 128.
    chunk = 128
    while chunk * mean_drain_us < 8 * null_p90_us and chunk < (1 << 16):
        chunk *= 2
    est_sample_s = (chunk * mean_drain_us + null_p50_us) / 1e6
    samples = max(24, min(target_samples,
                          int(time_budget_s / max(est_sample_s, 1e-9))))

    state = make_state(window, num_acceptors)
    state = run_steps(state, chunk, block_size, masks_t, thresholds_t,
                      combine_any)
    _ = int(state.committed)  # warm the exact chunked shape
    times = []
    for _ in range(samples):
        t0 = time.perf_counter()
        state = run_steps(state, chunk, block_size, masks_t,
                          thresholds_t, combine_any)
        _ = int(state.committed)  # value fetch: cannot complete early
        times.append(time.perf_counter() - t0)
    per_drain_us = (np.asarray(times) * 1e6 - null_p50_us) / chunk
    per_drain_us = np.maximum(per_drain_us, 0.0)
    return {
        "p50_drain_latency_us": round(float(
            np.percentile(per_drain_us, 50)), 2),
        "p99_drain_latency_us": round(float(
            np.percentile(per_drain_us, 99)), 2),
        "latency_samples": samples,
        "drains_per_sample": chunk,
        "null_rtt_p50_us": round(null_p50_us, 1),
        "null_rtt_p90_us": round(null_p90_us, 1),
        "latency_method": (
            "host-timed dispatches of drains_per_sample fused drains "
            "each; per-drain = (sample - null_rtt_p50) / "
            "drains_per_sample; chunk auto-scaled so compute >= 8x "
            "null-RTT p90, so link jitter beyond the median RTT is "
            "attributed to the drain (p99 is an upper bound)"),
    }


# --------------------------------------------------------------------------
# Multi-chip: the same step under shard_map over a (group, slot) mesh.
# --------------------------------------------------------------------------

PIPELINE_PARTITION = PipelineState(
    votes=("group", "slot"),
    chosen=("slot",),
    commands=("slot",),
    results=("slot",),
    sm_state=(),
    committed=(),
    exec_wm=(),
    # The telemetry leaf defaults to None (plane off). When the plane is
    # on, its per-leaf axes come from ops/telemetry.TELEMETRY_PARTITION
    # via :func:`partition_specs`.
)


def partition_specs(telemetry: bool = False):
    """The ``PartitionSpec`` tree for a ``PipelineState`` over the
    ``(group, slot)`` mesh: ``PIPELINE_PARTITION`` leaf-for-leaf, with
    the paxpulse subtree (per ``TELEMETRY_PARTITION``) attached when the
    telemetry plane is on and an empty (``None``) node when off."""
    from jax.sharding import PartitionSpec as P

    tel = (TelemetryState(*(P(*axes) for axes in TELEMETRY_PARTITION))
           if telemetry else None)
    base = {field: P(*axes)
            for field, axes in zip(PipelineState._fields,
                                   PIPELINE_PARTITION)
            if isinstance(axes, tuple)}
    return PipelineState(telemetry=tel, **base)


def _shard_map_fn():
    fn = getattr(jax, "shard_map", None)
    if fn is None:  # older jax
        from jax.experimental.shard_map import shard_map as fn
    return fn


def make_sharded_step(mesh, *, block_size: int, masks: np.ndarray,
                      thresholds, combine_any: bool,
                      telemetry: bool = False):
    """Jit ``steady_state_step`` under shard_map over ``mesh``.

    ``mesh`` must have axes ``("group", "slot")``. Returns
    ``(step, state_sharding)``: ``step(state, i)`` runs one drain with
    quorum counts psum'd over the group axis and counters psum'd over the
    slot axis; ``state_sharding`` is the matching ``NamedSharding`` tree
    for ``jax.device_put``.
    """
    import inspect

    from jax.sharding import NamedSharding, PartitionSpec as P

    group_shards = mesh.shape["group"]
    slot_shards = mesh.shape["slot"]
    step = functools.partial(
        steady_state_step, block_size=block_size, masks=masks,
        thresholds=thresholds, combine_any=combine_any,
        group_axis="group", slot_axis="slot",
        group_shards=group_shards, slot_shards=slot_shards)

    spec_tree = partition_specs(telemetry)
    shard_map = _shard_map_fn()
    kwargs = {}
    params = inspect.signature(shard_map).parameters
    if "check_vma" in params:
        kwargs["check_vma"] = False
    elif "check_rep" in params:
        kwargs["check_rep"] = False
    sharded = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(spec_tree, P()), out_specs=spec_tree,
        **kwargs), donate_argnums=(0,))
    sharding = jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree)
    return sharded, sharding


def state_sharding(mesh, telemetry: bool = False):
    """The ``NamedSharding`` tree matching ``PIPELINE_PARTITION`` over
    ``mesh`` (what :func:`make_sharded_step` returns as its second
    element), for callers that place state without building a step."""
    from jax.sharding import NamedSharding

    spec_tree = partition_specs(telemetry)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree)


def make_sharded_state(mesh, window: int, block_size: int,
                       num_acceptors: int, *,
                       telemetry: bool = False) -> tuple:
    """``(state, sharding, w_padded)``: a fresh ``PipelineState`` laid
    out over ``mesh`` for a GLOBAL ``window`` of whole ``block_size``
    blocks. When the block does not divide over the slot shards the
    window is PADDED (see :func:`padded_window`); the pad lanes are
    masked inside :func:`steady_state_step`, so committed counts and
    per-slot results match the unpadded host oracle bit-for-bit
    (compare through :func:`gathered_layout`)."""
    slot_shards = mesh.shape["slot"]
    w_padded = padded_window(window, block_size, slot_shards)
    sharding = state_sharding(mesh, telemetry)
    state = jax.device_put(
        make_state(w_padded, num_acceptors, telemetry=telemetry,
                   slot_shards=slot_shards), sharding)
    return state, sharding, w_padded


def make_sharded_runner(mesh, *, block_size: int, masks: np.ndarray,
                        thresholds, combine_any: bool, iters: int,
                        telemetry: bool = False):
    """The mesh twin of :func:`run_steps_from`: jit one shard_map'd
    ``fori_loop`` of ``iters`` drains (ONE dispatch per call, the bench
    hot loop -- per-drain dispatch through :func:`make_sharded_step`
    costs a host round-trip per drain and measures the link, not the
    mesh). Returns ``(runner, sharding)`` with
    ``runner(state, start) -> state``."""
    import inspect

    from jax.sharding import PartitionSpec as P

    group_shards = mesh.shape["group"]
    slot_shards = mesh.shape["slot"]

    def run(state, start):
        def body(i, s):
            return steady_state_step(
                s, i, block_size=block_size, masks=masks,
                thresholds=thresholds, combine_any=combine_any,
                group_axis="group", slot_axis="slot",
                group_shards=group_shards, slot_shards=slot_shards)

        return jax.lax.fori_loop(start, start + iters, body, state)

    spec_tree = partition_specs(telemetry)
    shard_map = _shard_map_fn()
    kwargs = {}
    params = inspect.signature(shard_map).parameters
    if "check_vma" in params:
        kwargs["check_vma"] = False
    elif "check_rep" in params:
        kwargs["check_rep"] = False
    runner = jax.jit(shard_map(
        run, mesh=mesh, in_specs=(spec_tree, P()), out_specs=spec_tree,
        **kwargs), donate_argnums=(0,))
    return runner, state_sharding(mesh, telemetry)
