"""The fully device-resident MultiPaxos steady-state pipeline.

This is the north-star benchmark configuration (BASELINE.json): the
steady-state Phase2 write path of compartmentalized MultiPaxos --
propose -> acceptor votes -> quorum check -> chosen -> replica execute ->
GC -- expressed as one jitted step over a ``[acceptors, window]`` vote
board with a 1M-slot in-flight window, iterated under ``lax.fori_loop``
with donated state. No host round-trips on the hot path (mandatory: the
device link has ~10ms+ fetch latency; see .claude/skills/verify/SKILL.md).

Mapping to the reference's roles (SURVEY.md section 3.1):

  * Leader.processClientRequestBatch (Leader.scala:331-408): slot
    assignment is the contiguous block frontier; proposed command ids are
    written into the window.
  * Acceptor.handlePhase2a (Acceptor.scala:184-220): vote arrivals land
    as a dense ``[n, B]`` bitmask OR'd into the board. Arrival patterns
    are hash-derived per (iteration, acceptor, slot): ~87% of votes
    arrive in the drain after proposal, the rest one drain later --
    modeling cross-drain vote straggling.
  * ProxyLeader.handlePhase2b (ProxyLeader.scala:217-258): the quorum
    predicate matmul over the touched blocks; newly-chosen = hit & ~chosen.
  * Replica.executeLog (Replica.scala:394-453): chosen commands apply to
    a device state register; the executed watermark trails the fully
    chosen block; replies are counted.
  * BufferMap GC (BufferMap.scala:55-62): executed blocks are zeroed so
    the ring can wrap.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class PipelineState(NamedTuple):
    votes: jax.Array      # [n, window] uint8
    chosen: jax.Array     # [window] bool
    commands: jax.Array   # [window] int32 proposed command ids
    results: jax.Array    # [window] int32 state-machine outputs
    sm_state: jax.Array   # [] int32: the replica's running register
    committed: jax.Array  # [] int32 committed commands
    exec_wm: jax.Array    # [] int32 executed watermark (global slots)


def make_state(window: int, num_acceptors: int) -> PipelineState:
    return PipelineState(
        votes=jnp.zeros((num_acceptors, window), jnp.uint8),
        chosen=jnp.zeros((window,), jnp.bool_),
        commands=jnp.zeros((window,), jnp.int32),
        results=jnp.zeros((window,), jnp.int32),
        sm_state=jnp.int32(0),
        committed=jnp.int32(0),
        exec_wm=jnp.int32(0),
    )


def _arrivals(i: jax.Array, start: jax.Array, n: int, block: int,
              salt: int) -> jax.Array:
    """Deterministic pseudo-random [n, block] uint8 vote-arrival mask."""
    lane = start + jnp.arange(block, dtype=jnp.int32)
    acc = jnp.arange(n, dtype=jnp.int32)[:, None]
    h = (lane[None, :] * 1103515245 + acc * 12820163
         + (i + salt) * 22695477) >> 7
    return ((h & 7) < 7).astype(jnp.uint8)  # ~87.5% arrive this drain


def steady_state_step(state: PipelineState, i: jax.Array, *,
                      block_size: int, masks: np.ndarray,
                      threshold: int) -> PipelineState:
    """One event-loop drain: new proposals + straggler completion.

    Each block gets exactly two passes (drain t: most votes; drain t+1:
    the stragglers), so the window holds ~2 blocks of in-flight
    vote-collection at the frontier plus the 1M-slot chosen/executing
    tail behind it.
    """
    n, window = state.votes.shape
    b = block_size
    masks_d = jnp.asarray(masks, dtype=jnp.int32)          # [1, N]
    num_blocks = window // b
    start_new = (i % num_blocks) * b
    start_old = ((i - 1) % num_blocks) * b

    # --- Leader: assign slots, propose command ids --------------------------
    proposed = (start_new + jnp.arange(b, dtype=jnp.int32)) * 7 + i
    commands = jax.lax.dynamic_update_slice(state.commands, proposed,
                                            (start_new,))

    def quorum_pass(votes, chosen, committed, start, arrivals):
        block = jax.lax.dynamic_slice(votes, (0, start), (n, b)) | arrivals
        votes = jax.lax.dynamic_update_slice(votes, block, (0, start))
        counts = (masks_d @ block.astype(jnp.int32))[0]     # [B]
        hit = counts >= threshold
        old = jax.lax.dynamic_slice(chosen, (start,), (b,))
        newly = hit & ~old
        chosen = jax.lax.dynamic_update_slice(chosen, hit | old, (start,))
        return votes, chosen, committed + newly.sum(dtype=jnp.int32), newly

    # --- Acceptors + ProxyLeader: pass 1 on the new block -------------------
    arr1 = _arrivals(i, start_new, n, b, salt=0)
    votes, chosen, committed, newly1 = quorum_pass(
        state.votes, state.chosen, state.committed, start_new, arr1)
    # --- pass 2: stragglers complete the previous block ---------------------
    arr2 = 1 - _arrivals(i - 1, start_old, n, b, salt=0)
    votes, chosen, committed, newly2 = quorum_pass(
        votes, chosen, committed, start_old, arr2)

    # --- Replica: execute the now fully-chosen previous block ---------------
    cmds_old = jax.lax.dynamic_slice(commands, (start_old,), (b,))
    block_results = cmds_old * 3 + 7
    results = jax.lax.dynamic_update_slice(state.results, block_results,
                                           (start_old,))
    sm_state = state.sm_state + cmds_old.sum(dtype=jnp.int32)
    exec_wm = jnp.where(i >= 1, (i.astype(jnp.int32)) * b, 0)

    # --- GC: release the block executed long ago so the ring can wrap -------
    # (Early iterations "GC" still-zero wrap-around blocks: harmless.)
    start_gc = ((i - 2) % num_blocks) * b
    votes = jax.lax.dynamic_update_slice(
        votes, jnp.zeros((n, b), jnp.uint8), (0, start_gc))
    chosen = jax.lax.dynamic_update_slice(
        chosen, jnp.zeros((b,), jnp.bool_), (start_gc,))

    return PipelineState(votes, chosen, commands, results, sm_state,
                         committed, exec_wm)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4),
                   donate_argnums=(0,))
def run_steps(state: PipelineState, iters: int, block_size: int,
              masks_t: tuple, threshold: int) -> PipelineState:
    """``iters`` drains in one dispatch (the bench hot loop)."""
    masks = np.asarray(masks_t, dtype=np.int32)

    def body(i, s):
        return steady_state_step(s, i, block_size=block_size, masks=masks,
                                 threshold=threshold)

    return jax.lax.fori_loop(0, iters, body, state)
