"""Client workload generators.

Reference behavior: jvm/src/main/scala/frankenpaxos/Workload.scala (the
write-only family: StringWorkload, UniformSingleKeyWorkload,
BernoulliSingleKeyWorkload), jvm/.../multipaxos/ReadWriteWorkload.scala
(the read/write family: UniformReadWriteWorkload,
PointSkewedReadWriteWorkload, UniformMultiKeyReadWriteWorkload, and
WriteOnly wrappers), and their Python spec side benchmarks/workload.py +
benchmarks/read_write_workload.py. Specs are JSON dicts here (the
prototext analog), constructed via ``workload_from_dict``.

Commands are bytes for the target state machine: raw strings for
AppendLog/Noop/Register, pickled GetRequest/SetRequest for
KeyValueStore.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Union

from frankenpaxos_tpu.runtime.serializer import PickleSerializer
from frankenpaxos_tpu.statemachine import GetRequest, SetRequest

_SER = PickleSerializer()


def _sized_value(rng: random.Random, mean: int, std: int) -> str:
    size = max(0, round(rng.gauss(mean, std)))
    return "x" * size


@dataclasses.dataclass(frozen=True)
class StringWorkload:
    """Write-only strings, sizes ~ N(mean, std) (Workload.scala:27-37)."""

    size_mean: int = 8
    size_std: int = 0

    def get(self, rng: random.Random) -> bytes:
        return _sized_value(rng, self.size_mean, self.size_std).encode()


@dataclasses.dataclass(frozen=True)
class UniformSingleKeyWorkload:
    """Coin-flip get/set over ``num_keys`` uniform keys
    (Workload.scala:39-72)."""

    num_keys: int = 1
    size_mean: int = 8
    size_std: int = 0

    def get(self, rng: random.Random) -> bytes:
        key = str(rng.randrange(self.num_keys))
        if rng.random() < 0.5:
            return _SER.to_bytes(GetRequest((key,)))
        value = _sized_value(rng, self.size_mean, self.size_std)
        return _SER.to_bytes(SetRequest(((key, value),)))


@dataclasses.dataclass(frozen=True)
class BernoulliSingleKeyWorkload:
    """Set key "x" with probability ``conflict_rate``, else get key "y"
    -- the conflict-rate dial for generalized protocols
    (Workload.scala:74-103)."""

    conflict_rate: float = 0.5
    size_mean: int = 8
    size_std: int = 0

    def get(self, rng: random.Random) -> bytes:
        if rng.random() <= self.conflict_rate:
            value = _sized_value(rng, self.size_mean, self.size_std)
            return _SER.to_bytes(SetRequest((("x", value),)))
        return _SER.to_bytes(GetRequest(("y",)))


Workload = Union[StringWorkload, UniformSingleKeyWorkload,
                 BernoulliSingleKeyWorkload]


# --- read/write workloads --------------------------------------------------

READ = "read"
WRITE = "write"


@dataclasses.dataclass(frozen=True)
class UniformReadWriteWorkload:
    """``read_fraction`` of ops are reads; keys uniform over
    ``num_keys`` (multipaxos/ReadWriteWorkload.scala:19-58)."""

    num_keys: int = 1
    read_fraction: float = 0.5
    write_size_mean: int = 8
    write_size_std: int = 0

    def get(self, rng: random.Random) -> tuple[str, bytes]:
        key = str(rng.randrange(self.num_keys))
        if rng.random() < self.read_fraction:
            return READ, _SER.to_bytes(GetRequest((key,)))
        value = _sized_value(rng, self.write_size_mean,
                             self.write_size_std)
        return WRITE, _SER.to_bytes(SetRequest(((key, value),)))


@dataclasses.dataclass(frozen=True)
class PointSkewedReadWriteWorkload:
    """``point_fraction`` of ops hit one hot key; the rest are uniform
    (multipaxos/ReadWriteWorkload.scala:60-110)."""

    num_keys: int = 1
    read_fraction: float = 0.5
    point_fraction: float = 0.5
    write_size_mean: int = 8
    write_size_std: int = 0

    def get(self, rng: random.Random) -> tuple[str, bytes]:
        if rng.random() < self.point_fraction:
            key = "point"
        else:
            key = str(rng.randrange(self.num_keys))
        if rng.random() < self.read_fraction:
            return READ, _SER.to_bytes(GetRequest((key,)))
        value = _sized_value(rng, self.write_size_mean,
                             self.write_size_std)
        return WRITE, _SER.to_bytes(SetRequest(((key, value),)))


@dataclasses.dataclass(frozen=True)
class UniformMultiKeyReadWriteWorkload:
    """Each op touches ``num_operations`` distinct uniform keys
    (multipaxos/ReadWriteWorkload.scala:112-163)."""

    num_keys: int = 2
    num_operations: int = 2
    read_fraction: float = 0.5
    write_size_mean: int = 8
    write_size_std: int = 0

    def get(self, rng: random.Random) -> tuple[str, bytes]:
        n = min(self.num_operations, self.num_keys)
        keys = [str(k) for k in rng.sample(range(self.num_keys), n)]
        if rng.random() < self.read_fraction:
            return READ, _SER.to_bytes(GetRequest(tuple(keys)))
        pairs = tuple(
            (key, _sized_value(rng, self.write_size_mean,
                               self.write_size_std))
            for key in keys)
        return WRITE, _SER.to_bytes(SetRequest(pairs))


@dataclasses.dataclass(frozen=True)
class WriteOnlyWorkload:
    """Wrap a write-only Workload as a ReadWriteWorkload
    (multipaxos/ReadWriteWorkload.scala:165-170)."""

    workload: Workload

    def get(self, rng: random.Random) -> tuple[str, bytes]:
        return WRITE, self.workload.get(rng)


ReadWriteWorkload = Union[UniformReadWriteWorkload,
                          PointSkewedReadWriteWorkload,
                          UniformMultiKeyReadWriteWorkload,
                          WriteOnlyWorkload]


# --- the shared open-loop workload (paxload, serve/loadgen.py) -------------


@dataclasses.dataclass(frozen=True)
class OpenLoopWorkload:
    """THE open-loop generator both bench arms share: the vectorized
    sim load tier (serve/loadgen.py, bench/overload_lt.py) and the
    deployed driver (bench/client_main.py --open_loop) draw from this
    one definition, so "10x offered load" means the same arrival
    process, key skew, and mix on both paths.

    Open loop: arrivals fire on the ARRIVAL PROCESS's schedule,
    independent of completions (closed loops self-throttle and can
    never overload anything -- the pathology "Paxos in the Cloud"
    documents needs open-loop pressure). Components:

      * ``rate`` arrivals/s aggregate, as a Poisson process
        (``process="poisson"``) or with heavy-tailed per-window burst
        modulation (``process="pareto"``: the window's rate is scaled
        by a Pareto(alpha) multiplier normalized to mean 1 -- bursty
        like production edges, still open-loop).
      * Zipf(``zipf_s``) key skew over ``num_keys`` (0 = uniform):
        the canonical hot-key distribution.
      * A diurnal ramp: rate * (1 + amplitude * sin(2*pi*t/period)).

    Scalar ``get(rng)`` keeps the ReadWriteWorkload interface for
    closed-loop reuse; the vectorized entry points take a
    ``numpy.random.Generator`` and return arrays."""

    rate: float = 1000.0
    process: str = "poisson"         # "poisson" | "pareto"
    pareto_alpha: float = 2.5        # burst-tail index (>1)
    zipf_s: float = 0.0              # 0 = uniform keys
    num_keys: int = 1024
    read_fraction: float = 0.0
    write_size_mean: int = 8
    write_size_std: int = 0
    diurnal_amplitude: float = 0.0
    diurnal_period_s: float = 60.0
    #: Phase shift of the diurnal ramp (paxworld follow-the-sun:
    #: region k's lane runs the SAME ramp offset by k * period/3, so
    #: the global peak walks around the planet).
    diurnal_phase_s: float = 0.0

    def offered_rate(self, t: float) -> float:
        """The instantaneous target rate at virtual time ``t``
        (diurnal modulation only; burstiness is sampled per window)."""
        if not self.diurnal_amplitude:
            return self.rate
        import math

        return self.rate * max(0.0, 1.0 + self.diurnal_amplitude
                               * math.sin(2 * math.pi
                                          * (t + self.diurnal_phase_s)
                                          / self.diurnal_period_s))

    def arrival_count(self, np_rng, t: float, dt: float) -> int:
        """Arrivals in [t, t+dt): Poisson around the modulated rate,
        optionally Pareto-burst-scaled (mean-1 multiplier, so the
        long-run offered rate is unchanged -- only its variance)."""
        lam = self.offered_rate(t) * dt
        if self.process == "pareto":
            alpha = self.pareto_alpha
            # numpy's pareto is the Lomax shift: mean alpha/(alpha-1)
            # after +1; normalize to mean 1.
            burst = (1.0 + np_rng.pareto(alpha)) * (alpha - 1.0) / alpha
            lam *= burst
        return int(np_rng.poisson(lam))

    def _zipf_cdf(self, np_rng):
        import numpy as np

        cdf = _ZIPF_CDF_CACHE.get((self.num_keys, self.zipf_s))
        if cdf is None:
            ranks = np.arange(1, self.num_keys + 1, dtype=np.float64)
            weights = ranks ** -self.zipf_s
            cdf = np.cumsum(weights)
            cdf /= cdf[-1]
            _ZIPF_CDF_CACHE[(self.num_keys, self.zipf_s)] = cdf
        return cdf

    def sample_keys(self, np_rng, n: int):
        """``n`` key indices, Zipf-skewed (vectorized searchsorted
        over the precomputed CDF) or uniform when ``zipf_s`` is 0."""
        import numpy as np

        if not self.zipf_s:
            return np_rng.integers(0, self.num_keys, n)
        u = np_rng.random(n)
        return np.searchsorted(self._zipf_cdf(np_rng), u)

    def sample_kinds(self, np_rng, n: int):
        """Boolean read mask for ``n`` ops."""
        return np_rng.random(n) < self.read_fraction

    def get(self, rng: random.Random) -> tuple[str, bytes]:
        """Scalar ReadWriteWorkload-compatible draw (the deployed
        closed-loop drivers and tests)."""
        if self.zipf_s:
            # Inverse-CDF draw through the same table as the
            # vectorized path (one bisect).
            import bisect

            cdf = self._zipf_cdf(None)
            key = str(bisect.bisect_left(cdf, rng.random()))
        else:
            key = str(rng.randrange(self.num_keys))
        if rng.random() < self.read_fraction:
            return READ, _SER.to_bytes(GetRequest((key,)))
        value = _sized_value(rng, self.write_size_mean,
                             self.write_size_std)
        return WRITE, _SER.to_bytes(SetRequest(((key, value),)))


_ZIPF_CDF_CACHE: dict = {}


# Client read-consistency level -> multipaxos Client method name
# (Client.scala:851-933, :697+, :739+).
READ_METHODS = {
    "linearizable": "read",
    "sequential": "sequential_read",
    "eventual": "eventual_read",
}

_BY_NAME = {
    "string": StringWorkload,
    "uniform_single_key": UniformSingleKeyWorkload,
    "bernoulli_single_key": BernoulliSingleKeyWorkload,
    "uniform_read_write": UniformReadWriteWorkload,
    "point_skewed_read_write": PointSkewedReadWriteWorkload,
    "uniform_multi_key_read_write": UniformMultiKeyReadWriteWorkload,
    "write_only": WriteOnlyWorkload,
    "open_loop": OpenLoopWorkload,
}


def workload_from_dict(spec: dict):
    """Build a workload from a JSON spec: ``{"name": ..., **params}``
    (the prototext-config analog, Workload.scala:105-147)."""
    spec = dict(spec)
    name = spec.pop("name")
    try:
        cls = _BY_NAME[name]
    except KeyError:
        raise ValueError(
            f"unknown workload {name!r}; known: {sorted(_BY_NAME)}"
        ) from None
    if cls is WriteOnlyWorkload:
        return WriteOnlyWorkload(workload_from_dict(spec["workload"]))
    return cls(**spec)


def workload_to_dict(workload) -> dict:
    name = next(n for n, cls in _BY_NAME.items()
                if cls is type(workload))
    if isinstance(workload, WriteOnlyWorkload):
        return {"name": name,
                "workload": workload_to_dict(workload.workload)}
    return {"name": name, **dataclasses.asdict(workload)}
