"""Process-failure chaos for deployed clusters: SIGKILL + relaunch,
and the paxepoch repair path: kill -> reconfigure-out -> replace.

The deployment twin of the sim's ``crash_restart`` command
(SimTransport.crash + the harness restart): ``kill -9`` a role process
mid-benchmark -- no SIGTERM grace, no flush, the real crash -- then
relaunch it VERBATIM from the command ``deploy_suite.launch_roles``
recorded (same ports, same ``--wal_dir``), so the role recovers from
its WAL and rejoins the live cluster. With no wal_dir this demonstrates
the pre-WAL failure mode instead: the role comes back amnesiac.

The RECONFIGURATION driver (reconfig/, docs/RECONFIG.md) goes further
than resurrection: ``launch_replacement_acceptor`` starts a brand-new
acceptor process at a FRESH address (a rewritten config file puts it in
the dead member's group slot) and ``reconfigure_acceptors`` drives the
leader's epoch-change flow to swap the membership live -- the repair
the PR 3 vldb20_reconfig study showed a frozen acceptor set lacks.

Used by the deployed crash-restart and reconfigure-under-kill tests
(tests/test_deployment.py) and the vldb20_reconfig sweep's
kill-then-repair events (bench/sweeps.py).
"""

from __future__ import annotations

import copy
import json
import os
import signal
import sys
import time

from frankenpaxos_tpu.bench.harness import BenchmarkDirectory, LocalHost


def sigkill_role(bench: BenchmarkDirectory, label: str) -> None:
    """``kill -9`` the role process for ``label`` and reap it. When the
    deployment ran with ``trace_dir`` (paxtrace), the killed role's
    flight-recorder ring is snapshotted to the post-mortem JSON
    immediately -- BEFORE any relaunch can reuse the ring file. When a
    paxpulse :class:`~frankenpaxos_tpu.obs.telemetry.TelemetryReporter`
    is registered for the label (``bench.telemetry_reporters``), its
    last device-counter summary is snapshotted beside the ring."""
    proc = bench.labeled_procs[label]
    os.kill(proc.pid(), signal.SIGKILL)
    proc.wait(timeout=10)
    collect_flight_record(bench, label)
    collect_telemetry_snapshot(bench, label)


def collect_flight_record(bench: BenchmarkDirectory,
                          label: str) -> "str | None":
    """Dump ``label``'s flight-recorder ring (the mmap'd file survives
    SIGKILL) to ``<bench>/<label>.flight.json``; numbered like the
    killed logs so repeated kills of one label keep every post-mortem.
    Returns the dump path, or None when tracing was off."""
    trace_dir = getattr(bench, "trace_dir", None)
    if not trace_dir:
        return None
    ring = os.path.join(trace_dir, f"{label}.flight")
    if not os.path.exists(ring):
        return None
    from frankenpaxos_tpu.obs import FlightRecorder

    out = bench.abspath(f"{label}.flight.json")
    n = 1
    while os.path.exists(out):
        out = bench.abspath(f"{label}.flight.json.killed{n}")
        n += 1
    FlightRecorder.dump_file(ring, out)
    return out


def collect_telemetry_snapshot(bench: BenchmarkDirectory,
                               label: str) -> "str | None":
    """Dump the last paxpulse device-counter summary for ``label`` to
    ``<bench>/<label>.telemetry.json`` -- the device-plane half of the
    SIGKILL post-mortem (the flight ring is the host half).

    Harnesses that drive a device pipeline beside the deployed roles
    register the reporter in ``bench.telemetry_reporters[label]``; the
    host-side reporter holds the last ``collect()`` snapshot, so the
    post-mortem shows the pipeline's committed/occupancy/lag counters
    as of the last reporting interval before the kill. Numbered like
    the flight dumps so repeated kills keep every post-mortem.
    Returns the dump path, or None when no reporter is registered."""
    reporter = getattr(bench, "telemetry_reporters", {}).get(label)
    if reporter is None:
        return None
    out = bench.abspath(f"{label}.telemetry.json")
    n = 1
    while os.path.exists(out):
        out = bench.abspath(f"{label}.telemetry.json.killed{n}")
        n += 1
    with open(out, "w") as f:
        json.dump(reporter.summary(), f, indent=2, sort_keys=True)
        f.write("\n")
    return out


def relaunch_role(bench: BenchmarkDirectory, label: str,
                  host: "LocalHost | None" = None):
    """Restart ``label`` with its recorded command, RE-READ from the
    launch spec at call time (``bench.role_commands`` -- so a spec
    updated since launch, e.g. by a replacement swap, relaunches the
    current membership, not a stale snapshot). The old log moves aside
    (``<label>.log.killed<N>``) so the relaunch does not destroy the
    pre-kill evidence."""
    cmd, env = bench.role_commands[label]
    log = bench.abspath(f"{label}.log")
    if os.path.exists(log):
        n = 1
        while os.path.exists(f"{log}.killed{n}"):
            n += 1
        os.replace(log, f"{log}.killed{n}")
    return bench.popen(host or LocalHost(), label, cmd, env=env)


def wait_relaunched_ready(bench: BenchmarkDirectory, labels,
                          host: "LocalHost | None" = None,
                          timeout_s: float = 60.0) -> None:
    """Block until every relaunched ``label`` reports "listening" in
    its FRESH log (relaunch_role moved the pre-kill log aside, so the
    grep can't match stale output). The launch-time connect-back
    handshake is gone by now -- its listener closed after
    ``launch_roles`` -- so readiness after a mid-run relaunch is the
    log-grep seam, same as remote hosts use at launch."""
    host = host or LocalHost()
    deadline = time.time() + timeout_s
    pending = set(labels)
    while pending and time.time() < deadline:
        ready = host.grep_ready(
            [bench.abspath(f"{label}.log") for label in pending],
            "listening")
        pending -= {label for label in pending
                    if bench.abspath(f"{label}.log") in ready}
        if pending:
            time.sleep(0.1)
    if pending:
        raise RuntimeError(
            f"relaunched roles never became ready: {sorted(pending)}")


def kill_relaunch(bench: BenchmarkDirectory, labels, *,
                  down_s: float = 0.5,
                  host: "LocalHost | None" = None,
                  wait_ready: bool = False,
                  ready_timeout_s: float = 60.0) -> list:
    """THE kill -> dwell -> relaunch (-> reready) sequence, shared by
    the per-role and per-zone wrappers below and by the paxchaos
    deployed fault backend (faults/deployed_backend.py) -- previously
    copied three times with drifting details. SIGKILLs every label (no
    grace, flight post-mortems snapshotted), leaves them dead for
    ``down_s`` (requests that depended on them must ride resends),
    relaunches each verbatim from the recorded launch spec, and
    optionally blocks until the relaunches report listening."""
    for label in labels:
        sigkill_role(bench, label)
    time.sleep(down_s)
    procs = [relaunch_role(bench, label, host=host) for label in labels]
    if wait_ready:
        wait_relaunched_ready(bench, labels, host=host,
                              timeout_s=ready_timeout_s)
    return procs


def kill_restart_role(bench: BenchmarkDirectory, label: str,
                      down_s: float = 0.5,
                      host: "LocalHost | None" = None):
    """SIGKILL ``label``, dwell, relaunch (one label through
    :func:`kill_relaunch`)."""
    return kill_relaunch(bench, [label], down_s=down_s, host=host)[0]


# --- paxepoch repair: reconfigure-out + replacement -------------------------


def launch_replacement_acceptor(bench: BenchmarkDirectory, raw_config,
                                group: int, member: int,
                                protocol_name: str = "multipaxos",
                                state_machine: str = "AppendLog",
                                wal_dir: "str | None" = None,
                                trace_dir: "str | None" = None,
                                overrides: "dict | None" = None,
                                host: "LocalHost | None" = None):
    """Start a BRAND-NEW acceptor process at a fresh port to replace
    ``raw_config['acceptors'][group][member]``.

    The replacement gets its own rewritten config file (the original
    with its address in the dead member's slot) -- the group/index
    lookups in the acceptor's constructor then resolve, while every
    OTHER role keeps the original config: membership authority lives
    in the epoch store, which the subsequent ``Reconfigure`` updates.
    Returns ``(new_members, label)`` where ``new_members`` is the full
    address tuple to pass to :func:`reconfigure_acceptors`.
    """
    from frankenpaxos_tpu.bench.deploy_suite import role_process_env
    from frankenpaxos_tpu.bench.harness import free_port

    new_raw = copy.deepcopy(raw_config)
    new_address = ["127.0.0.1", free_port()]
    new_raw["acceptors"][group][member] = new_address
    index = sum(len(g) for g in new_raw["acceptors"][:group]) + member
    label = f"acceptor_{index}_replacement"
    n = 1
    while label in bench.labeled_procs:
        label = f"acceptor_{index}_replacement{n}"
        n += 1
    config_path = bench.write_json(f"{label}_config.json", new_raw)
    cmd = [sys.executable, "-m", "frankenpaxos_tpu.cli",
           "--protocol", protocol_name, "--role", "acceptor",
           "--index", str(index), "--config", config_path,
           "--state_machine", state_machine, "--seed", str(100 + index)]
    if wal_dir:
        # The cli derives the WAL path from the role LABEL
        # (acceptor_<index>) -- which the replacement shares with the
        # member it replaces. A private subdirectory keeps the new
        # member's log genuinely FRESH (it must join via the epoch
        # handover, not inherit the dead acceptor's votes) and rules
        # out two live processes appending to one WAL on a non-kill
        # swap.
        cmd += ["--wal_dir", os.path.join(wal_dir, label)]
    if trace_dir:
        cmd += ["--trace", trace_dir]
    for key, value in (overrides or {}).items():
        cmd.append(f"--options.{key}={value}")
    env = role_process_env()
    bench.role_commands[label] = (cmd, env)
    bench.popen(host or LocalHost(), label, cmd, env=env)
    members = tuple(tuple(a) for a in new_raw["acceptors"][group])
    return members, label


def reconfigure_acceptors(transport, leader_addresses,
                          members: tuple) -> None:
    """Fire the paxepoch config-change request at every leader (only
    the active one acts; the leader-driven flow -- EpochCommit,
    durable old-quorum acks, watermark-bounded handover -- takes it
    from there). Call from off the transport's loop thread."""
    from frankenpaxos_tpu.reconfig import Reconfigure
    from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER

    data = DEFAULT_SERIALIZER.to_bytes(Reconfigure(members=members))
    for leader in leader_addresses:
        transport.send(transport.listen_address, tuple(leader), data)


# --- paxgeo: zone-scoped failure + object placement -------------------------


def zone_labels(labels, zone_roles) -> list:
    """The deployed labels belonging to one zone, in kill order
    (leader first so nothing proposes into a dying row). ``labels``
    is the bench's ``labeled_procs`` keys; ``zone_roles`` the exact
    role labels the zone owns (e.g. from ``wpaxos_zone_roles``)."""
    return [label for label in zone_roles if label in labels]


def wpaxos_zone_roles(raw_config: dict, zone: int) -> list:
    """Role labels for zone ``zone`` of a deployed wpaxos cluster
    (the deploy registry's label scheme: leader_<z>, acceptor_<flat
    index>, replica_<z>)."""
    width = len(raw_config["acceptors"][zone])
    return ([f"leader_{zone}"]
            + [f"acceptor_{zone * width + i}" for i in range(width)]
            + [f"replica_{zone}"])


def sigkill_zone(bench: BenchmarkDirectory, labels) -> None:
    """Zone outage: ``kill -9`` EVERY role in the zone through the
    PR 3 SIGKILL machinery (flight-recorder post-mortems included),
    instead of the per-role loops the scenario drivers used to
    hand-roll."""
    for label in labels:
        sigkill_role(bench, label)


def relaunch_zone(bench: BenchmarkDirectory, labels,
                  host: "LocalHost | None" = None) -> list:
    """Relaunch a killed zone VERBATIM from the recorded role
    commands (same ports, same ``--wal_dir``): acceptors recover
    their promises/votes/epochs from their WALs, the leader and
    replica come back fresh and re-acquire state through steals and
    hole recovery."""
    return [relaunch_role(bench, label, host=host)
            for label in labels]


def kill_restart_zone(bench: BenchmarkDirectory, labels,
                      down_s: float = 0.5,
                      host: "LocalHost | None" = None) -> list:
    """SIGKILL a whole zone, leave it dark for ``down_s`` (steals of
    its objects block on the dead row -- the f_z = 0 tradeoff,
    docs/GEO.md), then relaunch it verbatim (one zone through
    :func:`kill_relaunch`)."""
    return kill_relaunch(bench, labels, down_s=down_s, host=host)


def steal_group(transport, leader_address, group: int) -> None:
    """Admin trigger: make ``leader_address``'s zone steal object
    group ``group`` (the placement driver's adapt step and the
    zone-outage repair path). Call from off the transport's loop
    thread, like :func:`reconfigure_acceptors`."""
    from frankenpaxos_tpu.protocols.wpaxos.messages import Steal
    from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER

    data = DEFAULT_SERIALIZER.to_bytes(Steal(group=group))
    transport.send(transport.listen_address, tuple(leader_address)
                   if isinstance(leader_address, list)
                   else leader_address, data)
