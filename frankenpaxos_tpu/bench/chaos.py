"""Process-failure chaos for deployed clusters: SIGKILL + relaunch.

The deployment twin of the sim's ``crash_restart`` command
(SimTransport.crash + the harness restart): ``kill -9`` a role process
mid-benchmark -- no SIGTERM grace, no flush, the real crash -- then
relaunch it VERBATIM from the command ``deploy_suite.launch_roles``
recorded (same ports, same ``--wal_dir``), so the role recovers from
its WAL and rejoins the live cluster. With no wal_dir this demonstrates
the pre-WAL failure mode instead: the role comes back amnesiac.

Used by the deployed crash-restart test (tests/test_deployment.py) and
the vldb20_reconfig sweep's kill-mid-reconfig event
(bench/sweeps.py).
"""

from __future__ import annotations

import os
import signal
import time

from frankenpaxos_tpu.bench.harness import BenchmarkDirectory, LocalHost


def sigkill_role(bench: BenchmarkDirectory, label: str) -> None:
    """``kill -9`` the role process for ``label`` and reap it. When the
    deployment ran with ``trace_dir`` (paxtrace), the killed role's
    flight-recorder ring is snapshotted to the post-mortem JSON
    immediately -- BEFORE any relaunch can reuse the ring file."""
    proc = bench.labeled_procs[label]
    os.kill(proc.pid(), signal.SIGKILL)
    proc.wait(timeout=10)
    collect_flight_record(bench, label)


def collect_flight_record(bench: BenchmarkDirectory,
                          label: str) -> "str | None":
    """Dump ``label``'s flight-recorder ring (the mmap'd file survives
    SIGKILL) to ``<bench>/<label>.flight.json``; numbered like the
    killed logs so repeated kills of one label keep every post-mortem.
    Returns the dump path, or None when tracing was off."""
    trace_dir = getattr(bench, "trace_dir", None)
    if not trace_dir:
        return None
    ring = os.path.join(trace_dir, f"{label}.flight")
    if not os.path.exists(ring):
        return None
    from frankenpaxos_tpu.obs import FlightRecorder

    out = bench.abspath(f"{label}.flight.json")
    n = 1
    while os.path.exists(out):
        out = bench.abspath(f"{label}.flight.json.killed{n}")
        n += 1
    FlightRecorder.dump_file(ring, out)
    return out


def relaunch_role(bench: BenchmarkDirectory, label: str,
                  host: "LocalHost | None" = None):
    """Restart ``label`` with its recorded command. The old log moves
    aside (``<label>.log.killed<N>``) so the relaunch does not destroy
    the pre-kill evidence."""
    cmd, env = bench.role_commands[label]
    log = bench.abspath(f"{label}.log")
    if os.path.exists(log):
        n = 1
        while os.path.exists(f"{log}.killed{n}"):
            n += 1
        os.replace(log, f"{log}.killed{n}")
    return bench.popen(host or LocalHost(), label, cmd, env=env)


def kill_restart_role(bench: BenchmarkDirectory, label: str,
                      down_s: float = 0.5,
                      host: "LocalHost | None" = None):
    """SIGKILL ``label``, leave it dead for ``down_s`` (requests that
    depended on it must ride resends), then relaunch it."""
    sigkill_role(bench, label)
    time.sleep(down_s)
    return relaunch_role(bench, label, host=host)
