"""Benchmark orchestration harness.

Reference behavior: benchmarks/ (proc.py:65-160 PopenProc, host.py:10-37,
benchmark.py:73-335 SuiteDirectory/BenchmarkDirectory/Suite with
latency/throughput output schemas, workload.py). This is the local-
process slice of that harness: launch every role as its own OS process
via the CLI (frankenpaxos_tpu/cli.py), drive a closed-loop workload from
in-process clients, and record the reference-compatible stats
(latency.median_ms, start_throughput_1s.p90 analogs) as JSON/CSV.
SSH deployment (ParamikoProc) plugs in behind Proc.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional, Sequence

import numpy as np


class Proc:
    """A managed subprocess (the PopenProc shape, proc.py:65-110)."""

    def __init__(self, args: Sequence[str], out_path: str,
                 env: Optional[dict] = None):
        self._out = open(out_path, "w")
        self._proc = subprocess.Popen(
            list(args), stdout=self._out, stderr=subprocess.STDOUT,
            env=env,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))

    def pid(self) -> int:
        return self._proc.pid

    def kill(self) -> None:
        if self._proc.poll() is None:
            self._proc.send_signal(signal.SIGTERM)
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._out.close()

    def running(self) -> bool:
        return self._proc.poll() is None

    def wait(self, timeout: Optional[float] = None) -> int:
        return self._proc.wait(timeout=timeout)


@dataclasses.dataclass(frozen=True)
class LocalHost:
    """(host.py:10-24)."""

    ip: str = "127.0.0.1"

    def popen(self, args: Sequence[str], out_path: str,
              env: Optional[dict] = None) -> Proc:
        return Proc(args, out_path, env=env)

    def read_output(self, path: str) -> str:
        """Current contents of a launched process' output file (the
        ready-wait seam; RemoteHost reads through its shell instead)."""
        try:
            with open(path) as f:
                return f.read()
        except OSError:
            return ""

    def grep_ready(self, paths: Sequence[str], needle: str) -> set:
        """Which of ``paths`` currently contain ``needle`` (RemoteHost
        answers this in one shell round-trip for the whole set)."""
        return {p for p in paths if needle in self.read_output(p)}


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class BenchmarkDirectory:
    """A directory collecting one benchmark's artifacts
    (benchmark.py:220-340)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.procs: list[Proc] = []
        #: label -> Proc, for per-role accounting (CPU-time breakdowns).
        self.labeled_procs: dict[str, Proc] = {}
        # label -> /metrics port, filled by deploy_suite.launch_roles
        # when prometheus=True.
        self.prometheus_ports: dict[str, int] = {}
        # label -> (cmd, env), filled by deploy_suite.launch_roles so a
        # role can be relaunched verbatim (readiness retry, chaos
        # driver).
        self.role_commands: dict[str, tuple] = {}
        # label -> obs.telemetry.TelemetryReporter, registered by
        # harnesses that drive a device pipeline beside the roles;
        # chaos SIGKILL post-mortems snapshot each reporter's last
        # device-counter summary next to the flight ring.
        self.telemetry_reporters: dict = {}

    def abspath(self, name: str) -> str:
        return os.path.join(self.path, name)

    def write_json(self, name: str, data) -> str:
        path = self.abspath(name)
        with open(path, "w") as f:
            json.dump(data, f, indent=2, default=str)
        return path

    def popen(self, host: LocalHost, label: str,
              args: Sequence[str], env: Optional[dict] = None) -> Proc:
        proc = host.popen(args, self.abspath(f"{label}.log"), env=env)
        self.procs.append(proc)
        self.labeled_procs[label] = proc
        return proc

    @staticmethod
    def stage_projection(role_cpu: dict) -> dict:
        """The decoupling projection from a per-role CPU split: once
        every stage owns a core, pipeline wall time shrinks from
        sum(stage cpu) to max(stage cpu) -- Amdahl on the stage graph
        (DistributionScheme.scala:151-162). Returns {} when there is
        nothing to project. The ONE implementation shared by the sweep
        families and the protocol suite."""
        if not role_cpu:
            return {}
        total = sum(role_cpu.values())
        bottleneck_stage = max(role_cpu, key=role_cpu.get)
        bottleneck = role_cpu[bottleneck_stage]
        if bottleneck <= 0:
            return {}
        return {
            "role_cpu_s": round(total, 3),
            "bottleneck_stage": bottleneck_stage,
            "bottleneck_cpu_s": round(bottleneck, 3),
            "parallelizable_fraction": round(1 - bottleneck / total, 3),
            "projected_stage_speedup": round(total / bottleneck, 2),
        }

    def role_cpu_seconds(self) -> dict:
        """Per-role CPU time (user+sys, /proc/<pid>/stat) for every
        still-running local role process. Call BEFORE cleanup(). The
        per-stage accounting behind the compartmentalization
        projection (bench/coupled.py): on a one-core host the 4-8x
        decoupling win cannot show up in wall-clock, but the
        parallelizable fraction is exactly this breakdown."""
        tick = os.sysconf("SC_CLK_TCK")
        out = {}
        for label, proc in self.labeled_procs.items():
            if not isinstance(proc, Proc):
                # RemoteProc.pid() is a REMOTE pid: /proc/<it>/stat on
                # the launcher machine would describe some unrelated
                # local process. Per-role CPU accounting is
                # local-launch only.
                continue
            pid = proc.pid()
            try:
                with open(f"/proc/{pid}/stat") as f:
                    fields = f.read().rsplit(") ", 1)[-1].split()
                # utime, stime are fields 14,15 (1-indexed) = 11,12
                # after the (comm) split leaves state at index 0.
                out[label] = round(
                    (int(fields[11]) + int(fields[12])) / tick, 3)
            except (OSError, IndexError, ValueError):
                pass
        return out

    def cleanup(self) -> None:
        for proc in self.procs:
            proc.kill()


class SuiteDirectory:
    """(benchmark.py:73-130)."""

    def __init__(self, root: str, name: str):
        self.path = os.path.join(root, f"{name}_{int(time.time())}")
        os.makedirs(self.path, exist_ok=True)
        self._counter = 0

    def benchmark_directory(self) -> BenchmarkDirectory:
        self._counter += 1
        return BenchmarkDirectory(
            os.path.join(self.path, f"{self._counter:03d}"))


def rolling_throughput(starts_s: Sequence[float],
                       window_s: float = 1.0) -> np.ndarray:
    """Rolling-window throughput series (pd_util.py:35-86 semantics).

    For each request start t, the count of starts in (t - window, t]
    divided by the window, with the first window of samples trimmed
    (they see a partially-filled window and read artificially low).
    """
    starts = np.asarray(sorted(starts_s), dtype=np.float64)
    if starts.size == 0:
        return starts
    lo = np.searchsorted(starts, starts - window_s, side="right")
    counts = np.arange(1, starts.size + 1) - lo
    series = counts / window_s
    keep = starts >= starts[0] + window_s
    # Match pd_util.throughput's fallback: if everything happened within
    # one window, trim the first 100 samples instead of all of them.
    if not keep.any():
        return series[100:]
    return series[keep]


def _dist(values: np.ndarray, prefix: str, scale: float = 1.0,
          suffix: str = "") -> dict:
    if values.size == 0:
        return {}
    q = lambda p: float(np.percentile(values, p) * scale)
    return {
        f"{prefix}.mean{suffix}": float(values.mean() * scale),
        f"{prefix}.median{suffix}": q(50),
        f"{prefix}.min{suffix}": float(values.min() * scale),
        f"{prefix}.max{suffix}": float(values.max() * scale),
        f"{prefix}.p90{suffix}": q(90),
        f"{prefix}.p95{suffix}": q(95),
        f"{prefix}.p99{suffix}": q(99),
    }


def latency_throughput_stats(latencies_s: Sequence[float],
                             duration_s: float,
                             starts_s: Optional[Sequence[float]] = None,
                             ) -> dict:
    """The reference's RecorderOutput schema (benchmark.py:308-341).

    latency.* in milliseconds over per-request latencies;
    start_throughput_1s.* as percentiles of the rolling 1-second-window
    throughput series over request start times (benchmark.py:420) — NOT
    a mean disguised as a percentile.
    """
    lat = np.asarray(sorted(latencies_s))
    if lat.size == 0:
        return {"num_requests": 0}
    stats = {"num_requests": int(lat.size)}
    stats.update(_dist(lat, "latency", scale=1000.0, suffix="_ms"))
    series = (rolling_throughput(starts_s)
              if starts_s is not None and len(starts_s) > 0
              else np.empty(0))
    if series.size > 0:
        stats.update(_dist(series, "start_throughput_1s"))
    else:
        # No start timestamps recorded: report the honest mean under an
        # honest name rather than a fake percentile.
        stats["throughput_mean"] = float(lat.size / duration_s)
    return stats
