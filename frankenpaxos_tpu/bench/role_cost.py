"""Per-role cost breakdown for the deployed MultiPaxos pipeline.

VERDICT r3 (weak #3): deployed throughput here is 2-3 orders below the
reference's EC2 clusters, and nothing separated "Python actor
overhead" from "1-CPU contention". This benchmark separates them:

  * every role runs under cProfile (``launch_roles(profiled=True)``);
  * per role: CPU seconds (user+sys from /proc), wall seconds, and the
    cProfile time bucketed into IDLE_WAIT (blocked in the event loop's
    poll -- spare capacity, not work), STARTUP_IMPORT (one-time module
    import/compile), SERIALIZATION (wire codecs + pickle), TRANSPORT
    (asyncio/socket machinery), PROTOCOL (frankenpaxos_tpu protocol +
    runtime actor code), and OTHER;
  * aggregate: total role CPU vs wall shows the contention factor
    (>1 core-second per wall second means processes time-share);
    the per-bucket split says what a faster host/runtime would buy.

Usage::

    python -m frankenpaxos_tpu.bench.role_cost --duration 4 \
        --out bench_results/role_cost_breakdown.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import pstats
import tempfile
import time


_IDLE_FUNCS = ("select.epoll", "select.poll", "select.select",
               "time.sleep", "_thread.lock")
_IMPORT_FUNCS = ("builtins.compile", "builtins.exec", "_io.open_code",
                 "_imp.", "marshal.", "posix.stat", "posix.listdir")


def _bucket_of(path: str, func: str) -> str:
    # cProfile charges time BLOCKED in the event loop's poll to the
    # builtin itself -- that's idle capacity, not work, and on a lone
    # deployed role it dominates. Startup imports (compile/exec of
    # module code) are one-time cost, also not steady-state work.
    if any(tag in func for tag in _IDLE_FUNCS):
        return "idle_wait"
    if "importlib" in path or any(tag in func for tag in _IMPORT_FUNCS):
        return "startup_import"
    if "wire" in path or "pickle" in func or "serializer" in path \
            or "codec" in path:
        return "serialization"
    if "asyncio" in path or "selectors" in path or "socket" in func \
            or "tcp_transport" in path:
        return "transport"
    if "frankenpaxos_tpu" in path:
        return "protocol"
    return "other"


BUCKETS = ("idle_wait", "startup_import", "serialization", "transport",
           "protocol", "other")


def bucket_profile(prof_path: str) -> dict:
    """Bucket a cProfile dump's TOTTIME (self time) by subsystem."""
    stats = pstats.Stats(prof_path)
    buckets = dict.fromkeys(BUCKETS, 0.0)
    total = 0.0
    for (path, _line, func), (_cc, _nc, tottime, _ct, _callers) \
            in stats.stats.items():
        buckets[_bucket_of(path, func)] += tottime
        total += tottime
    return {
        "profiled_cpu_s": round(total, 3),
        **{k: round(v, 3) for k, v in buckets.items()},
    }


def main(argv=None) -> dict:
    from frankenpaxos_tpu.bench.harness import SuiteDirectory
    from frankenpaxos_tpu.bench.multipaxos_suite import (
        MultiPaxosInput,
        run_benchmark,
    )

    parser = argparse.ArgumentParser()
    parser.add_argument("--duration", type=float, default=4.0)
    parser.add_argument("--client_procs", type=int, default=2)
    parser.add_argument("--num_clients", type=int, default=5)
    parser.add_argument("--suite_dir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_rolecost_")
    suite = SuiteDirectory(root, "role_cost")
    bench = suite.benchmark_directory()
    t0 = time.time()
    stats = run_benchmark(
        bench,
        MultiPaxosInput(num_clients=args.num_clients,
                        client_procs=args.client_procs,
                        duration_s=args.duration, profiled=True))
    wall_s = time.time() - t0

    roles = {}
    for prof in sorted(glob.glob(os.path.join(bench.path, "*.prof"))):
        label = os.path.basename(prof)[:-len(".prof")]
        try:
            roles[label] = bucket_profile(prof)
        except Exception as e:  # truncated dump from a hard kill
            roles[label] = {"error": repr(e)}

    role_cpu = stats.get("role_cpu_seconds", {})
    total_cpu = sum(role_cpu.values())
    ok_roles = [r for r in roles.values() if "error" not in r]
    agg = {b: round(sum(r[b] for r in ok_roles), 3) for b in BUCKETS}
    profiled_total = sum(r["profiled_cpu_s"] for r in ok_roles) or 1.0
    result = {
        "benchmark": "role_cost_breakdown",
        "host_cpus": os.cpu_count(),
        "duration_s": args.duration,
        "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
        "latency_median_ms": stats.get("latency.median_ms"),
        "wall_s": round(wall_s, 1),
        "total_role_cpu_s": round(total_cpu, 3),
        "contention_factor": round(total_cpu / args.duration, 2),
        "role_cpu_seconds": role_cpu,
        "profiled_buckets_cpu_s": agg,
        "profiled_bucket_fractions": {
            k: round(v / profiled_total, 3) for k, v in agg.items()},
        "per_role": roles,
        "note": ("throughput here includes cProfile overhead (~3x vs the "
                 "unprofiled protocol_lt.json numbers); use it for the "
                 "cost SPLIT, not absolute rates. "
                 "contention_factor = role CPU seconds consumed per "
                 "wall second of load: above ~1.0 on this 1-core host "
                 "the roles time-share the CPU, so deployed throughput "
                 "measures the host, not the architecture. "
                 "profiled_bucket_fractions split the profiled time: "
                 "'idle_wait' is capacity the role had to spare "
                 "(blocked in poll), 'startup_import' is one-time "
                 "import cost, and the steady-state work splits into "
                 "'protocol' (actor/handler logic), 'serialization' "
                 "(wire codecs), 'transport' (asyncio/socket), and "
                 "'other' (interpreter/stdlib). "
                 "Together with coupled_vs_compartmentalized.json's "
                 "projection this separates Python overhead from "
                 "1-CPU contention (VERDICT r3 weak #3)."),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
