"""Benchmarking: the device-resident MultiPaxos pipeline and harness."""
