"""transport_lt: paired A/B of the paxwire batched TcpTransport vs the
per-frame baseline (docs/TRANSPORT.md).

    python -m frankenpaxos_tpu.bench.transport_lt \
        --out bench_results/transport_lt.json

Methodology (the multipaxos_lt/overload_lt paired-arm shape, applied
at the TRANSPORT layer): per in-flight width, the SAME closed-loop
request/reply workload runs over two real-TCP transport pairs in one
process --

  * ``per_frame``: ``TcpTransport(batching=False)`` -- the historical
    path, one encoded frame and one flush per ``send`` (the deployed
    transport before paxwire);
  * ``batched``: the default paxwire path -- per-event-loop-pass
    flushes, batch frames over adjacent same-type payloads, one
    scatter/gather writev per peer per pass.

The workload is the deployed wire's own message shapes (multipaxos
ClientRequest -> ClientReply through the registered fixed-layout
codecs), N pipelined in-flight commands per width, closed loop: every
reply immediately issues the next request. Both arms pay identical
codec, delivery, and handler costs; only the frame/flush/syscall layer
differs -- which is exactly what this artifact measures. Recorded per
arm: end-to-end cmds/s, syscalls/cmd (the transports' own counters:
one per writev/write call -- asyncio issues one ``send`` per
uncongested write), wire frames/cmd, and bytes/drain (batched bytes
per flush). Widths cover 16..4096; each pair is best-of-``reps`` on a
fresh transport pair (alternating arm order to split any thermal/GC
drift).

The committed artifact's gates (ISSUE 8 acceptance):
  * batched/per_frame throughput >= 2x at every width >= 256;
  * syscalls/cmd reduced >= 10x at 1024 in-flight.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import threading
import time

from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ClientReply,
    ClientRequest,
    Command,
    CommandId,
)
from frankenpaxos_tpu.runtime import FakeLogger
from frankenpaxos_tpu.runtime.actor import Actor
from frankenpaxos_tpu.runtime.logger import LogLevel
from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport

WIDTHS = (16, 64, 256, 1024, 4096)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _EchoServer(Actor):
    """Replies per request -- the reply stream is what the batched
    transport coalesces into batch frames."""

    def receive(self, src, message):
        self.send(src, ClientReply(
            command_id=message.command.command_id, slot=0,
            result=message.command.command))


class _ColumnEchoServer(Actor):
    """The paxingest arm's server: whole client batch frames land as
    SoA columns through the wire sink (ingest/columns.py) and each
    frame draws ONE ClientReplyArray -- no per-message decode, no
    Command objects (docs/TRANSPORT.md wire-to-device section)."""

    def __init__(self, address, transport, logger):
        super().__init__(address, transport, logger)
        from frankenpaxos_tpu.ingest.columns import (
            parse_client_array,
            parse_client_batch,
        )

        self.wire_sinks = {
            151: (parse_client_batch, self._handle_columns),
            115: (parse_client_array, self._handle_columns),
            4: (parse_client_array, self._handle_columns),
        }

    def _handle_columns(self, src, colrun) -> None:
        from frankenpaxos_tpu.protocols.multipaxos.messages import (
            ClientReplyArray,
        )

        cols = colrun.cols
        self.send(src, ClientReplyArray(entries=tuple(
            (int(p), int(c), 0, b"")
            for p, c in zip(cols[:, 1], cols[:, 2]))))

    def receive(self, src, message):
        # Fallback for shapes the sink declines.
        _EchoServer.receive(self, src, message)


class _LoadClient(Actor):
    """Closed loop: ``width`` pipelined commands; each reply issues the
    next request until ``total`` have been acknowledged."""

    def __init__(self, address, transport, logger, server, width,
                 total):
        super().__init__(address, transport, logger)
        self.server = server
        self.width = width
        self.total = total
        self.sent = 0
        self.acked = 0
        self.t0 = 0.0
        self.t1 = 0.0
        self.done = threading.Event()

    def start(self) -> None:
        def kick():
            self.t0 = time.perf_counter()
            for _ in range(min(self.width, self.total)):
                self._send_next()

        self.transport.loop.call_soon_threadsafe(kick)

    def _send_next(self) -> None:
        i = self.sent
        self.sent += 1
        self.send(self.server, ClientRequest(Command(
            CommandId(self.address, 0, i), b"w%010d" % i)))

    def receive(self, src, message) -> None:
        # The ingest arm acks whole frames with ClientReplyArray; the
        # classic arms reply per command.
        k = len(getattr(message, "entries", ())) or 1
        self.acked += k
        if self.acked >= self.total:
            self.t1 = time.perf_counter()
            self.done.set()
        else:
            for _ in range(min(k, self.total - self.sent)):
                self._send_next()


def run_arm(arm: str, width: int, total: int) -> dict:
    batching = arm != "per_frame"
    logger = FakeLogger(LogLevel.FATAL)
    server_addr = ("127.0.0.1", _free_port())
    client_addr = ("127.0.0.1", _free_port())
    server_t = TcpTransport(server_addr, logger, batching=batching)
    client_t = TcpTransport(client_addr, logger, batching=batching)
    server_t.start()
    client_t.start()
    try:
        server_cls = (_ColumnEchoServer if arm == "ingest"
                      else _EchoServer)
        server_cls(server_addr, server_t, logger)
        client = _LoadClient(client_addr, client_t, logger,
                             server_addr, width, total)
        client.start()
        if not client.done.wait(timeout=120):
            raise RuntimeError(
                f"arm wedged: {client.acked}/{total} acked")
        elapsed = client.t1 - client.t0
        syscalls = server_t.stat_syscalls + client_t.stat_syscalls
        frames = server_t.stat_frames + client_t.stat_frames
        flushes = server_t.stat_flushes + client_t.stat_flushes
        batch_bytes = (server_t.stat_batch_bytes
                       + client_t.stat_batch_bytes)
        return {
            "arm": arm,
            "batching": batching,
            "in_flight": width,
            "num_commands": total,
            "elapsed_s": elapsed,
            "cmds_per_s": total / elapsed,
            "syscalls": syscalls,
            "syscalls_per_cmd": syscalls / total,
            "frames": frames,
            "frames_per_cmd": frames / total,
            "flushes": flushes,
            "bytes_per_drain": (batch_bytes / flushes
                                if batching and flushes else None),
            "coalesced_acks": (server_t.stat_coalesced_acks
                               + client_t.stat_coalesced_acks),
        }
    finally:
        server_t.stop()
        client_t.stop()


def run_pair(width: int, total: int, reps: int) -> dict:
    """Best-of-``reps`` for each arm on fresh transports, order
    alternated so drift lands on all arms equally. The ``ingest`` arm
    (paxingest wire-sink columns, one reply array per frame) rides
    along as the wire-to-device reference point; its own gate lives in
    bench/ingest_lt.py."""
    best: dict = {}
    order = ("per_frame", "batched", "ingest")
    for rep in range(reps):
        arms = order if rep % 2 == 0 else tuple(reversed(order))
        for arm in arms:
            stats = run_arm(arm, width, total)
            if arm not in best or stats["cmds_per_s"] \
                    > best[arm]["cmds_per_s"]:
                best[arm] = stats
    pair = dict(best)
    pair["throughput_ratio"] = (best["batched"]["cmds_per_s"]
                                / best["per_frame"]["cmds_per_s"])
    pair["ingest_ratio"] = (best["ingest"]["cmds_per_s"]
                            / best["per_frame"]["cmds_per_s"])
    pair["syscall_reduction"] = (
        best["per_frame"]["syscalls_per_cmd"]
        / max(best["batched"]["syscalls_per_cmd"], 1e-12))
    return pair


def evaluate_gates(pairs: dict) -> dict:
    """The ISSUE 8 acceptance clauses over the measured pairs."""
    throughput_2x = {
        str(w): pairs[w]["throughput_ratio"]
        for w in pairs if w >= 256}
    syscalls_at_1024 = (pairs[1024]["syscall_reduction"]
                        if 1024 in pairs else None)
    return {
        "throughput_ratio_at_ge_256": throughput_2x,
        "throughput_2x_passed": all(
            r >= 2.0 for r in throughput_2x.values()),
        "syscall_reduction_at_1024": syscalls_at_1024,
        "syscalls_10x_passed": (syscalls_at_1024 is not None
                                and syscalls_at_1024 >= 10.0),
        # The control-never-shed-behind-client-batches clause is a
        # TEST, not a measurement:
        # tests/test_paxwire.py::test_outbound_shed_drops_client_lane_before_control
        # and the native/Python bit-parity clause is
        # tests/test_native_parity.py.
        "gate_passed": None,  # filled below
    }


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(
        description="paxwire batched-transport A/B (docs/TRANSPORT.md)")
    parser.add_argument("--out", default=None,
                        help="write the JSON artifact here")
    parser.add_argument("--smoke", action="store_true",
                        help="reduced widths/commands (~30 s)")
    parser.add_argument("--reps", type=int, default=3)
    args = parser.parse_args(argv)

    widths = (16, 256, 1024) if args.smoke else WIDTHS
    reps = 2 if args.smoke else args.reps
    pairs: dict = {}
    for width in widths:
        total = min(max(width * 30, 2000),
                    8000 if args.smoke else 40000)
        pairs[width] = run_pair(width, total, reps)
        p = pairs[width]
        print(f"in_flight={width:5d}: per_frame "
              f"{p['per_frame']['cmds_per_s']:9.0f}/s "
              f"batched {p['batched']['cmds_per_s']:9.0f}/s "
              f"ratio {p['throughput_ratio']:.2f}x "
              f"ingest {p['ingest']['cmds_per_s']:9.0f}/s "
              f"({p['ingest_ratio']:.2f}x) "
              f"syscalls/cmd {p['per_frame']['syscalls_per_cmd']:.2f}"
              f"->{p['batched']['syscalls_per_cmd']:.4f} "
              f"({p['syscall_reduction']:.0f}x)")
    gates = evaluate_gates(pairs)
    gates["gate_passed"] = (gates["throughput_2x_passed"]
                            and gates["syscalls_10x_passed"])
    result = {
        "benchmark": "transport_lt",
        "methodology": (
            "paired real-TCP closed-loop A/B in one process "
            "(multipaxos_lt deployed-points shape at the transport "
            "layer): per width, the same ClientRequest->ClientReply "
            "workload over TcpTransport(batching=False) vs the "
            "paxwire batched default; best-of-reps per arm on fresh "
            "transports, arm order alternated. syscalls = the "
            "transports' writev/write counters (one asyncio send per "
            "uncongested write); bytes_per_drain = batched bytes per "
            "flush pass."),
        "smoke": bool(args.smoke),
        "reps": reps,
        "pairs": {str(w): pairs[w] for w in sorted(pairs)},
        "gates": gates,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    print(f"gate_passed={gates['gate_passed']}")
    return result


if __name__ == "__main__":
    main()
