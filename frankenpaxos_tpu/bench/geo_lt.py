"""paxgeo A/B: zone-local commits, steal latency, geo-layer overhead.

One artifact (``bench_results/geo_lt.json``), four questions, three
CI-gated clauses (the geo-smoke job):

  1. **Is the common case zone-local?** 3 regions x 3-acceptor rows
     under the GeoTopology latency matrix; per-zone clients drive
     objects HOMED in their zone. GATE: home-zone commit p50 <
     0.25 x the cross-region RTT. A ``static_single_leader`` baseline
     arm (every group homed in zone 0, the pre-paxgeo deployment
     shape) shows what remote zones pay without per-object leaders:
     >= 1 WAN RTT per commit.

  2. **What does moving an object cost?** Traffic migrates zones, the
     new zone steals the group. GATE: steal latency (Phase1 start ->
     epoch active + tail recovered) <= 3 x one WAN RTT; post-steal
     traffic is zone-local again.

  3. **What does the geo layer cost when distance is free?** The
     flat-topology arm (every link 0ms): the SAME protocol over
     GeoSimTransport vs plain SimTransport, alternating-rep wall
     clock. GATE: median per-command ratio within noise (>= 0.8x).
     A plain-multipaxos reference arm (per-message path, same
     delivery mode) is recorded alongside for scale.

  4. **Scenario extras (recorded, ungated):** zone outage -> WAL
     relaunch -> steal repair latency, and Zipf-skewed hot objects
     re-homed to where their traffic originates.

All latency arms run on VIRTUAL time (deterministic per seed): the
latencies are exact simulated durations, so gates are sharp instead
of host-noise-bound. Usage::

    python -m frankenpaxos_tpu.bench.geo_lt --out bench_results/geo_lt.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import time

from frankenpaxos_tpu.geo import GeoTopology
from frankenpaxos_tpu.protocols.wpaxos.messages import Steal


def _topology(seed: int = 0, flat: bool = False) -> GeoTopology:
    if flat:
        return GeoTopology({"r0": ["zone-0"], "r1": ["zone-1"],
                            "r2": ["zone-2"]},
                           intra_zone_s=0.0, intra_region_s=0.0,
                           cross_region_s=0.0, jitter=0.0, seed=seed)
    return GeoTopology({"r0": ["zone-0"], "r1": ["zone-1"],
                        "r2": ["zone-2"]}, seed=seed)


def _make(topology=None, num_groups: int = 6, num_clients: int = 3,
          initial_home=None, seed: int = 0):
    from frankenpaxos_tpu.protocols.wpaxos import WPaxosConfig  # noqa: F401
    from tests.protocols.wpaxos_harness import make_wpaxos

    sim = make_wpaxos(num_zones=3, row_width=3,
                      num_groups=num_groups, num_clients=num_clients,
                      topology=topology, seed=seed)
    if initial_home is not None:
        import dataclasses

        config = dataclasses.replace(sim.config,
                                     initial_home=tuple(initial_home))
        for actor in (sim.leaders + sim.acceptors + sim.replicas
                      + sim.clients):
            actor.config = config
        for leader in sim.leaders:
            from frankenpaxos_tpu.geo import (
                GeoQuorumTracker,
                ObjectEpochStore,
            )

            leader.epochs = ObjectEpochStore(config.num_groups,
                                             config.initial_home)
            leader.trackers = [
                GeoQuorumTracker(leader.epochs, g, leader.grid)
                for g in range(config.num_groups)]
        for acceptor in sim.acceptors:
            from frankenpaxos_tpu.geo import ObjectEpochStore

            acceptor.epochs = ObjectEpochStore(config.num_groups,
                                               config.initial_home)
        for client in sim.clients:
            client.routing = {g: (home, home) for g, home
                              in enumerate(config.initial_home)}
        sim.config = config
    return sim


def _keys_for_zone(config, zone: int, n: int) -> list:
    keys, i = [], 0
    while len(keys) < n:
        key = b"obj-%d" % i
        group = config.group_of_key(key)
        if config.initial_home[group] == zone:
            keys.append(key)
        i += 1
    return keys


def _write(sim, client: int, key: bytes, payload: bytes) -> float:
    """One closed-loop write, settled on virtual time; returns the
    virtual commit latency."""
    from tests.protocols.wpaxos_harness import settle

    done: list = []
    sim.clients[client].write(0, payload, done.append, key=key)
    settle(sim, lambda: bool(done), max_waves=400)
    return sim.clients[client].latencies[-1][2]


def _percentiles(xs) -> dict:
    xs = sorted(xs)
    if not xs:
        return {}
    pick = lambda q: xs[min(len(xs) - 1, int(q * len(xs)))]  # noqa: E731
    return {"p50": pick(0.5), "p90": pick(0.9), "p99": pick(0.99),
            "mean": statistics.fmean(xs), "n": len(xs)}


def home_zone_arm(writes: int, seed: int = 0) -> dict:
    """Per-zone clients drive objects homed in their own zone."""
    topo = _topology(seed)
    sim = _make(topology=topo, seed=seed)
    per_zone = {}
    counter = 0
    for zone in range(3):
        key = _keys_for_zone(sim.config, zone, 1)[0]
        lats = []
        for n in range(writes):
            lat = _write(sim, zone, key, b"hz-%d" % counter)
            counter += 1
            if n > 0:  # first write pays the bootstrap steal
                lats.append(lat)
        per_zone[f"zone-{zone}"] = _percentiles(lats)
    p50s = [v["p50"] for v in per_zone.values()]
    return {"arm": "wpaxos_home_zone", "per_zone": per_zone,
            "wan_rtt_s": topo.wan_rtt(),
            "home_p50_s": max(p50s),
            "home_p50_over_wan_rtt": max(p50s) / topo.wan_rtt()}


def static_single_leader_arm(writes: int, seed: int = 0) -> dict:
    """The baseline: every group homed in zone 0 and never stolen --
    remote zones pay the WAN for every commit."""
    topo = _topology(seed)
    sim = _make(topology=topo, initial_home=[0] * 6, seed=seed)
    per_zone = {}
    counter = 0
    for zone in range(3):
        key = b"obj-0"
        lats = []
        for n in range(writes):
            lat = _write(sim, zone, key, b"sl-%d" % counter)
            counter += 1
            if n > 0:
                lats.append(lat)
        per_zone[f"zone-{zone}"] = _percentiles(lats)
    remote = [per_zone["zone-1"]["p50"], per_zone["zone-2"]["p50"]]
    return {"arm": "static_single_leader", "per_zone": per_zone,
            "wan_rtt_s": topo.wan_rtt(),
            "remote_p50_s": min(remote),
            "remote_p50_over_wan_rtt": min(remote) / topo.wan_rtt()}


def steal_arm(writes: int, seed: int = 0) -> dict:
    """Traffic migrates from the home zone to a remote zone; the
    remote zone steals the object group."""
    topo = _topology(seed)
    sim = _make(topology=topo, seed=seed)
    key = _keys_for_zone(sim.config, 0, 1)[0]
    group = sim.config.group_of_key(key)
    counter = 0
    for _ in range(max(2, writes // 2)):  # steady home traffic
        _write(sim, 0, key, b"st-%d" % counter)
        counter += 1
    # Traffic migrates: zone 1 now drives the object, paying WAN.
    before = [
        _write(sim, 1, key, b"st-%d" % (counter + i))
        for i in range(max(2, writes // 2))]
    counter += max(2, writes // 2)
    from tests.protocols.wpaxos_harness import settle

    thief = sim.leaders[1]
    n_events = len(thief.steal_events)
    thief.receive("bench-admin", Steal(group))
    settle(sim, lambda: group in thief.active, max_waves=400)
    settle(sim, lambda: len(thief.steal_events) > n_events,
           max_waves=400)
    event = thief.steal_events[-1]
    after = []
    for i in range(writes):
        after.append(_write(sim, 1, key, b"st-%d" % (counter + i)))
    steal_latency = event["first_commit_s"] - event["started_s"]
    return {
        "arm": "steal_migration",
        "wan_rtt_s": topo.wan_rtt(),
        "steal_latency_s": steal_latency,
        "steal_latency_over_wan_rtt": steal_latency / topo.wan_rtt(),
        "epoch_activation_s": event["active_s"] - event["started_s"],
        "pre_steal_remote": _percentiles(before),
        "post_steal_local": _percentiles(after[1:] or after),
    }


def zone_outage_arm(dwell_s: float = 2.0, seed: int = 0) -> dict:
    """Kill zone 0 outright (leader + row + replica), relaunch its
    acceptors from WAL after ``dwell_s`` of virtual downtime, and
    measure kill -> first post-outage commit for a zone-0-homed
    group (the steal completes only once f+1 of the old row are
    back: the f_z = 0 tradeoff, docs/GEO.md)."""
    from tests.protocols.wpaxos_harness import (
        crash_zone,
        make_wpaxos,
        restart_zone,
        settle,
    )

    topo = _topology(seed)
    sim = make_wpaxos(num_zones=3, row_width=3, num_groups=6,
                      num_clients=3, topology=topo, wal=True,
                      seed=seed)
    key = _keys_for_zone(sim.config, 0, 1)[0]
    group = sim.config.group_of_key(key)
    counter = 0
    for _ in range(4):
        _write(sim, 0, key, b"zo-%d" % counter)
        counter += 1
    t_kill = sim.transport.now
    crash_zone(sim, 0)
    # A remote client keeps trying (its failover budget will ask
    # zone 1 to steal; the steal blocks on the dead row).
    done: list = []
    sim.clients[1].write(0, b"zo-%d" % counter, done.append, key=key)
    counter += 1
    sim.transport.run_for(dwell_s, max_steps=200_000)
    restart_zone(sim, 0)
    settle(sim, lambda: bool(done), max_waves=800)
    t_recovered = sim.transport.now
    return {
        "arm": "zone_outage",
        "wan_rtt_s": topo.wan_rtt(),
        "downtime_dwell_s": dwell_s,
        "kill_to_first_commit_s": t_recovered - t_kill,
        "repair_after_relaunch_s":
            (t_recovered - t_kill) - dwell_s,
        "stolen_to_zone": next(
            (sim.leaders[z].zone for z in range(3)
             if group in sim.leaders[z].active), None),
    }


def hot_object_arm(writes: int, seed: int = 0) -> dict:
    """Zipf-skewed keys, traffic concentrated in one remote zone;
    adaptive placement steals the hot groups to where the traffic
    is."""
    import random as _random

    topo = _topology(seed)
    sim = _make(topology=topo, num_groups=6, seed=seed)
    rng = _random.Random(seed + 1)
    # Zipf-ish skew over 32 objects (rank-weighted without scipy).
    objects = [b"hot-%d" % i for i in range(32)]
    weights = [1.0 / (rank + 1) for rank in range(len(objects))]
    counter = 0

    def run_phase(n):
        nonlocal counter
        lats = []
        for _ in range(n):
            key = rng.choices(objects, weights=weights)[0]
            lats.append(_write(sim, 1, key, b"ho-%d" % counter))
            counter += 1
        return lats

    before = run_phase(writes)
    # Placement: steal every group whose traffic originated in
    # zone 1 (all of it here) -- the scenario driver's adapt step.
    from tests.protocols.wpaxos_harness import settle

    hot_groups = {sim.config.group_of_key(key) for key in objects}
    for group in sorted(hot_groups):
        if group in sim.leaders[1].active:
            continue
        sim.leaders[1].receive("bench-admin", Steal(group))
        settle(sim, lambda g=group: g in sim.leaders[1].active,
               max_waves=400)
    after = run_phase(writes)
    return {
        "arm": "hot_objects_zipf",
        "wan_rtt_s": topo.wan_rtt(),
        "groups_rehomed": len(hot_groups),
        "before_adapt": _percentiles(before),
        "after_adapt": _percentiles(after),
        "speedup_p50": (_percentiles(before)["p50"]
                        / max(_percentiles(after)["p50"], 1e-12)),
    }


# --- the flat-topology overhead arm -----------------------------------------


class _FlatDriver:
    """One live arm of the flat A/B: a wpaxos cluster with a counter,
    driven in chunks so arms alternate inside one noise window."""

    def __init__(self, kind: str, seed: int):
        self.kind = kind
        self.n = 0
        if kind == "multipaxos":
            from tests.protocols.multipaxos_harness import (
                make_multipaxos,
            )

            self.sim = make_multipaxos(f=1, seed=seed)
            return
        from tests.protocols.wpaxos_harness import make_wpaxos

        self.topology = (_topology(seed, flat=True)
                         if kind == "geo" else None)
        self.sim = make_wpaxos(num_zones=3, row_width=3, num_groups=4,
                               topology=self.topology, seed=seed)
        for p in range(4):  # bootstrap steals outside timed chunks
            self.sim.clients[0].write(p, b"warm%d" % p, key=b"k%d" % p)
        self._pump()

    def _pump(self) -> None:
        # Flat links put every arrival at the CURRENT instant, so
        # run_until(now) delivers in same-timestamp waves with one
        # drain per touched actor -- the same drain batching as
        # deliver_all_coalesced on the plain arm (an A/B of the
        # transport layer, not of two delivery modes).
        if self.kind == "multipaxos":
            self.sim.transport.deliver_all()
        elif self.topology is not None:
            self.sim.transport.run_until(self.sim.transport.now,
                                         max_steps=100_000)
        else:
            self.sim.transport.deliver_all_coalesced(max_steps=100_000)

    def chunk(self, commands: int) -> float:
        """Run ``commands`` closed-loop writes; return elapsed
        seconds."""
        got: list = []
        t0 = time.perf_counter()
        for _ in range(commands):
            n = self.n
            self.n += 1
            if self.kind == "multipaxos":
                self.sim.clients[0].write(n % 4, b"w%d" % n,
                                          got.append)
            else:
                self.sim.clients[0].write(n % 4, b"w%d" % n,
                                          got.append,
                                          key=b"k%d" % (n % 4))
            self._pump()
        elapsed = time.perf_counter() - t0
        assert len(got) == commands
        return elapsed


def flat_arm(commands: int, reps: int, seed: int = 0,
             chunk: int = 25) -> dict:
    """The overload_lt A/B discipline (docs/BENCH_HISTORY.md): keep
    all three arms' sims ALIVE, alternate them in small chunks with
    GC disabled (every noise window is shared), ratio summed per-arm
    times, gate on the median over fresh-sim reps -- whole-rep
    timing on a busy host spreads +-50%, alternated chunks land
    within a few percent."""
    import gc

    ratios, mp_ratios = [], []
    for rep in range(reps):
        drivers = {kind: _FlatDriver(kind, seed + rep)
                   for kind in ("geo", "plain", "multipaxos")}
        totals = {kind: 0.0 for kind in drivers}
        gc.disable()
        try:
            done = 0
            while done < commands:
                n = min(chunk, commands - done)
                for kind, driver in drivers.items():
                    totals[kind] += driver.chunk(n)
                done += n
        finally:
            gc.enable()
            gc.collect()
        ratios.append(totals["plain"] / totals["geo"])
        mp_ratios.append(totals["multipaxos"] / totals["geo"])
    return {
        "arm": "flat_topology",
        "commands_per_rep": commands,
        "chunk": chunk,
        "reps": reps,
        # >1 means the geo layer is FASTER than plain SimTransport;
        # the gate only demands it stays within noise (>= 0.8).
        "geo_over_plain_ratio_median": statistics.median(ratios),
        "geo_over_plain_ratios": ratios,
        # Scale reference: the per-message multipaxos sim driving the
        # same closed-loop count (different protocol; recorded, and
        # loosely gated >= 0.25x to catch pathological regressions).
        "geo_over_multipaxos_ratio_median":
            statistics.median(mp_ratios),
        "geo_over_multipaxos_ratios": mp_ratios,
    }


# --- gates + main -----------------------------------------------------------


def evaluate_gates(result: dict) -> dict:
    home = result["home_zone"]
    steal = result["steal"]
    flat = result["flat"]
    gates = {
        "home_p50_below_quarter_wan_rtt": {
            "value": home["home_p50_over_wan_rtt"],
            "threshold": 0.25,
            "passed": home["home_p50_over_wan_rtt"] < 0.25,
        },
        "steal_latency_within_3_wan_rtt": {
            "value": steal["steal_latency_over_wan_rtt"],
            "threshold": 3.0,
            "passed": steal["steal_latency_over_wan_rtt"] <= 3.0,
        },
        # The acceptance clause: with every link at zero, the whole
        # geo subsystem (topology + virtual clock + wpaxos) drives
        # the same closed-loop work at plain multipaxos's pace.
        "flat_vs_multipaxos_at_noise_floor": {
            "value": flat["geo_over_multipaxos_ratio_median"],
            "threshold": 0.8,
            "passed":
                flat["geo_over_multipaxos_ratio_median"] >= 0.8,
        },
        # Diagnostic bound on the geo TRANSPORT layer itself (same
        # protocol over GeoSimTransport vs plain SimTransport): the
        # virtual clock's heap bookkeeping costs a bounded fraction.
        "flat_geo_layer_overhead_bounded": {
            "value": flat["geo_over_plain_ratio_median"],
            "threshold": 0.6,
            "passed": flat["geo_over_plain_ratio_median"] >= 0.6,
        },
    }
    gates["all_passed"] = all(
        g["passed"] for g in gates.values() if isinstance(g, dict))
    return gates


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--writes", type=int, default=40)
    parser.add_argument("--flat_commands", type=int, default=300)
    parser.add_argument("--flat_reps", type=int, default=5)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--smoke", action="store_true",
                        help="reduced counts for the geo-smoke CI job")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)
    if args.smoke:
        args.writes = min(args.writes, 12)
        args.flat_commands = min(args.flat_commands, 120)
        args.flat_reps = min(args.flat_reps, 3)

    t0 = time.time()
    result = {
        "benchmark": "geo_lt",
        "topology": {
            "regions": 3, "zones": 3, "acceptors_per_zone": 3,
            "intra_zone_rtt_s": 2 * 0.0005,
            "intra_region_rtt_s": 2 * 0.004,
            "wan_rtt_s": 2 * 0.040,
        },
        "home_zone": home_zone_arm(args.writes, args.seed),
        "static_single_leader":
            static_single_leader_arm(args.writes, args.seed),
        "steal": steal_arm(args.writes, args.seed),
        "zone_outage": zone_outage_arm(seed=args.seed),
        "hot_objects": hot_object_arm(args.writes, args.seed),
        "flat": flat_arm(args.flat_commands, args.flat_reps,
                         args.seed),
    }
    result["gates"] = evaluate_gates(result)
    result["wpaxos_vs_static_speedup_p50"] = (
        result["static_single_leader"]["remote_p50_s"]
        / result["home_zone"]["home_p50_s"])
    result["seconds"] = round(time.time() - t0, 1)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    raise SystemExit(0 if main()["gates"]["all_passed"] else 1)
