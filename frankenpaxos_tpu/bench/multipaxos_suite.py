"""MultiPaxos deployment benchmark: every role its own OS process.

The analog of benchmarks/multipaxos/multipaxos.py: compute a placement
(ports on localhost; multipaxos.py:199-246), write the cluster config,
launch every role via the CLI over real TCP (multipaxos.py:311-577),
drive closed-loop clients, and report the reference-compatible stats.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from frankenpaxos_tpu.bench.harness import (
    BenchmarkDirectory,
    free_port,
    latency_throughput_stats,
)
from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport
from frankenpaxos_tpu.runtime.serializer import PickleSerializer
from frankenpaxos_tpu.statemachine import SetRequest


@dataclasses.dataclass(frozen=True)
class MultiPaxosInput:
    """(multipaxos.py:33-96)."""

    f: int = 1
    num_acceptor_groups: int = 1
    num_clients: int = 2
    duration_s: float = 2.0
    quorum_backend: str = "dict"
    state_machine: str = "KeyValueStore"


def placement(input: MultiPaxosInput) -> dict:
    def addrs(n):
        return [["127.0.0.1", free_port()] for _ in range(n)]

    f = input.f
    return {
        "f": f,
        "batchers": [],
        "read_batchers": [],
        "leaders": addrs(f + 1),
        "leader_elections": addrs(f + 1),
        "proxy_leaders": addrs(f + 1),
        "acceptors": [addrs(2 * f + 1)
                      for _ in range(input.num_acceptor_groups)],
        "replicas": addrs(f + 1),
        "proxy_replicas": [],
    }


def run_benchmark(bench: BenchmarkDirectory,
                  input: MultiPaxosInput) -> dict:
    from frankenpaxos_tpu.bench.deploy_suite import launch_roles
    from frankenpaxos_tpu.deploy import get_protocol
    from frankenpaxos_tpu.protocols.multipaxos import Client, ClientOptions

    config_raw = placement(input)
    config_path = bench.write_json("config.json", config_raw)
    config = get_protocol("multipaxos").load_config(config_raw)
    launch_roles(bench, "multipaxos", config_path, config,
                 state_machine=input.state_machine,
                 overrides={"quorum_backend": input.quorum_backend})
    serializer = PickleSerializer()

    # Explicit leader-ready probe: a warmup write with a short resend
    # period retries until leader 0 has completed Phase 1 and can commit
    # it. Only then does the measured run start (replaces the old
    # sleep-and-hope, which raced under load).
    probe_logger = FakeLogger(LogLevel.FATAL)
    probe_transport = TcpTransport(("127.0.0.1", free_port()), probe_logger)
    probe_transport.start()
    probe = Client(probe_transport.listen_address, probe_transport,
                   probe_logger, config,
                   ClientOptions(resend_client_request_period_s=0.25),
                   seed=0xBEEF)
    ready = threading.Event()
    probe_transport.loop.call_soon_threadsafe(
        probe.write, 0, serializer.to_bytes(SetRequest((("warmup", "1"),))),
        lambda _: ready.set())
    ok = ready.wait(timeout=60)
    probe_transport.stop()
    if not ok:
        bench.cleanup()
        raise RuntimeError("leader never committed the warmup write")

    # Closed-loop clients (in-process, real TCP).
    latencies: list[float] = []
    starts: list[float] = []
    lock = threading.Lock()
    stop_at = time.time() + input.duration_s

    def run_client(i: int) -> None:
        logger = FakeLogger(LogLevel.FATAL)
        transport = TcpTransport(("127.0.0.1", free_port()), logger)
        transport.start()
        client = Client(transport.listen_address, transport, logger,
                        config, ClientOptions(), seed=i)
        try:
            k = 0
            while time.time() < stop_at:
                done = threading.Event()
                t0 = time.perf_counter()
                wall0 = time.time()
                transport.loop.call_soon_threadsafe(
                    client.write, 0,
                    serializer.to_bytes(
                        SetRequest(((f"k{i}", str(k)),))),
                    lambda _: done.set())
                if not done.wait(timeout=10):
                    break
                with lock:
                    latencies.append(time.perf_counter() - t0)
                    starts.append(wall0)
                k += 1
        finally:
            transport.stop()

    threads = [threading.Thread(target=run_client, args=(i,))
               for i in range(input.num_clients)]
    start = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.time() - start

    bench.cleanup()
    stats = latency_throughput_stats(latencies, elapsed, starts_s=starts)
    stats["input"] = dataclasses.asdict(input)
    bench.write_json("results.json", stats)
    return stats
