"""MultiPaxos deployment benchmark: every role its own OS process.

The analog of benchmarks/multipaxos/multipaxos.py: compute a placement
(ports on localhost; multipaxos.py:199-246), write the cluster config,
launch every role via the CLI over real TCP (multipaxos.py:311-577),
drive closed-loop clients, and report the reference-compatible stats.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

from frankenpaxos_tpu.bench.harness import (
    BenchmarkDirectory,
    free_port,
    latency_throughput_stats,
)
from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
from frankenpaxos_tpu.runtime.serializer import PickleSerializer
from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport
from frankenpaxos_tpu.statemachine import SetRequest


@dataclasses.dataclass(frozen=True)
class MultiPaxosInput:
    """(multipaxos.py:33-96)."""

    f: int = 1
    num_acceptor_groups: int = 1
    num_replicas: int = 0  # 0 -> f + 1
    # Batchers between clients and leaders (Batcher.scala:60-90): the
    # whole batch shares ONE log slot -- the eurosys fig4 ~4x lever.
    # 0 disables (clients talk to leaders directly).
    num_batchers: int = 0
    batch_size: int = 1
    batch_flush_period_s: float = 0.05  # partial-batch flush
    num_clients: int = 2
    duration_s: float = 2.0
    quorum_backend: str = "dict"
    # Pipelined device drains for the tpu backend (hide the device-link
    # RTT behind the event loop; see ProxyLeaderOptions.tpu_pipelined).
    tpu_pipelined: bool = False
    # The drain-granular run pipeline (ClientRequestArray -> Phase2aRun
    # -> Phase2bRange -> ChosenRun -> ClientReplyArray): clients
    # coalesce each event-loop pass's writes into one array and every
    # downstream hop works in contiguous slot runs.
    coalesced: bool = False
    state_machine: str = "KeyValueStore"
    # A ReadWriteWorkload (bench/workload.py); None -> the legacy
    # write-only SetRequest loop.
    workload: object = None
    # "linearizable" (quorum reads), "sequential", or "eventual"
    # (Client.scala:851-933, :697+, :739+).
    read_consistency: str = "linearizable"
    # > 0: drive load from this many separate client OS processes
    # (bench/client_main.py, the reference's ClientMain shape), each
    # running ``num_clients`` closed loops. 0: in-process threads.
    client_procs: int = 0
    # Expose per-role /metrics endpoints and record them in the results
    # (benchmarks/prometheus.py semantics).
    prometheus: bool = False
    # Coupled baseline: all roles colocated in one process
    # (SuperNode.scala:22+). Compartmentalized (False) vs coupled (True)
    # is the reference's headline 4-8x shape (BASELINE.md).
    supernode: bool = False
    # Run every role under cProfile (bench/role_cost.py consumes the
    # dumps; the perf_util.py flamegraph-wrap analog).
    profiled: bool = False
    # Durability root (wal/): acceptors/replicas log to
    # <wal_dir>/<label> with one group-commit fsync per drain and
    # recover on relaunch. None = the reference's in-memory behavior.
    wal_dir: "str | None" = None


def placement(input: MultiPaxosInput) -> dict:
    def addrs(n):
        return [["127.0.0.1", free_port()] for _ in range(n)]

    f = input.f
    return {
        "f": f,
        "batchers": addrs(input.num_batchers),
        "read_batchers": [],
        "leaders": addrs(f + 1),
        "leader_elections": addrs(f + 1),
        "proxy_leaders": addrs(f + 1),
        "acceptors": [addrs(2 * f + 1)
                      for _ in range(input.num_acceptor_groups)],
        "replicas": addrs(max(input.num_replicas, f + 1)),
        "proxy_replicas": [],
    }


def run_benchmark(bench: BenchmarkDirectory,
                  input: MultiPaxosInput) -> dict:
    # Launch + leader warmup, with ONE retry on a fresh placement: a
    # lost startup race (a free_port() stolen between allocation and
    # bind, a role losing the scheduler lottery on a loaded 1-CPU
    # host) is a deployment artifact, not a benchmark result, and a
    # retry runs with entirely fresh ports. The per-role readiness
    # itself is the launch_roles connect-back handshake.
    for attempt in (1, 2):
        try:
            config_path, config = _launch_and_warm(bench, input)
            break
        except RuntimeError as e:
            if attempt == 2:
                raise
            # Keep the failed attempt diagnosable: say what happened,
            # and move its role logs aside before the relaunch reopens
            # the same {label}.log paths with mode "w" (which would
            # destroy the attempt-1 evidence).
            print(f"deployment startup attempt {attempt} failed "
                  f"({e}); retrying with fresh ports")
            import glob

            for log in glob.glob(os.path.join(bench.path, "*.log")):
                os.replace(log, f"{log}.attempt{attempt}")

    if input.client_procs > 0:
        return _run_with_client_procs(bench, input, config_path)

    return _run_with_client_threads(bench, input, config)


def _launch_and_warm(bench: BenchmarkDirectory,
                     input: MultiPaxosInput) -> tuple:
    """One deployment startup attempt: launch every role (handshake
    readiness) and commit a warmup write through leader 0. Raises
    RuntimeError -- with the roles already cleaned up -- on failure."""
    from frankenpaxos_tpu.bench.deploy_suite import launch_roles
    from frankenpaxos_tpu.deploy import get_protocol
    from frankenpaxos_tpu.protocols.multipaxos import Client, ClientOptions

    config_raw = placement(input)
    config_path = bench.write_json("config.json", config_raw)
    config = get_protocol("multipaxos").load_config(config_raw)
    overrides = {"quorum_backend": input.quorum_backend}
    if input.tpu_pipelined:
        overrides["tpu_pipelined"] = "true"
    if input.coalesced:
        overrides["coalesce_writes"] = "true"
    if input.num_batchers:
        overrides["batch_size"] = str(input.batch_size)
        overrides["flush_period_s"] = str(input.batch_flush_period_s)
    launch_roles(bench, "multipaxos", config_path, config,
                 state_machine=input.state_machine,
                 overrides=overrides,
                 prometheus=input.prometheus, supernode=input.supernode,
                 profiled=input.profiled, wal_dir=input.wal_dir,
                 # tpu role startup pre-compiles kernels over the
                 # device link, which takes minutes under contention.
                 ready_timeout_s=(120.0 if input.quorum_backend == "dict"
                                  else 300.0))

    # Explicit leader-ready probe: a warmup write with a short resend
    # period retries until leader 0 has completed Phase 1 and can commit
    # it. Only then does the measured run start.
    serializer = PickleSerializer()
    probe_logger = FakeLogger(LogLevel.FATAL)
    probe_transport = TcpTransport(("127.0.0.1", free_port()), probe_logger)
    probe_transport.start()
    # A gentle resend for the tpu backend: rapid duplicate requests
    # during its first (compile-paying) drains each get proposed to a
    # fresh slot, snowballing the very backlog the probe waits on.
    probe_resend_s = 0.25 if input.quorum_backend == "dict" else 2.0
    probe = Client(probe_transport.listen_address, probe_transport,
                   probe_logger, config,
                   ClientOptions(
                       resend_client_request_period_s=probe_resend_s),
                   seed=0xBEEF)
    ready = threading.Event()
    probe_transport.loop.call_soon_threadsafe(
        probe.write, 0, serializer.to_bytes(SetRequest((("warmup", "1"),))),
        lambda _: ready.set())
    ok = ready.wait(timeout=60)
    probe_transport.stop()
    if not ok:
        bench.cleanup()
        raise RuntimeError("leader never committed the warmup write")
    return config_path, config


def _run_with_client_threads(bench: BenchmarkDirectory,
                             input: MultiPaxosInput, config) -> dict:
    from frankenpaxos_tpu.protocols.multipaxos import Client, ClientOptions

    serializer = PickleSerializer()

    # Closed-loop clients (in-process, real TCP). Each op comes from the
    # workload: writes go through the Phase2 write path; reads through
    # the configured consistency path (linearizable quorum reads /
    # sequential / eventual, Client.scala:851-933, :697+, :739+).
    import random as _random

    from frankenpaxos_tpu.bench.workload import WRITE

    samples: dict[str, tuple[list, list]] = {
        "read": ([], []), "write": ([], [])}
    lock = threading.Lock()
    stop_at = time.time() + input.duration_s
    from frankenpaxos_tpu.bench.workload import READ_METHODS

    read_method = READ_METHODS[input.read_consistency]

    def run_client(i: int) -> None:
        logger = FakeLogger(LogLevel.FATAL)
        transport = TcpTransport(("127.0.0.1", free_port()), logger)
        transport.start()
        client = Client(transport.listen_address, transport, logger,
                        config,
                        ClientOptions(coalesce_writes=input.coalesced),
                        seed=i)
        rng = _random.Random(1000 + i)
        try:
            k = 0
            while time.time() < stop_at:
                if input.workload is not None:
                    kind, command = input.workload.get(rng)
                else:
                    kind = WRITE
                    command = serializer.to_bytes(
                        SetRequest(((f"k{i}", str(k)),)))
                op = (client.write if kind == WRITE
                      else getattr(client, read_method))
                done = threading.Event()
                t0 = time.perf_counter()
                wall0 = time.time()
                transport.loop.call_soon_threadsafe(
                    op, 0, command, lambda _: done.set())
                if not done.wait(timeout=10):
                    break
                with lock:
                    samples[kind][0].append(time.perf_counter() - t0)
                    samples[kind][1].append(wall0)
                k += 1
        finally:
            transport.stop()

    threads = [threading.Thread(target=run_client, args=(i,))
               for i in range(input.num_clients)]
    start = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.time() - start

    role_metrics = _scrape_role_metrics(bench, input)
    role_cpu = bench.role_cpu_seconds()
    bench.cleanup()
    return _write_stats(bench, input, samples, elapsed, role_metrics,
                        input.workload, role_cpu)


def _run_with_client_procs(bench: BenchmarkDirectory,
                           input: MultiPaxosInput,
                           config_path: str) -> dict:
    """Drive load from separate client OS processes and aggregate their
    CSVs (the reference's ClientMain + parse-client-data shape,
    multipaxos.py:632-785)."""
    import json
    import sys

    from frankenpaxos_tpu.bench.deploy_suite import role_process_env
    from frankenpaxos_tpu.bench.harness import LocalHost
    from frankenpaxos_tpu.bench.workload import (
        StringWorkload,
        UniformReadWriteWorkload,
        WriteOnlyWorkload,
        workload_to_dict,
    )

    # Default workload must emit commands the deployed state machine can
    # parse: KV stores take pickled Get/SetRequests, the string family
    # (AppendLog/Noop/Register) takes raw bytes.
    workload = input.workload or (
        UniformReadWriteWorkload(num_keys=8, read_fraction=0.0)
        if input.state_machine == "KeyValueStore"
        else WriteOnlyWorkload(StringWorkload()))
    host = LocalHost()
    env = role_process_env()
    procs = []
    for i in range(input.client_procs):
        out_csv = bench.abspath(f"client_{i}_data.csv")
        procs.append((out_csv, bench.popen(host, f"client_{i}", [
            sys.executable, "-m", "frankenpaxos_tpu.bench.client_main",
            "--config", config_path,
            "--workload", json.dumps(workload_to_dict(workload)),
            "--num_clients", str(input.num_clients),
            "--duration", str(input.duration_s),
            "--read_consistency", input.read_consistency,
            "--seed", str(i), "--out", out_csv]
            + (["--client_options",
                json.dumps({"coalesce_writes": "true"})]
               if input.coalesced else []), env=env)))
    try:
        deadline = input.duration_s + 90
        for _, proc in procs:
            code = proc.wait(timeout=deadline)
            if code != 0:
                raise RuntimeError(
                    f"client process exited with code {code}; see "
                    f"{bench.path}")

        samples: dict[str, tuple[list, list]] = {
            "read": ([], []), "write": ([], [])}
        for out_csv, _ in procs:
            with open(out_csv) as f:
                next(f)  # header
                for line in f:
                    kind, start, latency = line.strip().split(",")
                    # Beyond read/write: "giveup" (RETRY_EXHAUSTED) and
                    # "thinned" rows are kept out of the ack stats.
                    lat, starts = samples.setdefault(kind, ([], []))
                    lat.append(float(latency))
                    starts.append(float(start))
        role_metrics = _scrape_role_metrics(bench, input)
        role_cpu = bench.role_cpu_seconds()
    finally:
        bench.cleanup()
    return _write_stats(bench, input, samples, input.duration_s,
                        role_metrics, workload, role_cpu)


def _scrape_role_metrics(bench: BenchmarkDirectory,
                         input: MultiPaxosInput) -> dict:
    """Scrape every role's /metrics endpoint (framework metrics only);
    must run before bench.cleanup() kills the roles."""
    if not input.prometheus:
        return {}
    from frankenpaxos_tpu.bench.metrics import scrape

    role_metrics = {}
    for label, port in bench.prometheus_ports.items():
        try:
            role_metrics[label] = {
                k: v for k, v in scrape(port).items()
                if k.startswith("multipaxos_")}
        except OSError:
            role_metrics[label] = {}
    return role_metrics


def _write_stats(bench: BenchmarkDirectory, input: MultiPaxosInput,
                 samples: dict, duration_s: float, role_metrics: dict,
                 workload, role_cpu: "dict | None" = None) -> dict:
    """Aggregate per-kind samples into the reference-shaped results
    (benchmark.py:308-341), tagged with the input and role metrics."""
    from frankenpaxos_tpu.bench.workload import workload_to_dict

    all_lat = samples["read"][0] + samples["write"][0]
    all_starts = samples["read"][1] + samples["write"][1]
    stats = latency_throughput_stats(all_lat, duration_s,
                                     starts_s=all_starts)
    for kind in ("read", "write"):
        lat, starts = samples[kind]
        if lat:
            sub = latency_throughput_stats(lat, duration_s,
                                           starts_s=starts)
            stats.update({f"{kind}.{k}": v for k, v in sub.items()})
    stats["input"] = dataclasses.asdict(input)
    if workload is not None:
        stats["input"]["workload"] = workload_to_dict(workload)
    if role_metrics:
        stats["role_metrics"] = role_metrics
    if role_cpu:
        stats["role_cpu_seconds"] = role_cpu
    bench.write_json("results.json", stats)
    return stats
