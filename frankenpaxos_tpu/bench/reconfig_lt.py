"""paxepoch A/B: steady-state epoch-tagging overhead + handover window.

Two questions, one artifact (``bench_results/reconfig_lt.json``):

  1. **What does reconfigurABILITY cost when nothing reconfigures?**
     The multipaxos_lt paired-sim methodology: per in-flight width,
     interleaved A/B of the full coalesced pipeline with arms
     ``plain`` (the pre-epoch hot path: untagged Phase2aRuns, the
     stock quorum tracker) vs ``epoch-tagged``
     (``LeaderOptions.epoch_tag_runs`` + the address-keyed
     epoch-segmented tracker from construction -- the steady state of
     a cluster that has EVER reconfigured); median of paired ratios
     over rotating-order reps, pooled across independent subprocess
     batches.

  2. **What does a live reconfiguration cost when it happens?** Drive
     closed-loop coalesced load, fire ``Reconfigure`` (swap one
     member for a fresh replacement) mid-run, and record the handover
     window: proposals buffered during the commit gate, delivery
     waves from Reconfigure receipt to activation, and the wall-clock
     window plus the per-write latency spike around the event.

Usage::

    python -m frankenpaxos_tpu.bench.reconfig_lt \
        --out bench_results/reconfig_lt.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _drive_waves(sim, inflight: int, waves: int, tag: bytes,
                 results: list) -> None:
    """Closed-loop waves of coalesced writes at drain granularity
    (the wal_lt driver shape)."""
    for b in range(waves):
        for p in range(inflight):
            sim.clients[0].write(p, b"%s%d.%d" % (tag, b, p),
                                 results.append)
        sim.clients[0].flush_writes()
        sim.transport.deliver_all_coalesced()
        for _ in range(60):
            if not sim.clients[0].states:
                break
            for timer in sim.transport.running_timers():
                if timer.name == "recover" \
                        or timer.name.startswith("resendWrite"):
                    sim.transport.trigger_timer(timer.id)
            sim.transport.deliver_all_coalesced()


def _make(arm: str):
    from tests.protocols.multipaxos_harness import make_multipaxos

    if arm == "plain":
        return make_multipaxos(f=1, coalesced=True)
    return make_multipaxos(f=1, coalesced=True, epoch_tag_runs=True,
                           epoch_quorums=True)


def sim_ab_pipeline(inflights, reps: int = 6, waves: int = 0,
                    warm: int = 2) -> dict:
    """Interleaved paired A/B of epoch-tagged vs plain (multipaxos_lt
    sim_ab methodology)."""
    import gc
    import statistics

    ARMS = ("plain", "epoch-tagged")

    def measure(arm: str, inflight: int, w: int) -> float:
        gc.collect()
        sim = _make(arm)
        results: list = []
        _drive_waves(sim, inflight, warm, b"w", results)
        t0 = time.perf_counter()
        _drive_waves(sim, inflight, w, b"x", results)
        elapsed = time.perf_counter() - t0
        assert len(results) == (warm + w) * inflight, (
            arm, inflight, len(results))
        return w * inflight / elapsed

    table = {}
    for inflight in inflights:
        w = waves or max(8 if inflight >= 1024 else 16, 256 // inflight)
        runs: dict = {arm: [] for arm in ARMS}
        ratios: list = []
        for rep in range(reps):
            rot = list(ARMS[rep % 2:]) + list(ARMS[:rep % 2])
            got = {arm: measure(arm, inflight, w) for arm in rot}
            for arm in ARMS:
                runs[arm].append(got[arm])
            ratios.append(got["epoch-tagged"] / got["plain"])
        table[str(inflight)] = {
            "plain_cmds_per_sec": round(
                statistics.median(runs["plain"]), 1),
            "epoch_tagged_cmds_per_sec": round(
                statistics.median(runs["epoch-tagged"]), 1),
            "tagged_over_plain_ratio": round(
                statistics.median(ratios), 3),
            "ratio_range": [round(min(ratios), 3),
                            round(max(ratios), 3)],
        }
    return table


def sim_handover(inflight: int = 64, reps: int = 5) -> dict:
    """Fire a live reconfiguration under closed-loop load and measure
    the handover window (buffered proposals, waves to activation,
    wall-clock)."""
    import statistics

    from frankenpaxos_tpu.reconfig import Reconfigure
    from tests.protocols.multipaxos_harness import (
        add_replacement_acceptor,
        make_multipaxos,
    )

    rows = []
    for rep in range(reps):
        sim = make_multipaxos(f=1, coalesced=True, wal=True,
                              seed=rep)
        results: list = []
        _drive_waves(sim, inflight, 4, b"w", results)
        group = list(sim.config.acceptor_addresses[0])
        members = tuple(group[:2] + [f"acceptor-0-repl{rep}"])
        add_replacement_acceptor(sim, members,
                                 f"acceptor-0-repl{rep}")
        leader = sim.leaders[0]
        # In-flight load + the reconfiguration in the same breath.
        for p in range(inflight):
            sim.clients[0].write(p, b"h%d" % p, results.append)
        sim.clients[0].flush_writes()
        leader.receive("bench-admin", Reconfigure(members=members))
        t0 = time.perf_counter()
        waves = 0
        buffered = 0
        while leader._epoch_change is not None \
                and not leader._epoch_change.activated:
            # Small steps so the buffered-proposal high-water mark is
            # sampled mid-handover, not only at the quiescent edges.
            sim.transport.deliver_all_coalesced(max_steps=5)
            change = leader._epoch_change
            if change is not None:
                buffered = max(buffered, len(change.pending))
            waves += 1
            if waves > 1000:
                raise AssertionError("handover never activated")
        window_s = time.perf_counter() - t0
        # Settle the handover's in-flight writes to quiescence before
        # the post-handover waves reuse their pseudonyms.
        for _ in range(200):
            if not sim.clients[0].states:
                break
            for timer in sim.transport.running_timers():
                if timer.name == "recover" \
                        or timer.name.startswith("resendWrite"):
                    sim.transport.trigger_timer(timer.id)
            sim.transport.deliver_all_coalesced()
        _drive_waves(sim, inflight, 2, b"z", results)
        assert leader.epochs.multi_epoch
        rows.append({"buffered_proposals": buffered,
                     "waves_to_activation": waves,
                     "handover_wall_s": round(window_s, 6)})
    return {
        "inflight": inflight,
        "reps": rows,
        "handover_wall_s_median": round(statistics.median(
            r["handover_wall_s"] for r in rows), 6),
        "note": ("the handover window is ONE commit round trip: "
                 "proposals buffer from Reconfigure receipt until f+1 "
                 "old-epoch acceptors durably ack the EpochCommit, "
                 "then flush as the new epoch's first runs"),
    }


def deployed_handover(duration_s: float = 8.0) -> dict:
    """A real-TCP handover latency point: closed-loop writes while a
    replacement launches and a Reconfigure fires; the handover window
    surfaces as the per-write latency spike around the event."""
    import tempfile
    import threading

    from frankenpaxos_tpu.bench.chaos import (
        launch_replacement_acceptor,
        reconfigure_acceptors,
        sigkill_role,
    )
    from frankenpaxos_tpu.bench.deploy_suite import launch_roles
    from frankenpaxos_tpu.bench.harness import (
        BenchmarkDirectory,
        free_port,
    )
    from frankenpaxos_tpu.deploy import DeployCtx, get_protocol
    from frankenpaxos_tpu.runtime import FakeLogger, LogLevel
    from frankenpaxos_tpu.runtime.serializer import PickleSerializer
    from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport
    from frankenpaxos_tpu.statemachine import SetRequest

    serializer = PickleSerializer()
    root = tempfile.mkdtemp(prefix="fpx_reconfig_lt_")
    bench = BenchmarkDirectory(os.path.join(root, "bench"))
    protocol = get_protocol("multipaxos")
    raw = protocol.cluster(1, lambda: ["127.0.0.1", free_port()])
    config_path = bench.write_json("config.json", raw)
    config = protocol.load_config(raw)
    overrides = {"resend_phase1as_period_s": "0.5",
                 "recover_log_entry_min_period_s": "0.5",
                 "recover_log_entry_max_period_s": "1.0",
                 "send_chosen_watermark_every_n_entries": "1"}
    launch_roles(bench, "multipaxos", config_path, config,
                 state_machine="KeyValueStore", overrides=overrides,
                 wal_dir=os.path.join(root, "wal"))
    transport = None
    try:
        logger = FakeLogger(LogLevel.FATAL)
        transport = TcpTransport(("127.0.0.1", free_port()), logger)
        transport.start()
        ctx = DeployCtx(config=config, transport=transport,
                        logger=logger,
                        overrides={"resend_client_request_period_s":
                                   "0.5"},
                        seed=7, state_machine="KeyValueStore")
        client = protocol.make_client(ctx, transport.listen_address)
        latencies: list = []
        reconfig_at: list = []

        def write(k: int) -> None:
            done = threading.Event()
            t0 = time.perf_counter()
            transport.loop.call_soon_threadsafe(
                client.write, 0,
                serializer.to_bytes(SetRequest(((f"k{k}", str(k)),))),
                lambda _: done.set())
            assert done.wait(timeout=30), f"write k{k} never acked"
            latencies.append((time.perf_counter(),
                              time.perf_counter() - t0))

        deadline = time.time() + duration_s
        k = 0
        fired = False
        while time.time() < deadline:
            write(k)
            k += 1
            if not fired and k == 25:
                sigkill_role(bench, "acceptor_2")
                members, _ = launch_replacement_acceptor(
                    bench, raw, group=0, member=2,
                    state_machine="KeyValueStore",
                    wal_dir=os.path.join(root, "wal"),
                    overrides=overrides)
                reconfig_at.append(time.perf_counter())
                reconfigure_acceptors(transport,
                                      config.leader_addresses, members)
                fired = True
        pre = [lat for t, lat in latencies[5:24]]
        at = reconfig_at[0] if reconfig_at else 0
        spike = max((lat for t, lat in latencies
                     if at <= t <= at + 3.0), default=None)
        import statistics

        return {
            "writes": k,
            "steady_latency_median_s": round(statistics.median(pre), 6)
            if pre else None,
            "handover_spike_latency_s": round(spike, 6)
            if spike is not None else None,
            "note": ("spike = max write latency within 3s of the "
                     "Reconfigure: the commit round trip plus the "
                     "proposal buffer flush, over real TCP with WAL "
                     "fsyncs"),
        }
    finally:
        if transport is not None:
            transport.stop()
        bench.cleanup()


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sim_inflight", type=str,
                        default="1,16,256,1024")
    parser.add_argument("--sim_repeats", type=int, default=4)
    parser.add_argument("--sim_ab_batches", type=int, default=3)
    parser.add_argument("--skip_deployed", action="store_true")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    from frankenpaxos_tpu.bench.deploy_suite import role_process_env

    import statistics as _stats

    inflights = [int(x) for x in args.sim_inflight.split(",")]
    per_width: dict = {str(i): [] for i in inflights}
    for _batch in range(args.sim_ab_batches):
        ab = subprocess.run(
            [sys.executable, "-c",
             "import json; from frankenpaxos_tpu.bench.reconfig_lt "
             "import sim_ab_pipeline; "
             f"print(json.dumps(sim_ab_pipeline({inflights!r}, "
             f"reps={args.sim_repeats})))"],
            capture_output=True, text=True, env=role_process_env(),
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        if ab.returncode != 0:
            print(f"sim A/B batch failed (rc={ab.returncode}): "
                  f"{ab.stderr[-500:]}", file=sys.stderr)
            continue
        out = json.loads(ab.stdout.strip().splitlines()[-1])
        print(json.dumps({"sim_ab_batch": out}))
        for key, row in out.items():
            per_width[key].append(row)
    sim_ab = {}
    for key, rows in per_width.items():
        if not rows:
            continue
        ratios = [r["tagged_over_plain_ratio"] for r in rows]
        sim_ab[key] = {
            "tagged_over_plain_ratio": round(
                _stats.median(ratios), 3),
            "ratio_range": [min(r["ratio_range"][0] for r in rows),
                            max(r["ratio_range"][1] for r in rows)],
            "plain_cmds_per_sec_med": round(_stats.median(
                r["plain_cmds_per_sec"] for r in rows), 1),
            "epoch_tagged_cmds_per_sec_med": round(_stats.median(
                r["epoch_tagged_cmds_per_sec"] for r in rows), 1),
            "batches": len(rows),
        }

    handover = sim_handover()
    deployed = None
    if not args.skip_deployed:
        deployed = deployed_handover()
        print(json.dumps({"deployed_handover": deployed}))

    result = {
        "benchmark": "reconfig_lt",
        "host_cpus": os.cpu_count(),
        "sim_ab_pipeline": sim_ab,
        "sim_handover": handover,
        "deployed_handover": deployed,
        "sim_ab_methodology": (
            "per-width ratio = median over independent subprocess "
            "batches of each batch's paired-A/B median (the "
            "multipaxos_lt/wal_lt sim_ab methodology); arms are "
            "plain (untagged Phase2aRuns + the stock quorum tracker: "
            "the epoch-frozen hot path) vs epoch-tagged "
            "(EpochPhase2aRun on every proposal + the address-keyed "
            "epoch-segmented tracker from construction: the steady "
            "state of a cluster that has ever reconfigured)"),
        "note": (
            "Single-epoch clusters pay ZERO reconfig overhead by "
            "construction (tagging and the epoch tracker only engage "
            "on the first committed change); this A/B measures the "
            "post-first-reconfiguration steady state. The handover "
            "window is one EpochCommit round trip (proposals buffer "
            "until f+1 old-epoch acceptors durably ack)."),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
