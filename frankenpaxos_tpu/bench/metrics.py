"""Prometheus plumbing for the benchmark harness.

The analog of benchmarks/prometheus.py:10-132: every role process
exposes a prometheus_client ``/metrics`` endpoint
(``--prometheus_port``); the harness generates a Prometheus scrape
config for them (for users running a real Prometheus server + the
Grafana dashboards in ``grafana/``) and, for in-run results, scrapes the
endpoints directly into ``{metric_name{labels}: value}`` dicts -- the
query layer this environment supports without a Prometheus binary.
"""

from __future__ import annotations

import urllib.request

_NAME_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def parse_sample_line(line: str) -> "tuple[str, float] | None":
    """One exposition sample: ``name{labels} value [timestamp]`` ->
    ``(name{labels}, value)``, or None for comments/garbage.

    The old ``line.rpartition(" ")`` shortcut mis-keyed any sample
    whose label VALUES contain spaces (``{msg="hello world"}`` split
    inside the label) and any line carrying a trailing timestamp (the
    timestamp became the value and the real value joined the key). The
    label block is scanned with quote/escape awareness -- ``\\"`` and
    ``\\\\`` inside a quoted value never terminate it -- and the
    remainder splits into value + optional dropped timestamp.
    Histogram/summary series keep their suffixed names
    (``*_bucket{le=...}``, ``*_sum``, ``*_count``) so they stay
    queryable downstream (promdb)."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    i, n = 0, len(line)
    while i < n and line[i] in _NAME_CHARS:
        i += 1
    if i == 0:
        return None
    key_end = i
    if i < n and line[i] == "{":
        j = i + 1
        in_quotes = False
        while j < n:
            c = line[j]
            if in_quotes:
                if c == "\\":
                    j += 1  # escaped char: skip it
                elif c == '"':
                    in_quotes = False
            elif c == '"':
                in_quotes = True
            elif c == "}":
                break
            j += 1
        if j >= n:
            return None  # unterminated label block
        key_end = j + 1
    key = line[:key_end]
    rest = line[key_end:].split()
    if not rest:
        return None
    try:
        # float() accepts the exposition specials +Inf/-Inf/NaN.
        value = float(rest[0])
    except ValueError:
        return None
    # rest[1:], if present, is the millisecond timestamp: dropped (the
    # scraper stamps its own sample time).
    return key, value


def parse_exposition(text: str) -> dict:
    """A whole /metrics payload -> ``{name{labels}: value}``."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        parsed = parse_sample_line(line)
        if parsed is not None:
            out[parsed[0]] = parsed[1]
    return out


def scrape(port: int, host: str = "127.0.0.1",
           timeout_s: float = 5.0) -> dict:
    """Fetch and parse one /metrics endpoint (text exposition format)."""
    with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=timeout_s) as resp:
        text = resp.read().decode()
    return parse_exposition(text)


def scrape_config(targets: "dict[str, int]", host: str = "127.0.0.1",
                  scrape_interval: str = "1s") -> dict:
    """A prometheus.yml dict scraping every role endpoint
    (benchmarks/prometheus.py's generated config shape)."""
    return {
        "global": {"scrape_interval": scrape_interval},
        "scrape_configs": [
            {
                "job_name": label,
                "static_configs": [
                    {"targets": [f"{host}:{port}"]}],
            }
            for label, port in sorted(targets.items())
        ],
    }


def sum_metric(scrapes: "dict[str, dict]", metric: str) -> float:
    """Sum a counter across scraped roles (ignoring label variants)."""
    total = 0.0
    for values in scrapes.values():
        for name, value in values.items():
            if name == metric or name.startswith(metric + "{"):
                total += value
    return total
