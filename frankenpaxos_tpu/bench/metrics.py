"""Prometheus plumbing for the benchmark harness.

The analog of benchmarks/prometheus.py:10-132: every role process
exposes a prometheus_client ``/metrics`` endpoint
(``--prometheus_port``); the harness generates a Prometheus scrape
config for them (for users running a real Prometheus server + the
Grafana dashboards in ``grafana/``) and, for in-run results, scrapes the
endpoints directly into ``{metric_name{labels}: value}`` dicts -- the
query layer this environment supports without a Prometheus binary.
"""

from __future__ import annotations

import urllib.request


def scrape(port: int, host: str = "127.0.0.1",
           timeout_s: float = 5.0) -> dict:
    """Fetch and parse one /metrics endpoint (text exposition format)."""
    with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=timeout_s) as resp:
        text = resp.read().decode()
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def scrape_config(targets: "dict[str, int]", host: str = "127.0.0.1",
                  scrape_interval: str = "1s") -> dict:
    """A prometheus.yml dict scraping every role endpoint
    (benchmarks/prometheus.py's generated config shape)."""
    return {
        "global": {"scrape_interval": scrape_interval},
        "scrape_configs": [
            {
                "job_name": label,
                "static_configs": [
                    {"targets": [f"{host}:{port}"]}],
            }
            for label, port in sorted(targets.items())
        ],
    }


def sum_metric(scrapes: "dict[str, dict]", metric: str) -> float:
    """Sum a counter across scraped roles (ignoring label variants)."""
    total = 0.0
    for values in scrapes.values():
        for name, value in values.items():
            if name == metric or name.startswith(metric + "{"):
                total += value
    return total
