"""Remote (multi-host) deployment: hosts, shells, and remote procs.

The reference deploys benchmarks by ssh'ing role processes onto cluster
machines (benchmarks/host.py:10-37 ``Host``/``RemoteHost``/``Endpoint``,
proc.py:110 ``ParamikoProc``, cluster.py:44 ``Cluster``). Here the same
seam is a pluggable *shell*:

  * :class:`SshShell` -- runs commands on a remote machine through the
    system ``ssh`` client (ControlMaster-friendly; no paramiko
    dependency).
  * :class:`LoopbackShell` -- runs the IDENTICAL command strings through
    a local ``bash -c``. This is the ssh-to-localhost stand-in for
    environments without an sshd (it exercises every line of the
    remote machinery: quoting, env exports, output redirection, pidfile
    tracking, and remote kill).

:class:`RemoteHost` plugs into the same ``popen(args, out_path, env)``
surface as :class:`frankenpaxos_tpu.bench.harness.LocalHost`, so
``BenchmarkDirectory``/``launch_roles`` deploy over it unchanged.

Scope: ``launch_roles`` reads role logs / writes configs at LOCAL
paths, so deploying through a RemoteHost requires those paths to be
visible on the launch target -- ssh-to-localhost (the reference's own
smoke topology, scripts/benchmark_smoke.sh:5-18) or a shared
filesystem (the reference's EC2 setups mount one). Fully disjoint
filesystems would additionally need config/log shipping, which this
seam does not do.

A launched command is wrapped as::

    echo $$ > <pidfile>; (<exports> exec <cmd>) > <out> 2>&1

The wrapper's pid lands in a pidfile scoped to the launch; ``kill()``
terminates the wrapper's children then the wrapper through the shell
(reference ParamikoProc kills via a nonce + pgrep, proc.py:100-150; a
pidfile avoids pgrep matching the probe's own command line).
"""

from __future__ import annotations

import abc
import dataclasses
import json
import shlex
import subprocess
import uuid
from typing import Optional, Sequence

from frankenpaxos_tpu.bench.harness import LocalHost


class Shell(abc.ABC):
    """Executes shell command strings somewhere (a remote machine, or
    locally for the loopback stand-in)."""

    @abc.abstractmethod
    def spawn(self, command: str) -> subprocess.Popen:
        """Start ``command`` without waiting; returns the local driver
        process (the ssh client, or the local bash)."""

    @abc.abstractmethod
    def run(self, command: str, timeout: float = 10.0
            ) -> tuple[int, str]:
        """Run ``command`` to completion; (returncode, stdout)."""


class LoopbackShell(Shell):
    def spawn(self, command: str) -> subprocess.Popen:
        return subprocess.Popen(["bash", "-c", command],
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def run(self, command: str, timeout: float = 10.0) -> tuple[int, str]:
        done = subprocess.run(["bash", "-c", command],
                              capture_output=True, text=True,
                              timeout=timeout)
        return done.returncode, done.stdout


class SshShell(Shell):
    """System-``ssh`` backed shell. ``dest`` is anything the ssh client
    accepts (``user@host``, a ``~/.ssh/config`` alias, ...)."""

    def __init__(self, dest: str, ssh_args: Sequence[str] = (
            "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=no")):
        self.dest = dest
        self.ssh_args = list(ssh_args)

    def _argv(self, command: str) -> list[str]:
        return ["ssh", *self.ssh_args, self.dest, command]

    def spawn(self, command: str) -> subprocess.Popen:
        return subprocess.Popen(self._argv(command),
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def run(self, command: str, timeout: float = 10.0) -> tuple[int, str]:
        done = subprocess.run(self._argv(command), capture_output=True,
                              text=True, timeout=timeout)
        return done.returncode, done.stdout


class RemoteProc:
    """A process launched through a :class:`Shell` (the ParamikoProc
    analog, proc.py:110)."""

    def __init__(self, shell: Shell, args: Sequence[str], out_path: str,
                 env: Optional[dict] = None, cwd: Optional[str] = None):
        import os
        import re

        self.shell = shell
        self._pidfile = f"/tmp/fpx_remote_{uuid.uuid4().hex}.pid"
        # Export the DELTA vs this process' environment -- callers
        # (launch_roles) pass full os.environ copies, and replaying the
        # local PATH/HOME onto a remote machine would clobber its own
        # resolution, while exported-bash-function keys
        # ('BASH_FUNC_x%%') are not even valid identifiers -- PLUS
        # every runtime-shaping var regardless (the delta is computed
        # against the LOCAL environment, not the remote login shell's:
        # a var like PYTHONUNBUFFERED=1 that happens to match locally
        # must still reach the remote role). Note the semantic
        # difference from Popen(env=...): a remote launch OVERLAYS the
        # remote login environment rather than replacing it.
        identifier = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
        # NOT the bare PYTHON prefix: PYTHONPATH/PYTHONHOME carry local
        # filesystem paths and must only ship when genuinely changed
        # (the delta rule) -- force-exporting them would clobber the
        # remote interpreter's module resolution.
        always = re.compile(
            r"^(PYTHONUNBUFFERED$|PYTHONDONTWRITEBYTECODE$"
            r"|JAX_|XLA_|FPX_|TPU_)")
        exports = "".join(
            f"export {key}={shlex.quote(str(value))}; "
            for key, value in (env or {}).items()
            if identifier.match(key)
            and (always.match(key)
                 or os.environ.get(key) != str(value)))
        cd = f"cd {shlex.quote(cwd)}; " if cwd else ""
        cmd = " ".join(shlex.quote(str(a)) for a in args)
        self._command = (f"echo $$ > {shlex.quote(self._pidfile)}; "
                         f"({cd}{exports}exec {cmd}) "
                         f"> {shlex.quote(out_path)} 2>&1")
        self._driver = shell.spawn(self._command)

    def pid(self) -> Optional[int]:
        """The REMOTE wrapper pid (not the local driver's)."""
        rc, out = self.shell.run(f"cat {shlex.quote(self._pidfile)}")
        try:
            return int(out.strip()) if rc == 0 else None
        except ValueError:
            return None

    def running(self) -> bool:
        return self._driver.poll() is None

    def wait(self, timeout: Optional[float] = None) -> int:
        return self._driver.wait(timeout=timeout)

    def kill(self) -> None:
        import time

        pid = self.pid()
        # The wrapper writes its pidfile first thing, but a launch whose
        # shell is still connecting may not have gotten there yet; a
        # kill that only terminated the local driver would leak the
        # remote role. Poll briefly before giving up on the remote side.
        deadline = time.time() + 2.0
        while pid is None and self._driver.poll() is None \
                and time.time() < deadline:
            time.sleep(0.1)
            pid = self.pid()
        if pid is not None:
            # Children first (the exec'd role), then the wrapper, then
            # drop the pidfile.
            self.shell.run(f"pkill -TERM -P {pid} 2>/dev/null; "
                           f"kill -TERM {pid} 2>/dev/null; "
                           f"rm -f {shlex.quote(self._pidfile)}")
        if self._driver.poll() is None:
            try:
                self._driver.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._driver.kill()


@dataclasses.dataclass(frozen=True)
class RemoteHost:
    """Drop-in for :class:`LocalHost` that launches through a shell
    (host.py:36-50)."""

    shell: Shell
    ip: str = "127.0.0.1"
    # Remote working directory for launched role processes (the repo
    # checkout on the remote machine); None inherits the login dir.
    cwd: Optional[str] = None

    def popen(self, args: Sequence[str], out_path: str,
              env: Optional[dict] = None) -> RemoteProc:
        return RemoteProc(self.shell, args, out_path, env=env,
                          cwd=self.cwd)


@dataclasses.dataclass(frozen=True)
class Endpoint:
    """(host.py:22-25)."""

    host: object  # LocalHost | RemoteHost
    port: int


_LOCAL_ADDRESSES = ("localhost", "127.0.0.1", "::1")


def default_connect(address: str) -> object:
    """Address -> Host: local addresses run in-process; anything else
    gets an ssh shell (reference's paramiko connect, cluster.py usage)."""
    if address in _LOCAL_ADDRESSES:
        return LocalHost()
    return RemoteHost(SshShell(address), ip=address.rsplit("@", 1)[-1])


class Cluster:
    """A cluster file maps f -> role -> machine addresses
    (cluster.py:15-44)::

        {"1": {"leaders": ["10.0.0.1", "10.0.0.2"],
               "acceptors": ["10.0.0.3", "10.0.0.4", "10.0.0.5"],
               "clients": ["localhost"]}}

    ``connect`` turns each distinct address into a Host exactly once
    (so multiple roles on one machine share the ssh connection).
    """

    def __init__(self, data: dict, connect=default_connect):
        self._hosts_by_address: dict[str, object] = {}
        self._by_f: dict[int, dict[str, list]] = {}
        for f_str, roles in data.items():
            if not isinstance(roles, dict):
                raise ValueError(f"cluster entry for f={f_str!r} must be "
                                 f"an object, got {roles!r}")
            by_role: dict[str, list] = {}
            for role, addresses in roles.items():
                if not isinstance(addresses, list) or not all(
                        isinstance(a, str) for a in addresses):
                    raise ValueError(
                        f"addresses for role {role!r} (f={f_str}) must "
                        f"be a list of strings, got {addresses!r}")
                hosts = []
                for address in addresses:
                    if address not in self._hosts_by_address:
                        self._hosts_by_address[address] = connect(address)
                    hosts.append(self._hosts_by_address[address])
                by_role[role] = hosts
            self._by_f[int(f_str)] = by_role

    @classmethod
    def from_file(cls, path: str, connect=default_connect) -> "Cluster":
        with open(path) as f:
            return cls(json.load(f), connect=connect)

    def f(self, f: int) -> dict[str, list]:
        """Role -> hosts for the given fault tolerance."""
        return self._by_f[f]
