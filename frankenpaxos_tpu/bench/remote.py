"""Remote (multi-host) deployment: hosts, shells, and remote procs.

The reference deploys benchmarks by ssh'ing role processes onto cluster
machines (benchmarks/host.py:10-37 ``Host``/``RemoteHost``/``Endpoint``,
proc.py:110 ``ParamikoProc``, cluster.py:44 ``Cluster``). Here the same
seam is a pluggable *shell*:

  * :class:`SshShell` -- runs commands on a remote machine through the
    system ``ssh`` client (ControlMaster-friendly; no paramiko
    dependency).
  * :class:`LoopbackShell` -- runs the IDENTICAL command strings through
    a local ``bash -c``. This is the ssh-to-localhost stand-in for
    environments without an sshd (it exercises every line of the
    remote machinery: quoting, env exports, output redirection, pidfile
    tracking, and remote kill).

:class:`RemoteHost` plugs into the same ``popen(args, out_path, env)``
surface as :class:`frankenpaxos_tpu.bench.harness.LocalHost`, so
``BenchmarkDirectory``/``launch_roles`` deploy over it unchanged.

Scope: by default ``launch_roles`` reads role logs / writes configs at
LOCAL paths, matching the reference's topologies (ssh-to-localhost,
scripts/benchmark_smoke.sh:5-18, or a shared EC2 filesystem). For
fully DISJOINT filesystems, construct the RemoteHost with
``staging_dir`` + ``local_root``: configs ship to the staging dir
before launch, role logs are read through the shell during the
ready-wait, and ``fetch_outputs()`` pulls outputs home afterwards (no
NFS/EFS required).

A launched command is wrapped as::

    echo $$ > <pidfile>; (<exports> exec <cmd>) > <out> 2>&1

The wrapper's pid lands in a pidfile scoped to the launch; ``kill()``
terminates the wrapper's children then the wrapper through the shell
(reference ParamikoProc kills via a nonce + pgrep, proc.py:100-150; a
pidfile avoids pgrep matching the probe's own command line).
"""

from __future__ import annotations

import abc
import dataclasses
import json
import shlex
import subprocess
from typing import Optional, Sequence
import uuid

from frankenpaxos_tpu.bench.harness import LocalHost


class Shell(abc.ABC):
    """Executes shell command strings somewhere (a remote machine, or
    locally for the loopback stand-in)."""

    @abc.abstractmethod
    def spawn(self, command: str) -> subprocess.Popen:
        """Start ``command`` without waiting; returns the local driver
        process (the ssh client, or the local bash)."""

    @abc.abstractmethod
    def run(self, command: str, timeout: float = 10.0
            ) -> tuple[int, str]:
        """Run ``command`` to completion; (returncode, stdout)."""

    def put(self, local_path: str, remote_path: str) -> None:
        """Ship a local file to the shell's filesystem (scp analog;
        the reference ships configs to EC2 the same way,
        benchmarks/README.md:22-27). Creates parent dirs."""
        import os

        parent = os.path.dirname(remote_path) or "."
        with open(local_path, "rb") as f:
            data = f.read()
        self._write_bytes(remote_path, parent, data)

    def get(self, remote_path: str, local_path: str) -> bool:
        """Fetch a remote file into ``local_path``; False if absent."""
        import os

        rc, out = self.run(
            f"base64 < {shlex.quote(remote_path)} 2>/dev/null",
            timeout=60.0)
        if rc != 0:
            return False
        import base64

        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        with open(local_path, "wb") as f:
            f.write(base64.b64decode(out))
        return True

    def _write_bytes(self, remote_path: str, parent: str,
                     data: bytes) -> None:
        import base64

        encoded = base64.b64encode(data).decode()
        # base64 keeps arbitrary bytes intact through the shell pipe
        # (ssh or bash -c), no stdin plumbing needed.
        rc, _ = self.run(
            f"mkdir -p {shlex.quote(parent)} && "
            f"echo {shlex.quote(encoded)} | base64 -d > "
            f"{shlex.quote(remote_path)}", timeout=60.0)
        if rc != 0:
            raise RuntimeError(f"failed to ship {remote_path}")


class LoopbackShell(Shell):
    def spawn(self, command: str) -> subprocess.Popen:
        return subprocess.Popen(["bash", "-c", command],
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def run(self, command: str, timeout: float = 10.0) -> tuple[int, str]:
        done = subprocess.run(["bash", "-c", command],
                              capture_output=True, text=True,
                              timeout=timeout)
        return done.returncode, done.stdout


class SshShell(Shell):
    """System-``ssh`` backed shell. ``dest`` is anything the ssh client
    accepts (``user@host``, a ``~/.ssh/config`` alias, ...)."""

    def __init__(self, dest: str, ssh_args: Sequence[str] = (
            "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=no")):
        self.dest = dest
        self.ssh_args = list(ssh_args)

    def _argv(self, command: str) -> list[str]:
        return ["ssh", *self.ssh_args, self.dest, command]

    def spawn(self, command: str) -> subprocess.Popen:
        return subprocess.Popen(self._argv(command),
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)

    def run(self, command: str, timeout: float = 10.0) -> tuple[int, str]:
        done = subprocess.run(self._argv(command), capture_output=True,
                              text=True, timeout=timeout)
        return done.returncode, done.stdout


class RemoteProc:
    """A process launched through a :class:`Shell` (the ParamikoProc
    analog, proc.py:110)."""

    def __init__(self, shell: Shell, args: Sequence[str], out_path: str,
                 env: Optional[dict] = None, cwd: Optional[str] = None):
        import os
        import re

        self.shell = shell
        self._pidfile = f"/tmp/fpx_remote_{uuid.uuid4().hex}.pid"
        # Export the DELTA vs this process' environment -- callers
        # (launch_roles) pass full os.environ copies, and replaying the
        # local PATH/HOME onto a remote machine would clobber its own
        # resolution, while exported-bash-function keys
        # ('BASH_FUNC_x%%') are not even valid identifiers -- PLUS
        # every runtime-shaping var regardless (the delta is computed
        # against the LOCAL environment, not the remote login shell's:
        # a var like PYTHONUNBUFFERED=1 that happens to match locally
        # must still reach the remote role). Note the semantic
        # difference from Popen(env=...): a remote launch OVERLAYS the
        # remote login environment rather than replacing it.
        identifier = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
        # NOT the bare PYTHON prefix: PYTHONPATH/PYTHONHOME carry local
        # filesystem paths and must only ship when genuinely changed
        # (the delta rule) -- force-exporting them would clobber the
        # remote interpreter's module resolution.
        always = re.compile(
            r"^(PYTHONUNBUFFERED$|PYTHONDONTWRITEBYTECODE$"
            r"|JAX_|XLA_|FPX_|TPU_)")
        exports = "".join(
            f"export {key}={shlex.quote(str(value))}; "
            for key, value in (env or {}).items()
            if identifier.match(key)
            and (always.match(key)
                 or os.environ.get(key) != str(value)))
        cd = f"cd {shlex.quote(cwd)}; " if cwd else ""
        cmd = " ".join(shlex.quote(str(a)) for a in args)
        self._command = (f"echo $$ > {shlex.quote(self._pidfile)}; "
                         f"({cd}{exports}exec {cmd}) "
                         f"> {shlex.quote(out_path)} 2>&1")
        self._driver = shell.spawn(self._command)

    def pid(self) -> Optional[int]:
        """The REMOTE wrapper pid (not the local driver's)."""
        rc, out = self.shell.run(f"cat {shlex.quote(self._pidfile)}")
        try:
            return int(out.strip()) if rc == 0 else None
        except ValueError:
            return None

    def running(self) -> bool:
        return self._driver.poll() is None

    def wait(self, timeout: Optional[float] = None) -> int:
        return self._driver.wait(timeout=timeout)

    def kill(self) -> None:
        import time

        pid = self.pid()
        # The wrapper writes its pidfile first thing, but a launch whose
        # shell is still connecting may not have gotten there yet; a
        # kill that only terminated the local driver would leak the
        # remote role. Poll briefly before giving up on the remote side.
        deadline = time.time() + 2.0
        while pid is None and self._driver.poll() is None \
                and time.time() < deadline:
            time.sleep(0.1)
            pid = self.pid()
        if pid is not None:
            # Children first (the exec'd role), then the wrapper, then
            # drop the pidfile.
            self.shell.run(f"pkill -TERM -P {pid} 2>/dev/null; "
                           f"kill -TERM {pid} 2>/dev/null; "
                           f"rm -f {shlex.quote(self._pidfile)}")
        if self._driver.poll() is None:
            try:
                self._driver.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._driver.kill()


@dataclasses.dataclass
class RemoteHost:
    """Drop-in for :class:`LocalHost` that launches through a shell
    (host.py:36-50).

    With ``staging_dir`` + ``local_root`` set, the host works across
    DISJOINT filesystems (no EFS/NFS needed, closing the gap
    docs/PARITY.md used to admit): every launch argument and output
    path under ``local_root`` is remapped under ``staging_dir``;
    arguments naming files that exist locally (configs) are SHIPPED to
    the staging dir before launch, and :meth:`fetch_outputs` pulls
    every remapped path that materialized remotely (role logs, client
    CSVs) back under ``local_root`` afterwards. Without them, paths
    pass through unchanged (ssh-to-localhost / shared filesystem, the
    reference's default topologies)."""

    shell: Shell
    ip: str = "127.0.0.1"
    # Remote working directory for launched role processes (the repo
    # checkout on the remote machine); None inherits the login dir.
    cwd: Optional[str] = None
    # Remote scratch dir for shipped inputs + outputs (disjoint-fs
    # mode); pairs with local_root.
    staging_dir: Optional[str] = None
    # The local directory whose paths get remapped into staging_dir.
    local_root: Optional[str] = None

    def __post_init__(self):
        self._mapped: dict[str, str] = {}  # local path -> remote path
        self._shipped: set[tuple[str, float]] = set()  # (path, mtime)
        self._inputs: set[str] = set()  # shipped inputs: not fetched back

    def _map(self, path: str) -> str:
        import os

        if (self.staging_dir is None or self.local_root is None
                or not path.startswith(self.local_root.rstrip("/") + "/")):
            return path
        rel = os.path.relpath(path, self.local_root)
        remote = os.path.join(self.staging_dir, rel)
        self._mapped[path] = remote
        return remote

    def popen(self, args: Sequence[str], out_path: str,
              env: Optional[dict] = None) -> RemoteProc:
        import os

        mapped_args = []
        for arg in args:
            arg = str(arg)
            mapped = self._map(arg)
            if mapped != arg and os.path.isfile(arg):
                # Ship inputs (configs) once per content version; every
                # role passes the same --config, so dedup by mtime.
                key = (arg, os.path.getmtime(arg))
                if key not in self._shipped:
                    self.shell.put(arg, mapped)
                    self._shipped.add(key)
                self._inputs.add(arg)
            mapped_args.append(mapped)
        remote_out = self._map(out_path)
        if remote_out != out_path:
            # The wrapper redirects into this dir before anything else
            # could create it; make it exist up front.
            import os as _os

            parent = _os.path.dirname(remote_out) or "."
            self.shell.run(f"mkdir -p {shlex.quote(parent)}")
        return RemoteProc(self.shell, mapped_args, remote_out,
                          env=env, cwd=self.cwd)

    def read_output(self, path: str) -> str:
        """Read a (possibly remapped) output file's current contents --
        the ready-wait seam (launch_roles polls role logs). Never
        raises: a stalled shell reads as 'nothing yet' so the caller's
        deadline logic (and its cleanup) stays in charge."""
        remote = self._mapped.get(path, self._map(path))
        try:
            rc, out = self.shell.run(
                f"cat {shlex.quote(remote)} 2>/dev/null")
        except (OSError, subprocess.TimeoutExpired):
            return ""
        return out if rc == 0 else ""

    def grep_ready(self, paths: Sequence[str], needle: str) -> set:
        """Which of ``paths`` currently contain ``needle`` -- ONE shell
        round-trip for the whole set (the ready-wait would otherwise
        spawn one ssh per pending role per poll tick)."""
        remotes = {self._mapped.get(p, self._map(p)): p for p in paths}
        if not remotes:
            return set()
        quoted = " ".join(shlex.quote(r) for r in remotes)
        try:
            rc, out = self.shell.run(
                f"grep -l -s -F {shlex.quote(needle)} {quoted}; true")
        except (OSError, subprocess.TimeoutExpired):
            return set()
        return {remotes[line] for line in out.splitlines()
                if line in remotes}

    def fetch_outputs(self) -> int:
        """Pull every remapped OUTPUT path that exists remotely back to
        its local home (shipped inputs are skipped); returns how many
        files landed."""
        fetched = 0
        for local, remote in sorted(set(self._mapped.items())):
            if local in self._inputs:
                continue
            if self.shell.get(remote, local):
                fetched += 1
        return fetched


@dataclasses.dataclass(frozen=True)
class Endpoint:
    """(host.py:22-25)."""

    host: object  # LocalHost | RemoteHost
    port: int


_LOCAL_ADDRESSES = ("localhost", "127.0.0.1", "::1")


def default_connect(address: str) -> object:
    """Address -> Host: local addresses run in-process; anything else
    gets an ssh shell (reference's paramiko connect, cluster.py usage)."""
    if address in _LOCAL_ADDRESSES:
        return LocalHost()
    return RemoteHost(SshShell(address), ip=address.rsplit("@", 1)[-1])


class Cluster:
    """A cluster file maps f -> role -> machine addresses
    (cluster.py:15-44)::

        {"1": {"leaders": ["10.0.0.1", "10.0.0.2"],
               "acceptors": ["10.0.0.3", "10.0.0.4", "10.0.0.5"],
               "clients": ["localhost"]}}

    ``connect`` turns each distinct address into a Host exactly once
    (so multiple roles on one machine share the ssh connection).
    """

    def __init__(self, data: dict, connect=default_connect):
        self._hosts_by_address: dict[str, object] = {}
        self._by_f: dict[int, dict[str, list]] = {}
        for f_str, roles in data.items():
            if not isinstance(roles, dict):
                raise ValueError(f"cluster entry for f={f_str!r} must be "
                                 f"an object, got {roles!r}")
            by_role: dict[str, list] = {}
            for role, addresses in roles.items():
                if not isinstance(addresses, list) or not all(
                        isinstance(a, str) for a in addresses):
                    raise ValueError(
                        f"addresses for role {role!r} (f={f_str}) must "
                        f"be a list of strings, got {addresses!r}")
                hosts = []
                for address in addresses:
                    if address not in self._hosts_by_address:
                        self._hosts_by_address[address] = connect(address)
                    hosts.append(self._hosts_by_address[address])
                by_role[role] = hosts
            self._by_f[int(f_str)] = by_role

    @classmethod
    def from_file(cls, path: str, connect=default_connect) -> "Cluster":
        with open(path) as f:
            return cls(json.load(f), connect=connect)

    def f(self, f: int) -> dict[str, list]:
        """Role -> hosts for the given fault tolerance."""
        return self._by_f[f]
