"""Read-scale benchmark: read throughput vs. replica count.

The Evelyn read-scaling experiment
(benchmarks/vldb21_compartmentalized/read_scale/): a read-heavy
UniformReadWriteWorkload against MultiPaxos while the replica count
grows. Writes cost a full Phase2 round regardless of replicas; reads are
served by replicas, so read throughput should scale with the replica
count (VLDB'21 "Scaling Replicated State Machines with Compartmentalization").

Usage::

    python -m frankenpaxos_tpu.bench.read_scale \
        --replicas 2 3 4 --duration 3 --out results/read_scale.json
"""

from __future__ import annotations

import argparse
import json
import tempfile

from frankenpaxos_tpu.bench.harness import SuiteDirectory
from frankenpaxos_tpu.bench.multipaxos_suite import (
    MultiPaxosInput,
    run_benchmark,
)
from frankenpaxos_tpu.bench.workload import UniformReadWriteWorkload


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--replicas", type=int, nargs="+",
                        default=[2, 3, 4])
    parser.add_argument("--client_procs", type=int, default=6,
                        help="client OS processes (0: in-process threads)")
    parser.add_argument("--num_clients", type=int, default=10,
                        help="closed loops per client process")
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--read_fraction", type=float, default=0.95)
    parser.add_argument("--read_consistency", nargs="+",
                        default=["linearizable", "eventual"],
                        choices=["linearizable", "sequential", "eventual"],
                        help="consistency levels to sweep (the "
                             "linearizable rows exercise the MaxSlot "
                             "quorum-read path, Client.scala:851-933)")
    parser.add_argument("--suite_dir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_readscale_")
    suite = SuiteDirectory(root, "read_scale")
    workload = UniformReadWriteWorkload(
        num_keys=16, read_fraction=args.read_fraction)

    rows = []
    for read_consistency in args.read_consistency:
        for num_replicas in args.replicas:
            stats = run_benchmark(
                suite.benchmark_directory(),
                MultiPaxosInput(
                    num_replicas=num_replicas,
                    num_clients=args.num_clients,
                    client_procs=args.client_procs,
                    duration_s=args.duration,
                    workload=workload,
                    read_consistency=read_consistency,
                    prometheus=True))
            role_metrics = stats.get("role_metrics", {})
            # Per-replica served reads from the scraped role metrics:
            # the Evelyn scaling mechanism is reads spreading over
            # replicas (each serves ~1/N), independent of this host's
            # core count.
            per_replica_reads = {
                label: metrics.get(
                    "multipaxos_replica_executed_reads_total", 0.0)
                for label, metrics in role_metrics.items()
                if label.startswith("replica_")}
            # Per-acceptor MaxSlot requests: the linearizable quorum
            # read fans out to acceptors BEFORE reading at a replica
            # (Client.scala:851-933, Acceptor.scala:222-237); eventual
            # reads never touch acceptors, so these counters make the
            # fan-out visible per consistency level.
            per_acceptor_max_slot = {
                label: metrics.get(
                    'multipaxos_acceptor_requests_total'
                    '{type="MaxSlotRequest"}', 0.0)
                for label, metrics in role_metrics.items()
                if label.startswith("acceptor_")}
            # Per-role CPU seconds: the attribution for WHY
            # linearizable writes collapse vs eventual on this host
            # (VERDICT r4 weak #6) -- the MaxSlot fan-out lands on the
            # same acceptors the write path needs, and every CPU
            # second acceptors spend answering MaxSlotRequests is
            # stolen from Phase2b voting on the shared core.
            role_cpu = stats.get("role_cpu_seconds") or {}
            acceptor_cpu = round(sum(
                cpu for label, cpu in role_cpu.items()
                if label.startswith("acceptor_")), 3)
            row = {
                "read_consistency": read_consistency,
                "num_replicas": num_replicas,
                "read_throughput": stats.get(
                    "read.start_throughput_1s.p90",
                    stats.get("read.throughput_mean")),
                "read_latency_median_ms": stats.get(
                    "read.latency.median_ms"),
                "write_throughput": stats.get(
                    "write.start_throughput_1s.p90",
                    stats.get("write.throughput_mean")),
                "num_requests": stats["num_requests"],
                "per_replica_reads": per_replica_reads,
                "per_acceptor_max_slot_requests": per_acceptor_max_slot,
                "role_cpu_seconds": role_cpu,
                "acceptor_cpu_s": acceptor_cpu,
            }
            rows.append(row)
            print(json.dumps(row))

    import os

    result = {
        "benchmark": "read_scale",
        "host_cpus": os.cpu_count(),
        "note": ("per_replica_reads is the scaling signal: reads spread "
                 "evenly, so per-replica load drops ~1/N with N replicas "
                 "(the Evelyn mechanism). Aggregate throughput only "
                 "rises with N when replicas have their own cores/hosts; "
                 "on a single-core host all processes time-share one "
                 "CPU. The linearizable rows run the MaxSlot quorum "
                 "path (visible as per_acceptor_max_slot_requests > 0); "
                 "the eventual rows never touch acceptors on reads. "
                 "WRITE-COLLAPSE ATTRIBUTION (role_cpu_seconds / "
                 "acceptor_cpu_s): under linearizable reads the "
                 "acceptors burn CPU answering the per-read MaxSlot "
                 "fan-out (f+1 of them per read, Client.scala:851-933, "
                 "Acceptor.scala:222-254) on the same shared core the "
                 "write path's Phase2b voting needs -- compare "
                 "acceptor_cpu_s between the linearizable and eventual "
                 "rows at equal load to see the steal directly."),
        "read_consistency_levels": args.read_consistency,
        "read_fraction": args.read_fraction,
        "client_procs": args.client_procs,
        "num_clients": args.num_clients,
        "duration_s": args.duration,
        "rows": rows,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
