"""Deployed benchmark for ANY protocol (the per-protocol suites analog).

The reference ships a benchmark suite per protocol
(benchmarks/<proto>/<proto>.py, 18 of them); here one generic suite
serves every protocol the deployment registry knows: launch the roles
over localhost TCP, drive closed loops from client OS processes through
the registry's ``drive`` entry (bench/client_main.py ``run_drive``), and
report the reference-shaped stats.

Usage::

    python -m frankenpaxos_tpu.bench.protocol_suite --protocol epaxos
    python -m frankenpaxos_tpu.bench.protocol_suite --protocol all \
        --out bench_results/protocol_lt.json
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time

from frankenpaxos_tpu.bench.deploy_suite import launch_roles, role_process_env
from frankenpaxos_tpu.bench.harness import (
    BenchmarkDirectory,
    free_port,
    latency_throughput_stats,
    LocalHost,
    SuiteDirectory,
)
from frankenpaxos_tpu.deploy import get_protocol, PROTOCOL_NAMES


# Single-decree protocols livelock under concurrent dueling proposers
# (phase-1 preemption cycles); drive them with one serial loop. The
# batching baseline needs batch_size=1 so ops don't wait on batch fill.
SINGLE_DECREE = ("paxos", "fastpaxos", "matchmakerpaxos")
LAUNCH_OVERRIDES = {
    "batchedunreplicated": {"batch_size": "1"},
    # Idle leader groups must skip their slots PROMPTLY or every command
    # waits on the replicas' ~1s hole-recover timer: the reference's own
    # LT sweeps run with watermark gossip every 1-20 commands and a skip
    # threshold of 1 slot (benchmarks/mencius/eurosys_lt.py:107-108
    # sweep values; Leader.scala code defaults of 10000 are for paper
    # peak-throughput points, not latency).
    "mencius": {"send_high_watermark_every_n": "1",
                "send_noop_range_if_lagging_by": "1"},
    # Dueling-proposer nack backoff sized for localhost RTT (~0.1ms):
    # the reference's 100ms-1s defaults (caspaxos/Leader.scala:29-30)
    # assume datacenter links and park a nacked leader for seconds of
    # benchmark time.
    "caspaxos": {"resend_period_s": "0.25",
                 "recover_min_period_s": "0.002",
                 "recover_max_period_s": "0.02"},
}


def run_protocol_benchmark(bench: BenchmarkDirectory, protocol_name: str,
                           *, f: int = 1, client_procs: int = 2,
                           clients_per_proc: int = 5,
                           duration_s: float = 3.0,
                           state_machine: str = "AppendLog",
                           supernode: bool = False,
                           point_skew: float | None = None) -> dict:
    if protocol_name in SINGLE_DECREE:
        client_procs, clients_per_proc = 1, 1
    if point_skew is not None and protocol_name != "craq":
        # Skewed loops issue SetRequests; conflict sensitivity needs
        # the KV conflict index (CRAQ's chain store is natively KV).
        state_machine = "KeyValueStore"
    protocol = get_protocol(protocol_name)
    raw = protocol.cluster(f, lambda: ["127.0.0.1", free_port()])
    config_path = bench.write_json("config.json", raw)
    config = protocol.load_config(raw)
    launch_roles(bench, protocol_name, config_path, config,
                 state_machine=state_machine,
                 overrides={"resend_phase1as_period_s": "0.5",
                            **LAUNCH_OVERRIDES.get(protocol_name, {})},
                 supernode=supernode)

    host = LocalHost()
    env = role_process_env()
    procs = []
    try:
        for i in range(client_procs):
            out_csv = bench.abspath(f"client_{i}_data.csv")
            procs.append((out_csv, bench.popen(host, f"client_{i}", [
                sys.executable, "-m", "frankenpaxos_tpu.bench.client_main",
                "--protocol", protocol_name,
                "--config", config_path,
                "--num_clients", str(clients_per_proc),
                "--duration", str(duration_s),
                "--seed", str(i + 1), "--out", out_csv]
                + (["--point_skew", str(point_skew)]
                   if point_skew is not None else []), env=env)))
        latencies, starts = [], []
        for out_csv, proc in procs:
            code = proc.wait(timeout=duration_s + 90)
            if code != 0:
                raise RuntimeError(
                    f"client process exited with code {code}; see "
                    f"{bench.path}")
            with open(out_csv) as f_csv:
                next(f_csv)
                for line in f_csv:
                    _, start, latency = line.strip().split(",")
                    latencies.append(float(latency))
                    starts.append(float(start))
        role_cpu = bench.role_cpu_seconds()
    finally:
        bench.cleanup()

    stats = latency_throughput_stats(latencies, duration_s,
                                     starts_s=starts)
    stats["role_cpu_seconds"] = {
        label: cpu for label, cpu in role_cpu.items()
        if not label.startswith("client_")}
    stats["protocol"] = protocol_name
    stats["client_procs"] = client_procs
    stats["clients_per_proc"] = clients_per_proc
    stats["duration_s"] = duration_s
    bench.write_json("results.json", stats)
    return stats


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--protocol", default="all",
                        choices=["all", *PROTOCOL_NAMES])
    parser.add_argument("--client_procs", type=int, default=2)
    parser.add_argument("--clients_per_proc", type=int, default=5)
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--suite_dir", default=None)
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)

    root = args.suite_dir or tempfile.mkdtemp(prefix="fpx_plt_")
    suite = SuiteDirectory(root, "protocol_lt")
    names = PROTOCOL_NAMES if args.protocol == "all" else [args.protocol]

    results, failures = {}, []
    for name in names:
        t0 = time.time()
        try:
            stats = run_protocol_benchmark(
                suite.benchmark_directory(), name,
                client_procs=args.client_procs,
                clients_per_proc=args.clients_per_proc,
                duration_s=args.duration)
            results[name] = {
                "throughput_p90_1s": stats.get("start_throughput_1s.p90"),
                "throughput_mean": stats.get(
                    "throughput_mean",
                    stats["num_requests"] / args.duration),
                "latency_median_ms": stats.get("latency.median_ms"),
                "num_requests": stats["num_requests"],
                # The load actually applied (SINGLE_DECREE runs 1x1
                # regardless of the requested flags).
                "client_procs": stats["client_procs"],
                "clients_per_proc": stats["clients_per_proc"],
            }
            # Per-role CPU + the decoupling projection, so every
            # protocol row states its parallelizable fraction -- what
            # a 1-CPU host can honestly assert about
            # compartmentalization.
            role_cpu = stats.get("role_cpu_seconds") or {}
            if role_cpu:
                results[name]["role_cpu_seconds"] = role_cpu
                results[name].update(
                    BenchmarkDirectory.stage_projection(role_cpu))
            if name in SINGLE_DECREE:
                results[name]["note"] = (
                    "single-decree: after the first decision the closed "
                    "loop measures cached-chosen-value replies, not "
                    "consensus decisions")
            print(f"{name}: {stats['num_requests']} reqs in "
                  f"{round(time.time() - t0, 1)}s")
        except Exception as e:  # noqa: BLE001 - report, then fail at end
            failures.append(name)
            print(f"{name}: FAILED: {e}")

    import os

    out = {
        "benchmark": "protocol_lt",
        "host_cpus": os.cpu_count(),
        "client_procs": args.client_procs,
        "clients_per_proc": args.clients_per_proc,
        "duration_s": args.duration,
        "note": ("absolute numbers on this 1-CPU host vary 15-30% "
                 "with ambient host state across days; treat the "
                 "'echo' row (a protocol no consensus change touches) "
                 "as the ambient control when comparing artifacts "
                 "across rounds. role_cpu_seconds / "
                 "projected_stage_speedup are the cross-round-stable "
                 "columns."),
        "protocols": results,
    }
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(out, fh, indent=2)
    print(json.dumps(out, indent=2))
    if failures:
        raise SystemExit(f"benchmark failed for: {failures}")
    return out


if __name__ == "__main__":
    main()
