"""Accelerator-link probe shared by every benchmark entry point.

The axon device link has been observed to wedge such that
``jax.devices()`` itself hangs indefinitely; any artifact script that
touches the device in-process must probe FIRST, in a throwaway
subprocess, and degrade when the link is dead instead of hanging. The
probe uses Popen + poll: after a timeout, ``subprocess.run``'s own
cleanup blocks in an UNBOUNDED wait on a child stuck in the wedged
syscall, so the child is killed, given one bounded wait to reap (no
zombie in the common case), and only then abandoned.
"""

from __future__ import annotations

import subprocess
import sys
import time

#: Platforms that count as the real accelerator (a silent CPU fallback
#: with rc=0 must NOT count as device-available).
_ACCELERATOR_PLATFORMS = ("tpu", "axon")


def device_probe(timeout_s: float = 90.0) -> tuple[bool, str]:
    """-> (device_available, note). The note records what actually
    happened -- the reported platform on success, the platform or
    stderr tail on a non-accelerator result, or the timeout -- so the
    artifact carries a true diagnosis."""
    probe = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    deadline = time.time() + timeout_s
    while probe.poll() is None and time.time() < deadline:
        time.sleep(1)
    if probe.poll() is None:
        probe.kill()
        # A killed child usually reaps promptly even when its syscall
        # was wedged; try a BOUNDED wait so it doesn't linger as a
        # zombie for the parent's lifetime. Only if the kill itself
        # can't take effect within the bound is the child abandoned
        # (never an unbounded wait -- that hang is the very failure
        # mode this probe exists to contain).
        try:
            probe.wait(timeout=1)
        except subprocess.TimeoutExpired:
            pass  # truly wedged: abandon it
        return False, (f"device probe timed out after {timeout_s:.0f}s "
                       f"(wedged link)")
    out, err = probe.communicate()
    platform = (out or "").strip().lower()
    if probe.returncode == 0 and platform in _ACCELERATOR_PLATFORMS:
        return True, platform
    if probe.returncode == 0:
        return False, (f"probe reported platform {platform!r} "
                       f"(silent CPU fallback, not the accelerator)")
    return False, (f"probe exited {probe.returncode}: "
                   f"{(err or '').strip()[-120:]}")
