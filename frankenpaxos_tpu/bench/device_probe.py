"""Accelerator-link probe shared by every benchmark entry point.

The axon device link has been observed to wedge such that
``jax.devices()`` itself hangs indefinitely; any artifact script that
touches the device in-process must probe FIRST, in a throwaway
subprocess, and degrade when the link is dead instead of hanging. The
probe uses Popen + poll: after a timeout, ``subprocess.run``'s own
cleanup blocks in an UNBOUNDED wait on a child stuck in the wedged
syscall, so the child is killed, given one bounded wait to reap (no
zombie in the common case), and only then abandoned.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import NamedTuple

#: Platforms that count as the real accelerator (a silent CPU fallback
#: with rc=0 must NOT count as device-available).
_ACCELERATOR_PLATFORMS = ("tpu", "axon")

#: Default probe timeout. A WEDGED link fails by timeout, so this used
#: to cost 90s per benchmark entry point on a dead-accelerator host
#: (BENCH_r05 device note: bench.py burned 90s before every run);
#: 20s comfortably covers a healthy cold attach, and
#: FPX_DEVICE_PROBE_TIMEOUT_S overrides it for slow fabrics.
DEFAULT_TIMEOUT_S = float(os.environ.get(
    "FPX_DEVICE_PROBE_TIMEOUT_S", "20"))

#: Process-lifetime verdict cache: the link's state does not change
#: under a benchmark run, and several suites (bench.py -> libbench ->
#: lt_suite) each probe -- a dead link must cost ONE timeout per
#: process, not one per entry point. Stored with the budget the probe
#: ran under, so a caller explicitly asking for a LONGER timeout can
#: upgrade a negative verdict instead of inheriting a shorter probe's
#: failure.
_VERDICT: "tuple[bool, str] | None" = None
_VERDICT_TIMEOUT_S: float = 0.0


def device_probe(timeout_s: "float | None" = None,
                 refresh: bool = False) -> tuple[bool, str]:
    """-> (device_available, note). The note records what actually
    happened -- the reported platform on success, the platform or
    stderr tail on a non-accelerator result, or the timeout -- so the
    artifact carries a true diagnosis.

    The verdict is cached for the process lifetime. Re-probes happen
    on ``refresh=True`` or when an explicit ``timeout_s`` exceeds the
    budget a cached NEGATIVE verdict was probed under (a slow fabric
    may just need the longer wait); ``timeout_s`` defaults to
    :data:`DEFAULT_TIMEOUT_S`."""
    global _VERDICT, _VERDICT_TIMEOUT_S
    budget = DEFAULT_TIMEOUT_S if timeout_s is None else timeout_s
    if _VERDICT is not None and not refresh:
        if _VERDICT[0] or budget <= _VERDICT_TIMEOUT_S:
            return _VERDICT
    _VERDICT = _probe_once(budget)
    _VERDICT_TIMEOUT_S = budget
    return _VERDICT


def _probe_once(timeout_s: float) -> tuple[bool, str]:
    probe = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    deadline = time.time() + timeout_s
    while probe.poll() is None and time.time() < deadline:
        time.sleep(1)
    if probe.poll() is None:
        probe.kill()
        # A killed child usually reaps promptly even when its syscall
        # was wedged; try a BOUNDED wait so it doesn't linger as a
        # zombie for the parent's lifetime. Only if the kill itself
        # can't take effect within the bound is the child abandoned
        # (never an unbounded wait -- that hang is the very failure
        # mode this probe exists to contain).
        try:
            probe.wait(timeout=1)
        except subprocess.TimeoutExpired:
            pass  # truly wedged: abandon it
        return False, (f"device probe timed out after {timeout_s:.0f}s "
                       f"(wedged link)")
    out, err = probe.communicate()
    platform = (out or "").strip().lower()
    if probe.returncode == 0 and platform in _ACCELERATOR_PLATFORMS:
        return True, platform
    if probe.returncode == 0:
        return False, (f"probe reported platform {platform!r} "
                       f"(silent CPU fallback, not the accelerator)")
    return False, (f"probe exited {probe.returncode}: "
                   f"{(err or '').strip()[-120:]}")


class MeshProbe(NamedTuple):
    """What :func:`mesh_probe` learned about the device mesh."""

    platform: str        # "" when the probe itself failed
    device_count: int    # 0 when the probe itself failed
    collective_ok: bool  # the all-device psum returned the right value
    note: str            # true diagnosis for the artifact


#: The collective micro-probe run inside the throwaway subprocess: a
#: tiny psum of per-device ones across EVERY device. A healthy mesh
#: prints ``<platform> <n> ok``; a wedged inter-chip link hangs (the
#: parent's deadline contains it, same as the wedged-attach class) or
#: errors; a mesh returning the WRONG sum prints ``bad-sum`` -- all
#: three land as ``collective_ok=False`` with the note saying which.
_MESH_PROBE_SRC = """
import jax, jax.numpy as jnp, numpy as np
ds = jax.devices()
n = len(ds)
status = "ok"
if n > 1:
    try:
        from jax.sharding import Mesh, PartitionSpec as P
        fn = getattr(jax, "shard_map", None)
        if fn is None:
            from jax.experimental.shard_map import shard_map as fn
        mesh = Mesh(np.array(ds), ("d",))
        f = jax.jit(fn(lambda x: jax.lax.psum(x, "d"), mesh=mesh,
                       in_specs=P("d"), out_specs=P()))
        out = np.asarray(f(jnp.ones(n, jnp.int32)))
        if int(out[()] if out.ndim == 0 else out[0]) != n:
            status = "bad-sum"
    except Exception as e:
        status = "error:" + type(e).__name__
print(ds[0].platform, n, status)
"""

#: Process-lifetime cache for the mesh verdict (same rationale as
#: ``_VERDICT``: a wedged link must cost ONE deadline per process).
_MESH_VERDICT: "MeshProbe | None" = None
_MESH_VERDICT_TIMEOUT_S: float = 0.0


def mesh_probe(timeout_s: "float | None" = None,
               refresh: bool = False) -> MeshProbe:
    """Probe the mesh: platform, device count, and a per-device
    collective micro-probe (a tiny psum every device participates in,
    under the same wedged-link deadline as :func:`device_probe`).

    ``collective_ok=False`` with ``device_count >= 2`` is the PARTIAL
    MESH verdict -- some inter-chip link is wedged or lying even though
    attach succeeded -- and headline benches must refuse to stamp a
    device result from it (the r05 regression class, extended from
    "CPU fallback" to "mesh that cannot psum"). A single-device result
    with ``collective_ok=True`` is a legitimate 1-chip run, not
    degradation."""
    global _MESH_VERDICT, _MESH_VERDICT_TIMEOUT_S
    budget = DEFAULT_TIMEOUT_S if timeout_s is None else timeout_s
    if _MESH_VERDICT is not None and not refresh:
        if _MESH_VERDICT.collective_ok \
                or budget <= _MESH_VERDICT_TIMEOUT_S:
            return _MESH_VERDICT
    _MESH_VERDICT = _mesh_probe_once(budget)
    _MESH_VERDICT_TIMEOUT_S = budget
    return _MESH_VERDICT


def _mesh_probe_once(timeout_s: float) -> MeshProbe:
    probe = subprocess.Popen(
        [sys.executable, "-c", _MESH_PROBE_SRC],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    deadline = time.time() + timeout_s
    while probe.poll() is None and time.time() < deadline:
        time.sleep(1)
    if probe.poll() is None:
        probe.kill()
        try:
            probe.wait(timeout=1)  # bounded reap; see _probe_once
        except subprocess.TimeoutExpired:
            pass
        return MeshProbe("", 0, False,
                         f"mesh probe timed out after {timeout_s:.0f}s "
                         f"(wedged link or hung collective)")
    out, err = probe.communicate()
    parts = (out or "").strip().split()
    if probe.returncode != 0 or len(parts) != 3:
        return MeshProbe("", 0, False,
                         f"mesh probe exited {probe.returncode}: "
                         f"{(err or '').strip()[-120:]}")
    platform, count, status = parts[0].lower(), int(parts[1]), parts[2]
    if status != "ok":
        return MeshProbe(
            platform, count, False,
            f"collective psum failed on the {count}-device {platform} "
            f"mesh: {status} (partial mesh -- refusing is on the "
            f"caller)")
    return MeshProbe(platform, count, True,
                     f"{platform} x{count}, collective psum healthy")
