"""Accelerator-link probe shared by every benchmark entry point.

The axon device link has been observed to wedge such that
``jax.devices()`` itself hangs indefinitely; any artifact script that
touches the device in-process must probe FIRST, in a throwaway
subprocess, and degrade when the link is dead instead of hanging. The
probe uses Popen + poll: after a timeout, ``subprocess.run``'s own
cleanup blocks in an UNBOUNDED wait on a child stuck in the wedged
syscall, so the child is killed, given one bounded wait to reap (no
zombie in the common case), and only then abandoned.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

#: Platforms that count as the real accelerator (a silent CPU fallback
#: with rc=0 must NOT count as device-available).
_ACCELERATOR_PLATFORMS = ("tpu", "axon")

#: Default probe timeout. A WEDGED link fails by timeout, so this used
#: to cost 90s per benchmark entry point on a dead-accelerator host
#: (BENCH_r05 device note: bench.py burned 90s before every run);
#: 20s comfortably covers a healthy cold attach, and
#: FPX_DEVICE_PROBE_TIMEOUT_S overrides it for slow fabrics.
DEFAULT_TIMEOUT_S = float(os.environ.get(
    "FPX_DEVICE_PROBE_TIMEOUT_S", "20"))

#: Process-lifetime verdict cache: the link's state does not change
#: under a benchmark run, and several suites (bench.py -> libbench ->
#: lt_suite) each probe -- a dead link must cost ONE timeout per
#: process, not one per entry point. Stored with the budget the probe
#: ran under, so a caller explicitly asking for a LONGER timeout can
#: upgrade a negative verdict instead of inheriting a shorter probe's
#: failure.
_VERDICT: "tuple[bool, str] | None" = None
_VERDICT_TIMEOUT_S: float = 0.0


def device_probe(timeout_s: "float | None" = None,
                 refresh: bool = False) -> tuple[bool, str]:
    """-> (device_available, note). The note records what actually
    happened -- the reported platform on success, the platform or
    stderr tail on a non-accelerator result, or the timeout -- so the
    artifact carries a true diagnosis.

    The verdict is cached for the process lifetime. Re-probes happen
    on ``refresh=True`` or when an explicit ``timeout_s`` exceeds the
    budget a cached NEGATIVE verdict was probed under (a slow fabric
    may just need the longer wait); ``timeout_s`` defaults to
    :data:`DEFAULT_TIMEOUT_S`."""
    global _VERDICT, _VERDICT_TIMEOUT_S
    budget = DEFAULT_TIMEOUT_S if timeout_s is None else timeout_s
    if _VERDICT is not None and not refresh:
        if _VERDICT[0] or budget <= _VERDICT_TIMEOUT_S:
            return _VERDICT
    _VERDICT = _probe_once(budget)
    _VERDICT_TIMEOUT_S = budget
    return _VERDICT


def _probe_once(timeout_s: float) -> tuple[bool, str]:
    probe = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    deadline = time.time() + timeout_s
    while probe.poll() is None and time.time() < deadline:
        time.sleep(1)
    if probe.poll() is None:
        probe.kill()
        # A killed child usually reaps promptly even when its syscall
        # was wedged; try a BOUNDED wait so it doesn't linger as a
        # zombie for the parent's lifetime. Only if the kill itself
        # can't take effect within the bound is the child abandoned
        # (never an unbounded wait -- that hang is the very failure
        # mode this probe exists to contain).
        try:
            probe.wait(timeout=1)
        except subprocess.TimeoutExpired:
            pass  # truly wedged: abandon it
        return False, (f"device probe timed out after {timeout_s:.0f}s "
                       f"(wedged link)")
    out, err = probe.communicate()
    platform = (out or "").strip().lower()
    if probe.returncode == 0 and platform in _ACCELERATOR_PLATFORMS:
        return True, platform
    if probe.returncode == 0:
        return False, (f"probe reported platform {platform!r} "
                       f"(silent CPU fallback, not the accelerator)")
    return False, (f"probe exited {probe.returncode}: "
                   f"{(err or '').strip()[-120:]}")
