"""sim_core_ab: the paxsim wave engine vs the frozen legacy sim core.

Core-isolated A/B (the paxwire discipline: the legacy arm is the REAL
pre-refactor machinery, pinned verbatim in runtime/sim_legacy.py) over
workloads shaped like the schedules the simulator actually runs, with
sink actors cheap enough that the measurement is the delivery
machinery, not protocol handler Python:

* ``geo-storm/soak-scale`` -- THE GATE. The geo-chaos soak shape
  (tests/soak.py geo-chaos/*: jittered wide-area topology, partition/
  heal cycles, resend-storm backlogs of thousands of frames in
  flight) replayed at the soak's 500x250 event volume. The legacy
  core pays a ``list.remove`` dataclass-``__eq__`` scan per delivered
  frame -- linear in the backlog, quadratic over a storm -- which is
  exactly what capped chaos soaks at ~dozen-zone topologies. Gate:
  >= 10x events/s.
* ``geo/1000-zones`` -- a 1000-zone topology at storm depth; ratio
  measured at a size the legacy core can still complete, then the SoA
  core alone at full size against a CI wall-clock budget.
* ``geo/million-event`` -- >= 1M-event schedule through the SoA core
  against a CI budget (history recording off: 1M+ DeliverMessage
  dataclasses are bookkeeping no oracle reads). The legacy core's
  cost is quadratic in backlog depth (measured slope reported from
  the 1000-zone row); it does not complete this schedule in useful
  time and is not timed here.
* ``fifo/deep-wave`` and ``fifo/shallow-wave`` -- context rows, no
  gate: plain FIFO waves at overload-queue depth (legacy pays an
  O(depth) pointer memmove per frame) and at chaos-soak depth (the
  legacy remove hits index 0; both cores are handler-bound, ~1x --
  reported so the headline can't be mistaken for a claim about
  shallow buffers).

Methodology (overload_lt calibration, docs/BENCH_HISTORY.md): the
gate workload alternates the two arms in identical per-round chunks
with GC disabled and warm-up rounds discarded, and the ratio is the
median over independent blocks. Before timing, both arms replay a
reduced storm with history on and must produce BYTE-IDENTICAL
delivery histories (the golden-equivalence contract of
tests/test_sim_core.py, re-asserted on every bench run).

Run::

    python -m frankenpaxos_tpu.bench.sim_core_ab \
        --out bench_results/sim_core_ab.json

``--smoke`` runs the CI-sized variant (reduced rounds, same storm
depth, gates enforced at the reduced size).
"""

from __future__ import annotations

import argparse
import gc
import json
import random
import statistics
import time

from frankenpaxos_tpu.geo.topology import GeoTopology
from frankenpaxos_tpu.geo.transport import GeoSimTransport
from frankenpaxos_tpu.runtime.actor import Actor
from frankenpaxos_tpu.runtime.logger import Logger
from frankenpaxos_tpu.runtime.sim_legacy import (
    LegacyGeoSimTransport,
    LegacySimTransport,
)
from frankenpaxos_tpu.runtime.sim_transport import SimTransport


class _NullLogger(Logger):
    def debug(self, m):
        pass

    def info(self, m):
        pass

    def warn(self, m):
        pass

    def error(self, m):
        pass

    def fatal(self, m):
        raise RuntimeError(m)


class _RawSerializer:
    """Identity codec: sink payloads are opaque bytes, so neither arm
    pays pickle and the A/B isolates the delivery machinery."""

    def to_bytes(self, m):
        return m

    def from_bytes(self, d):
        return d


_ECHO = 1  # payload flag: re-send one hop to a deterministic peer


class StormSink(Actor):
    """Counts deliveries; frames flagged ``_ECHO`` re-send one hop to
    a deterministic peer (cross-zone chatter). The ``receive_batch``
    override is the SoA-native path the wave engine exploits; the
    legacy core delivers per message through ``receive``. Both paths
    process frames in arrival order, so the two arms stay
    schedule-identical."""

    serializer = _RawSerializer()

    def __init__(self, address, transport, logger, peers, index):
        super().__init__(address, transport, logger)
        self.peers = peers
        self.index = index
        self.n = 0
        self.drains = 0

    def _react(self, data):
        if data[0] == _ECHO:
            hop = (self.index + data[1]) % len(self.peers)
            self.send(self.peers[hop], bytes((0, data[1])))

    def receive(self, src, data):
        self.n += 1
        self._react(data)

    def receive_batch(self, batch):
        self.n += len(batch)
        react = self._react
        for _, data in batch:
            react(data)

    def on_drain(self):
        self.drains += 1


class GeoStorm:
    """One arm of the geo storm: a jittered multi-region topology,
    per-zone sinks, and a deterministic per-round schedule -- burst
    sends to pseudo-random zones (a slice flagged to echo one hop),
    partition/heal cycles on a rotating link pair, and a short
    ``run_for`` so a multi-round backlog stays in flight (the
    resend-storm regime of the geo-chaos soaks)."""

    def __init__(self, transport_cls, zones: int, burst: int,
                 seed: int = 0, dwell_s: float = 0.003,
                 record_history: bool = False):
        per_region = 10 if zones >= 100 else 3
        regions = {f"r{i}": [f"z{i}-{j}" for j in range(per_region)]
                   for i in range(zones // per_region)}
        self.topology = GeoTopology(regions, seed=seed)
        self.transport = transport_cls(self.topology, _NullLogger())
        self.transport.record_history = record_history
        self.burst = burst
        self.dwell_s = dwell_s
        self.rng = random.Random(f"sim_core_ab|{seed}")
        self.peers = [f"sink-{i}" for i in range(len(self.topology.zones))]
        self.sinks = [
            StormSink(addr, self.transport, self.transport.logger,
                      self.peers, i)
            for i, addr in enumerate(self.peers)]
        for sink, zone in zip(self.sinks, self.topology.zones):
            self.topology.place(sink.address, zone)
        self.topology.place("driver", self.topology.zones[0])
        self.round = 0

    def run_round(self) -> None:
        r = self.round
        self.round += 1
        rng = self.rng
        send = self.transport.send
        n = len(self.peers)
        for k in range(self.burst):
            flag = _ECHO if k % 4 == 0 else 0
            send("driver", self.peers[rng.randrange(n)],
                 bytes((flag, rng.randrange(7))))
        zones = self.topology.zones
        if r % 20 == 4:
            a = zones[r % len(zones)]
            b = zones[(r * 7 + 3) % len(zones)]
            if a != b:
                self.topology.partition_link(a, b)
        if r % 20 == 14:
            self.topology.heal_all()
        self.transport.run_for(self.dwell_s)

    def finish(self) -> int:
        self.topology.heal_all()
        self.transport.run_until_quiescent()
        return sum(s.n for s in self.sinks)


def _projection(transport) -> list:
    from frankenpaxos_tpu.runtime.sim_transport import DeliverMessage

    return [(c.message.id, str(c.message.src), str(c.message.dst),
             bytes(c.message.data))
            for c in transport.history if isinstance(c, DeliverMessage)]


def golden_equivalence(rounds: int = 40, burst: int = 100) -> bool:
    """Reduced storm, history on, both arms: byte-identical delivered
    schedules (asserted -- a silent divergence would invalidate every
    ratio below)."""
    projections = []
    for cls in (LegacyGeoSimTransport, GeoSimTransport):
        storm = GeoStorm(cls, zones=9, burst=burst, seed=5,
                         record_history=True)
        for _ in range(rounds):
            storm.run_round()
        storm.finish()
        projections.append(_projection(storm.transport))
    assert projections[0] == projections[1], \
        "legacy/SoA delivery schedules diverged"
    assert len(projections[0]) > rounds * burst // 2
    return True


def measure_storm_block(rounds: int, burst: int, zones: int,
                        warmup: int, seed: int) -> dict:
    """One chunk-interleaved block: two persistent storms (legacy /
    SoA) driven alternately one round at a time with GC disabled, arm
    order flipped every round; returns summed per-arm seconds and the
    per-arm delivered totals (must match)."""
    storms = {
        "legacy": GeoStorm(LegacyGeoSimTransport, zones, burst,
                           seed=seed),
        "soa": GeoStorm(GeoSimTransport, zones, burst, seed=seed),
    }
    total = {"legacy": 0.0, "soa": 0.0}
    gc.collect()
    gc.disable()
    try:
        for r in range(warmup + rounds):
            order = (("legacy", "soa") if r % 2 else ("soa", "legacy"))
            for arm in order:
                t0 = time.perf_counter()
                storms[arm].run_round()
                elapsed = time.perf_counter() - t0
                if r >= warmup:
                    total[arm] += elapsed
    finally:
        gc.enable()
    events = {arm: storm.finish() for arm, storm in storms.items()}
    assert events["legacy"] == events["soa"], events
    return {"seconds": total, "events": events["soa"],
            "timed_events": events["soa"] * rounds // (warmup + rounds)}


def bench_storm(rounds: int, burst: int, zones: int, blocks: int,
                warmup: int) -> dict:
    ratios = []
    per_block = []
    events = timed = 0
    for b in range(blocks):
        block = measure_storm_block(rounds, burst, zones, warmup,
                                    seed=b)
        ratio = block["seconds"]["legacy"] / block["seconds"]["soa"]
        ratios.append(ratio)
        events = block["events"]
        timed = block["timed_events"]
        per_block.append({
            "legacy_s": round(block["seconds"]["legacy"], 3),
            "soa_s": round(block["seconds"]["soa"], 3),
            "ratio": round(ratio, 2),
        })
    ratios.sort()
    return {
        "zones": zones,
        "rounds_per_block": rounds,
        "burst_per_round": burst,
        "events_per_arm_per_block": events,
        "timed_events_per_arm_per_block": timed,
        "blocks": per_block,
        "ratio_median": round(statistics.median(ratios), 2),
        "ratio_range": [round(ratios[0], 2), round(ratios[-1], 2)],
    }


def bench_big_geo(zones: int, burst: int, rounds: int,
                  legacy_rounds: int) -> dict:
    """SoA core at full size against wall clock; legacy at a reduced
    round count for the ratio (its per-event cost grows with backlog
    depth, so the full-size ratio would only be LARGER -- recorded as
    a lower bound)."""
    gc.collect()
    results = {}
    for arm, cls, arm_rounds in (
            ("soa", GeoSimTransport, rounds),
            ("legacy", LegacyGeoSimTransport, legacy_rounds)):
        storm = GeoStorm(cls, zones=zones, burst=burst, seed=11)
        gc.disable()
        try:
            t0 = time.perf_counter()
            for _ in range(arm_rounds):
                storm.run_round()
            n = storm.finish()
            dt = time.perf_counter() - t0
        finally:
            gc.enable()
        results[arm] = {"rounds": arm_rounds, "events": n,
                        "seconds": round(dt, 2),
                        "events_per_s": round(n / dt)}
    ratio = (results["soa"]["events_per_s"]
             / results["legacy"]["events_per_s"])
    return {
        "zones": zones,
        "soa_full": results["soa"],
        "legacy_reduced": results["legacy"],
        "events_per_s_ratio_at_reduced_size_lower_bound": round(ratio, 1),
    }


def bench_million(zones: int, events_target: int, burst: int) -> dict:
    """>= ``events_target`` delivered frames through the SoA core
    (history off); the legacy core is quadratic in backlog depth at
    this scale and is not timed (see the 1000-zone row's reduced-size
    ratio for its measured slope)."""
    storm = GeoStorm(GeoSimTransport, zones=zones, burst=burst,
                     seed=13, dwell_s=0.02)
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        while True:
            storm.run_round()
            # Sends are >= deliveries-to-come; stop bursting once
            # enough frames are in the schedule, then drain.
            if storm.round * burst >= events_target:
                break
        n = storm.finish()
        dt = time.perf_counter() - t0
    finally:
        gc.enable()
    return {"zones": zones, "events": n, "seconds": round(dt, 2),
            "events_per_s": round(n / dt)}


# --- plain-FIFO context rows (no gate) ------------------------------------


class FifoSink(Actor):
    serializer = _RawSerializer()

    def __init__(self, address, transport, logger):
        super().__init__(address, transport, logger)
        self.n = 0

    def receive(self, src, data):
        self.n += 1

    def receive_batch(self, batch):
        self.n += len(batch)


def bench_fifo(depth: int, total_events: int) -> dict:
    out = {}
    for arm, cls in (("legacy", LegacySimTransport),
                     ("soa", SimTransport)):
        t = cls(_NullLogger())
        sinks = [FifoSink(f"s{i}", t, t.logger) for i in range(13)]
        payload = b"\x00" * 24
        reps = max(1, total_events // depth)
        gc.collect()
        gc.disable()
        try:
            t0 = time.perf_counter()
            for _ in range(reps):
                for i in range(depth):
                    t.send("c", f"s{i % 13}", payload)
                t.deliver_all_coalesced()
            dt = time.perf_counter() - t0
        finally:
            gc.enable()
        n = sum(s.n for s in sinks)
        out[arm] = {"events": n, "seconds": round(dt, 2),
                    "events_per_s": round(n / dt)}
    out["ratio"] = round(out["soa"]["events_per_s"]
                         / out["legacy"]["events_per_s"], 2)
    out["wave_depth"] = depth
    return out


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None)
    parser.add_argument("--smoke", action="store_true",
                        help="CI-sized run: fewer rounds/blocks at the "
                             "same storm depth, gates enforced")
    args = parser.parse_args(argv)

    if args.smoke:
        storm_rounds, blocks, warmup = 60, 3, 4
        big_zones, big_rounds, big_legacy_rounds = 300, 60, 8
        million_target = 120_000
        budget_big_s, budget_million_s = 120.0, 120.0
    else:
        # Soak scale: 500 timed rounds x 250-frame bursts per block =
        # the 500x250 chaos-soak event volume per arm per block.
        storm_rounds, blocks, warmup = 500, 3, 10
        big_zones, big_rounds, big_legacy_rounds = 1000, 120, 10
        million_target = 1_000_000
        budget_big_s, budget_million_s = 180.0, 300.0

    golden = golden_equivalence()

    storm = bench_storm(rounds=storm_rounds, burst=250, zones=12,
                        blocks=blocks, warmup=warmup)
    storm["gate"] = ">= 10x events/s over the legacy core"
    storm["gate_passed"] = storm["ratio_median"] >= 10.0

    big = bench_big_geo(zones=big_zones, burst=500, rounds=big_rounds,
                        legacy_rounds=big_legacy_rounds)
    big["budget_s"] = budget_big_s
    big["gate"] = (f"{big_zones}-zone storm completes within "
                   f"{budget_big_s:.0f}s on the SoA core")
    big["gate_passed"] = big["soa_full"]["seconds"] <= budget_big_s

    million = bench_million(zones=big_zones, events_target=million_target,
                            burst=5000)
    million["budget_s"] = budget_million_s
    million["gate"] = (f">= {million_target} events within "
                       f"{budget_million_s:.0f}s on the SoA core")
    million["gate_passed"] = (million["events"] >= million_target
                              and million["seconds"]
                              <= budget_million_s)

    fifo_deep = bench_fifo(depth=32768, total_events=131072)
    fifo_shallow = bench_fifo(depth=250, total_events=100_000)

    summary = {
        "benchmark": "sim_core_ab",
        "legacy_arm": "runtime/sim_legacy.py (verbatim pre-paxsim "
                      "delivery machinery)",
        "methodology": (
            "core-isolated: raw-bytes sink actors so the measurement "
            "is delivery machinery, not handlers; gate workload uses "
            "alternating per-round chunks with GC disabled, warm-up "
            "discarded, median ratio over independent blocks "
            "(overload_lt calibration); both arms verified "
            "byte-identical on a reduced schedule first"),
        "smoke": bool(args.smoke),
        "golden_equivalent": golden,
        "geo_storm_soak_scale": storm,
        "geo_1000_zones" if not args.smoke else "geo_300_zones": big,
        "geo_million_event" if not args.smoke else "geo_120k_event":
            million,
        "context_fifo_deep_wave": {
            **fifo_deep,
            "note": "plain FIFO at overload-queue depth; legacy pays "
                    "an O(depth) pointer memmove per frame",
        },
        "context_fifo_shallow_wave": {
            **fifo_shallow,
            "note": "chaos-soak depth: legacy remove hits index 0; "
                    "both cores handler-bound -- the headline gate is "
                    "about storm backlogs, not shallow buffers",
        },
        "gate_passed": bool(storm["gate_passed"] and big["gate_passed"]
                            and million["gate_passed"]),
    }
    print(json.dumps({k: v for k, v in summary.items()
                      if not k.startswith("context")}, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
            f.write("\n")
    if not summary["gate_passed"]:
        raise SystemExit(1)
    return summary


if __name__ == "__main__":
    main()
