"""ingest_lt: paired A/B of the paxingest wire-to-device plane vs the
current paxwire per-message path (docs/TRANSPORT.md).

    python -m frankenpaxos_tpu.bench.ingest_lt \
        --out bench_results/ingest_lt.json

Methodology (the transport_lt paired-arm shape one layer up): per
in-flight width, the SAME closed-loop SoA client tier drives real-TCP
transports in one process against two server-side ingestion planes:

  * ``paxwire`` (baseline -- today's deployed path): coalesced client
    arrays arrive at a LEADER-EDGE sink that does exactly what the
    run-pipeline leader does per command today -- the codec decodes
    every command into Python objects, the handler rebuilds the value
    tuple, the proposal re-encodes it for the proxy fan-out, and
    per-entry reply arrays ack each client. One Python object and one
    codec pass PER COMMAND.
  * ``ingest``: the same client bytes flow through a real
    ``IngestBatcher`` (wire-sink column scan, no per-message objects)
    into a sink consuming ``IngestRun`` descriptors: slot assignment
    and the proxy-bound re-encode touch only run METADATA (the value
    bytes forward as a raw copy), and acks are built from the SoA
    columns with numpy -- no ``Command`` ever materializes.

Both arms run the identical client tier (pre-encoded tag-115 arrays,
reply counting through a wire sink) and identical excluded costs (SM
execution and the acceptor RTT are downstream of the ingestion plane
and identical in both worlds), so the measured segment is exactly
recv() -> ordered proposal bytes + client acks. Recorded per arm:
cmds/s, syscalls/cmd (the transports' writev/write counters), and
Python-bytes/cmd (bytes passing through per-message Python codec
loops on the server side: the baseline counts its full decode+reencode
stream, the ingest arm only run metadata -- raw value segments that
forward untouched are not Python-touched bytes).

The batcher-off overhead clause reuses the overload_lt calibration:
alternating ~chunk closed-loop blocks between the live baseline and a
verbatim pre-ingest transport dispatch (no wire-sink check), GC off,
median ratio over blocks -- the ingest machinery must cost nothing
when unused.

Committed gates (ISSUE 15 acceptance):
  * ingest/paxwire throughput >= 10x at every width >= 1024;
  * batcher-off overhead < 3%.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import socket
import statistics
import struct
import threading
import time

import numpy as np

from frankenpaxos_tpu import native
from frankenpaxos_tpu.ingest import (
    IngestBatcher,
    IngestRun,
    MultiPaxosIngestRouter,
    value_view,
)
import frankenpaxos_tpu.protocols.multipaxos  # noqa: F401 (codecs)
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    CommandBatch,
    Phase2aRun,
)
from frankenpaxos_tpu.protocols.multipaxos.wire import _put_address
from frankenpaxos_tpu.runtime import FakeLogger
from frankenpaxos_tpu.runtime.actor import Actor
from frankenpaxos_tpu.runtime.logger import LogLevel
from frankenpaxos_tpu.runtime.serializer import DEFAULT_SERIALIZER
from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport

WIDTHS = (256, 1024, 4096)
PAYLOAD = b"w" * 10
_CLIENT_ARRAY_TAG = 115
_REPLY_ARRAY_TAG = 118
_I32 = struct.Struct("<i")

_ENTRY_DTYPE = np.dtype([("pseudonym", "<i8"), ("id", "<i8"),
                         ("len", "<i4"),
                         ("payload", "S%d" % len(PAYLOAD))])
_REPLY_DTYPE = np.dtype([("pseudonym", "<i8"), ("id", "<i8"),
                         ("slot", "<i8"), ("len", "<i4")])


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Acks:
    __slots__ = ("count",)

    def __init__(self, count: int):
        self.count = count


def _parse_reply_array(data) -> _Acks:
    if len(data) < 5 or data[0] != _REPLY_ARRAY_TAG:
        return None
    (n,) = _I32.unpack_from(data, 1)
    return _Acks(n)


def _parse_reply_batch(data) -> "_Acks | None":
    total = 0
    for s, e in native.scan_batch(data, 2):
        if e - s < 5 or data[s] != _REPLY_ARRAY_TAG:
            return None
        (n,) = _I32.unpack_from(data, s + 1)
        total += n
    return _Acks(total)


class SoAClient(Actor):
    """Closed-loop SoA load client: ``width`` commands in flight, acks
    counted through a wire sink without decoding reply entries.

    ``singles=False`` (the ingest arms): refills ship as pre-encoded
    tag-115 ClientRequestArray wire bytes, one numpy ``tobytes`` per
    slice. ``singles=True`` (the baseline): refills ship as
    per-command tag-4 ClientRequest frames from a pre-encoded pool --
    the deployed fan-in reality this plane attacks (1024 independent
    sessions hold ~1 op each; cross-client batching is exactly what
    client-side coalescing cannot do), priced GENEROUSLY cheap (no
    per-op codec encode, which today's client does pay)."""

    def __init__(self, address, transport, logger, dst, width,
                 singles=False):
        super().__init__(address, transport, logger)
        self.dst = dst
        self.width = width
        self.singles = singles
        self._pool = []
        self.total = 0
        self.sent = 0
        self.acked = 0
        self.done = threading.Event()
        addr_bytes = bytearray()
        _put_address(addr_bytes, address)
        self._addr_bytes = bytes(addr_bytes)
        self._template = np.zeros(width, dtype=_ENTRY_DTYPE)
        self._template["pseudonym"] = np.arange(width)
        self._template["len"] = len(PAYLOAD)
        self._template["payload"] = PAYLOAD
        self.wire_sinks = {
            _REPLY_ARRAY_TAG: (_parse_reply_array, self._on_acks),
            150: (_parse_reply_batch, self._on_acks),
        }

    def begin(self, total: int) -> None:
        self.total = total
        self.sent = 0
        self.acked = 0
        self.done.clear()
        if self.singles and len(self._pool) < total:
            # Pre-encode the whole chunk's single-request payloads
            # OUTSIDE the measured window (the load generator must not
            # cap the plane under test; today's real client additionally
            # pays a codec encode per op).
            template = (bytes((4,)) + self._addr_bytes
                        + struct.pack("<qq", 0, 0)
                        + _I32.pack(len(PAYLOAD)) + PAYLOAD)
            id_off = len(self._addr_bytes) + 9
            head, tail = template[:id_off], template[id_off + 8:]
            self._pool = [head + struct.pack("<q", i) + tail
                          for i in range(total)]
        self.transport.loop.call_soon_threadsafe(self._issue,
                                                 self.width)

    #: Refill slice: the in-flight window ships as several arrays so
    #: acks of one slice overlap the others in flight (a single
    #: window-sized array would serialize the closed loop on one
    #: round trip).
    SLICE = 256

    def _issue(self, k: int) -> None:
        k = min(k, self.total - self.sent)
        if k <= 0:
            return
        if self.singles:
            send = self.transport.send
            for data in self._pool[self.sent:self.sent + k]:
                send(self.address, self.dst, data)
            self.sent += k
            return
        while k > 0:
            step = min(k, self.SLICE)
            entries = self._template[:step].copy()
            entries["id"] = np.arange(self.sent, self.sent + step)
            payload = (bytes((_CLIENT_ARRAY_TAG,)) + self._addr_bytes
                       + _I32.pack(step) + entries.tobytes())
            self.sent += step
            k -= step
            self.transport.send(self.address, self.dst, payload)

    def _on_acks(self, src, acks: _Acks) -> None:
        self.acked += acks.count
        if self.acked >= self.total:
            self.done.set()
        else:
            self._issue(acks.count)

    def receive(self, src, message) -> None:
        # Fallback path (sink declined): count decoded reply arrays.
        entries = getattr(message, "entries", None)
        if entries is not None:
            self._on_acks(src, _Acks(len(entries)))


def _prom_collectors():
    """A fresh prometheus registry per system -- deployed roles run
    with /metrics on in every committed bench, so BOTH arms pay the
    real per-message (baseline) / per-run (ingest) metrics cost."""
    import prometheus_client

    from frankenpaxos_tpu.runtime.monitoring import (
        PrometheusCollectors,
    )

    return PrometheusCollectors(
        registry=prometheus_client.CollectorRegistry())


class DecodingLeaderSink(Actor):
    """The baseline leader edge -- today's per-message Python,
    faithful to the deployed Leader's receive stack: the codec decoded
    every command into objects upstream, the metrics wrapper times and
    counts each message (LeaderOptions.measure_latencies, on in every
    committed deployed bench), singles propose one Phase2a each /
    arrays one Phase2aRun (exactly _handle_client_request /
    _handle_client_request_array), and replies coalesce per client per
    drain like the replicas' ClientReplyArray path."""

    def __init__(self, address, transport, logger):
        super().__init__(address, transport, logger)
        collectors = _prom_collectors()
        self.metrics_latency = collectors.summary(
            "ingest_lt_leader_requests_latency_seconds",
            labels=("type",))
        self.metrics_requests = collectors.counter(
            "ingest_lt_leader_requests_total", labels=("type",))
        self.next_slot = 0
        self.stat_cmds = 0
        self.stat_py_bytes = 0
        self._pending_replies: dict = {}

    def receive(self, src, message) -> None:
        with self.metrics_latency.labels(
                type(message).__name__).time():
            self.metrics_requests.labels(type(message).__name__).inc()
            self._handle(src, message)

    def _handle(self, src, message) -> None:
        commands = getattr(message, "commands", None)
        if commands is None:  # a bare ClientRequest: one proposal each
            command = message.command
            from frankenpaxos_tpu.protocols.multipaxos.messages import (
                Phase2a,
            )

            proposal = DEFAULT_SERIALIZER.to_bytes(Phase2a(
                slot=self.next_slot, round=0,
                value=CommandBatch((command,))))
            self._note(src, (command,), 1, 2 * len(proposal))
            return
        values = tuple(CommandBatch((c,)) for c in commands)
        run = Phase2aRun(start_slot=self.next_slot, round=0,
                         values=values)
        proposal = DEFAULT_SERIALIZER.to_bytes(run)
        self._note(src, commands, len(commands), 2 * len(proposal))

    def _note(self, src, commands, n: int, py_bytes: int) -> None:
        slot = self.next_slot
        self.next_slot += n
        self.stat_cmds += n
        # The decode stream (~= the proposal re-encode, same content)
        # plus the re-encode both passed through per-message Python.
        self.stat_py_bytes += py_bytes
        for i, command in enumerate(commands):
            cid = command.command_id
            self._pending_replies.setdefault(
                cid.client_address, []).append(
                    (cid.client_pseudonym, cid.client_id, slot + i))

    def on_drain(self) -> None:
        pending, self._pending_replies = self._pending_replies, {}
        for address, entries in pending.items():
            out = bytearray((_REPLY_ARRAY_TAG,))
            out += _I32.pack(len(entries))
            for pseudonym, client_id, slot in entries:
                out += struct.pack("<qqq", pseudonym, client_id, slot)
                out += _I32.pack(0)
            self.stat_py_bytes += len(out)
            self.transport.send(self.address, address, bytes(out))


class DescriptorLeaderSink(Actor):
    """The ingest leader edge: run descriptors in, raw-copy proposal
    out, numpy-built acks from the SoA columns. The same metrics
    discipline as the baseline -- per MESSAGE, which is now per RUN."""

    def __init__(self, address, transport, logger):
        super().__init__(address, transport, logger)
        collectors = _prom_collectors()
        self.metrics_latency = collectors.summary(
            "ingest_lt_leader_requests_latency_seconds",
            labels=("type",))
        self.metrics_requests = collectors.counter(
            "ingest_lt_leader_requests_total", labels=("type",))
        self.next_slot = 0
        self.stat_cmds = 0
        self.stat_py_bytes = 0

    def receive(self, src, message) -> None:
        if not isinstance(message, IngestRun):
            return
        with self.metrics_latency.labels("IngestRun").time():
            self.metrics_requests.labels("IngestRun").inc()
            self._handle(src, message)

    def _handle(self, src, message) -> None:
        values = message.values
        n = len(values)
        run = Phase2aRun(start_slot=self.next_slot, round=0,
                         values=values)
        self.next_slot += n
        proposal = DEFAULT_SERIALIZER.to_bytes(run)  # raw copy
        view = value_view(values)
        if view is None:
            # Exotic run (tuple values): decode like the baseline.
            values = tuple(values)
            per_client: dict = {}
            for i, value in enumerate(values):
                cid = value.commands[0].command_id
                per_client.setdefault(cid.client_address, []).append(
                    (cid.client_pseudonym, cid.client_id))
            for address, entries in per_client.items():
                out = bytearray((_REPLY_ARRAY_TAG,))
                out += _I32.pack(len(entries))
                for pseudonym, client_id in entries:
                    out += struct.pack("<qqq", pseudonym, client_id, 0)
                    out += _I32.pack(0)
                self.transport.send(self.address, address, bytes(out))
            self.stat_cmds += n
            self.stat_py_bytes += len(proposal)
            return
        cols = view.cols
        addresses = view.addresses()
        reply = np.zeros(n, dtype=_REPLY_DTYPE)
        reply["pseudonym"] = cols[:, 1]
        reply["id"] = cols[:, 2]
        reply["slot"] = np.arange(self.next_slot - n, self.next_slot)
        meta_bytes = 0
        for idx in np.unique(cols[:, 0]):
            rows = reply[cols[:, 0] == idx]
            payload = (bytes((_REPLY_ARRAY_TAG,))
                       + _I32.pack(len(rows)) + rows.tobytes())
            meta_bytes += 5
            self.transport.send(self.address, addresses[int(idx)],
                                payload)
        self.stat_cmds += n
        # Python-touched bytes: the run's METADATA only -- the value
        # segment inside `proposal` is an untouched raw copy.
        raw = getattr(values, "raw", b"")
        self.stat_py_bytes += (len(proposal) - len(raw)) + meta_bytes


class _System:
    """One arm's live transports + actors."""

    def __init__(self, arm: str, width_total: int, num_clients: int,
                 transport_cls=TcpTransport):
        self.arm = arm
        logger = FakeLogger(LogLevel.FATAL)
        self.transports = []

        def make_transport(address):
            t = transport_cls(address, logger)
            t.start()
            self.transports.append(t)
            return t

        sink_addr = ("127.0.0.1", _free_port())
        sink_t = make_transport(sink_addr)
        if arm == "ingest":
            self.sink = DescriptorLeaderSink(sink_addr, sink_t, logger)
            batcher_addr = ("127.0.0.1", _free_port())
            batcher_t = make_transport(batcher_addr)

            class _Cfg:
                num_leaders = 1
                leader_addresses = [sink_addr]

            from frankenpaxos_tpu.ingest import IngestBatcherOptions

            # flush_period_s=0: on a TCP loop on_drain always flushes,
            # so the safety-net timer is pure (re)arm churn here.
            self.batcher = IngestBatcher(
                batcher_addr, batcher_t, logger,
                MultiPaxosIngestRouter(_Cfg), index=0,
                options=IngestBatcherOptions(flush_period_s=0.0))
            client_dst = batcher_addr
        else:
            self.sink = DecodingLeaderSink(sink_addr, sink_t, logger)
            client_dst = sink_addr
        client_t = make_transport(("127.0.0.1", _free_port()))
        width = max(width_total // num_clients, 1)
        self.clients = []
        for _ in range(num_clients):
            address = ("127.0.0.1", _free_port())
            client_t.listen_on(address)
            self.clients.append(SoAClient(
                address, client_t, logger, client_dst, width,
                singles=(arm == "paxwire")))

    def run_chunk(self, cmds_per_client: int) -> float:
        for client in self.clients:
            client.begin(cmds_per_client)
        t0 = time.perf_counter()
        for client in self.clients:
            if not client.done.wait(timeout=120):
                raise RuntimeError(
                    f"{self.arm} arm wedged: "
                    f"{client.acked}/{client.total} acked")
        return time.perf_counter() - t0

    def stats(self) -> dict:
        return {
            "syscalls": sum(t.stat_syscalls for t in self.transports),
            "cmds": self.sink.stat_cmds,
            "py_bytes": self.sink.stat_py_bytes,
        }

    def stop(self) -> None:
        for t in self.transports:
            t.stop()


def run_arm(arm: str, width: int, total: int, num_clients: int,
            transport_cls=TcpTransport) -> dict:
    system = _System(arm, width, num_clients,
                     transport_cls=transport_cls)
    try:
        per_client = total // num_clients
        # Warm-up (connections, allocator) then the measured chunk.
        system.run_chunk(max(per_client // 10, system.clients[0].width))
        before = system.stats()
        elapsed = system.run_chunk(per_client)
        after = system.stats()
        cmds = after["cmds"] - before["cmds"]
        syscalls = after["syscalls"] - before["syscalls"]
        py_bytes = after["py_bytes"] - before["py_bytes"]
        return {
            "arm": arm,
            "in_flight": width,
            "num_commands": cmds,
            "elapsed_s": elapsed,
            "cmds_per_s": cmds / elapsed,
            "syscalls_per_cmd": syscalls / max(cmds, 1),
            "python_bytes_per_cmd": py_bytes / max(cmds, 1),
        }
    finally:
        system.stop()


def run_pair(width: int, total: int, reps: int,
             num_clients: int) -> dict:
    best: dict = {}
    for rep in range(reps):
        arms = (("paxwire", "ingest") if rep % 2 == 0
                else ("ingest", "paxwire"))
        for arm in arms:
            stats = run_arm(arm, width, total, num_clients)
            if arm not in best or stats["cmds_per_s"] \
                    > best[arm]["cmds_per_s"]:
                best[arm] = stats
    pair = dict(best)
    pair["throughput_ratio"] = (best["ingest"]["cmds_per_s"]
                                / best["paxwire"]["cmds_per_s"])
    pair["python_bytes_reduction"] = (
        best["paxwire"]["python_bytes_per_cmd"]
        / max(best["ingest"]["python_bytes_per_cmd"], 1e-9))
    return pair


# --- batcher-off overhead ----------------------------------------------------
# A verbatim pre-ingest _dispatch_frame (no wire-sink check) on a
# TcpTransport subclass: the control arm of the alternating-chunk
# overhead block. Kept byte-faithful to the pre-PR dispatch so the A/B
# isolates exactly the ingest machinery's disabled-path cost.


class _PreIngestTransport(TcpTransport):
    def _dispatch_frame(self, buf, start, end, local):
        import struct as _struct

        from frankenpaxos_tpu.obs.trace import TraceContext
        from frankenpaxos_tpu.runtime import paxwire

        _LEN = _struct.Struct(">I")
        try:
            (hlen,) = _LEN.unpack_from(buf, start)
            if hlen > end - start - 4:
                raise ValueError(
                    f"header length {hlen} exceeds frame "
                    f"payload {end - start - 4}")
            header = bytes(buf[start + 4:start + 4 + hlen]).decode()
            addr_part, _, trace_part = header.partition("|")
            host, _, port = addr_part.rpartition(":")
            src = (host, int(port))
            ctx = (TraceContext.decode(trace_part)
                   if trace_part else None)
            data = bytes(buf[start + 4 + hlen:end])
            if paxwire.is_batch_payload(data):
                segments = paxwire.split_batch(data)
            else:
                segments = (data,)
            deliveries = []
            for segment in segments:
                delivery = self._decode(local, src, segment)
                if delivery is not None:
                    deliveries.append(delivery)
        except Exception as e:
            self.logger.error(
                f"dropping connection on corrupt frame: {e!r}")
            return False
        for delivery in deliveries:
            self._deliver(*delivery, ctx)
        return True


def measure_overhead(width: int, blocks: int, chunk: int,
                     num_clients: int) -> dict:
    """Alternating-chunk, GC-off A/A' of the BASELINE workload: live
    dispatch (with the unused wire-sink check) vs the verbatim
    pre-ingest dispatch. Median per-block ratio gates < 3%."""
    live = _System("paxwire", width, num_clients)
    control = _System("paxwire", width, num_clients,
                      transport_cls=_PreIngestTransport)
    # Clients keep their default sinks in both arms; only the SERVER
    # transports differ -- disable the client-side sink symmetrically
    # so the control truly runs the pre-ingest dispatch end to end.
    for system in (live, control):
        for client in system.clients:
            client.wire_sinks = None
    ratios = []
    gc_was_enabled = gc.isenabled()
    gc.disable()
    try:
        for system in (live, control):  # warm-up both
            system.run_chunk(chunk)
            system.run_chunk(chunk)
        for block in range(blocks):
            # Alternate chunk order so frequency/cache drift lands on
            # both arms equally (overload_lt calibration).
            first, second = ((live, control) if block % 2 == 0
                             else (control, live))
            t_first = first.run_chunk(chunk)
            t_second = second.run_chunk(chunk)
            ratios.append(t_first / t_second if first is live
                          else t_second / t_first)
    finally:
        if gc_was_enabled:
            gc.enable()
        live.stop()
        control.stop()
    median = statistics.median(ratios)
    return {
        "blocks": ratios,
        "median_ratio": median,
        "overhead_pct": (median - 1.0) * 100.0,
        "passed": median < 1.03,
    }


def main(argv=None) -> dict:
    parser = argparse.ArgumentParser(
        description="paxingest wire-to-device A/B (docs/TRANSPORT.md)")
    parser.add_argument("--out", default=None)
    parser.add_argument("--smoke", action="store_true",
                        help="reduced widths/commands (~1 min)")
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument("--num_clients", type=int, default=4)
    args = parser.parse_args(argv)

    widths = (1024,) if args.smoke else WIDTHS
    reps = 1 if args.smoke else args.reps
    pairs: dict = {}
    for width in widths:
        total = min(max(width * 40, 40000),
                    60000 if args.smoke else 200000)
        pairs[width] = run_pair(width, total, reps, args.num_clients)
        p = pairs[width]
        print(f"in_flight={width:5d}: paxwire "
              f"{p['paxwire']['cmds_per_s']:9.0f}/s "
              f"ingest {p['ingest']['cmds_per_s']:9.0f}/s "
              f"ratio {p['throughput_ratio']:.2f}x  "
              f"py-bytes/cmd "
              f"{p['paxwire']['python_bytes_per_cmd']:.0f}->"
              f"{p['ingest']['python_bytes_per_cmd']:.1f}  "
              f"syscalls/cmd "
              f"{p['paxwire']['syscalls_per_cmd']:.4f}->"
              f"{p['ingest']['syscalls_per_cmd']:.4f}")
    overhead = measure_overhead(
        width=256, blocks=3 if args.smoke else 7,
        chunk=2000 if args.smoke else 5000,
        num_clients=args.num_clients)
    print(f"batcher-off overhead: {overhead['overhead_pct']:+.2f}% "
          f"(median of {len(overhead['blocks'])} blocks)")
    gate_widths = {w: pairs[w]["throughput_ratio"]
                   for w in pairs if w >= 1024}
    gates = {
        "throughput_ratio_at_ge_1024": {
            str(w): r for w, r in gate_widths.items()},
        "throughput_10x_passed": all(r >= 10.0
                                     for r in gate_widths.values()),
        "overhead_pct": overhead["overhead_pct"],
        "overhead_passed": overhead["passed"],
    }
    gates["gate_passed"] = (gates["throughput_10x_passed"]
                            and gates["overhead_passed"])
    result = {
        "benchmark": "ingest_lt",
        "methodology": (
            "paired real-TCP closed-loop A/B in one process "
            "(transport_lt shape one layer up): identical SoA client "
            "tiers (pre-encoded tag-115 arrays, sink-counted acks) "
            "drive (a) the paxwire baseline -- a leader-edge sink "
            "doing today's per-command decode/re-encode/reply -- and "
            "(b) the ingest plane: real IngestBatcher (wire-sink "
            "column scan) -> IngestRun descriptors -> raw-copy "
            "proposal + numpy acks. SM execution and acceptor RTT are "
            "identical in both worlds and excluded from both arms. "
            "python_bytes_per_cmd counts bytes through per-message "
            "Python codec loops server-side. Overhead: alternating-"
            "chunk GC-off baseline vs verbatim pre-ingest dispatch, "
            "median over blocks (overload_lt calibration)."),
        "smoke": bool(args.smoke),
        "reps": reps,
        "num_clients": args.num_clients,
        "pairs": {str(w): pairs[w] for w in sorted(pairs)},
        "overhead": overhead,
        "gates": gates,
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.out}")
    print(f"gate_passed={gates['gate_passed']}")
    return result


if __name__ == "__main__":
    main()
