"""paxmesh A/B: the sharded drain pipeline vs one chip, same window.

THE ARTIFACT (ISSUE 17): ``bench_results/multichip_lt.json`` -- a
paired 1-chip vs mesh A/B over the SAME global window (1M slots, plus
an 8M arm), per-shard p50/p99 drain latency, and the correctness gates
that make the number trustworthy:

  * **bit-identity oracle gates**: the sharded step replayed at >= 3
    mesh shapes -- including a NON-DIVISIBLE slot split (a block that
    does not divide over the slot shards, exercising the round-up +
    pad-mask path) -- must match the unsharded host oracle on every
    state leaf, compared through ``pipeline.gathered_layout``.
  * **ingest routing gate**: ``ingest.shard.route_block`` /
    ``place_block`` round-trips a drain block onto the mesh (one
    explicitly placed ``device_put`` per mesh slice) and back.
  * **full-scale cross-arm equality**: after equal drains the two
    arms' committed / sm_state registers must agree exactly -- the
    oracle gate's bit-identity, enforced at headline scale for free.

Methodology (the overload_lt shape, calibrated on this 2-CPU
container, docs/BENCH_HISTORY.md): both arms PERSISTENT, driven
alternately in equal chunks with the order flipped every chunk and GC
disabled during the timed region, warmup chunks discarded, per-arm
times summed, and the reported speedup the MEDIAN over independent
blocks. Chunks resume the drain counter (``run_steps_from`` / the
sharded runner take a traced start), so every chunk reuses one
compiled executable and the ring keeps rolling.

Degradation is LOUD: with no accelerator mesh the A/B runs on a
FORCED 8-device host-platform mesh and the artifact says so
(``"host_mesh": true`` -- CI's multichip-smoke lane, and honest
methodology work on a dev box); an accelerator that attaches but
cannot psum (a wedged inter-chip link, the r05 class) writes
``"degraded": true`` with the probe note and exits nonzero instead of
benching a partial mesh.

Usage::

    python -m frankenpaxos_tpu.bench.multichip_lt \
        --out bench_results/multichip_lt.json [--smoke]
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time

from frankenpaxos_tpu.bench.device_probe import (
    _ACCELERATOR_PLATFORMS,
    mesh_probe,
)

#: Headline arms: the bench.py 1M-slot window and the scale-out 8M one,
#: both at the frontier-swept 32K-slot drain (bench_results/
#: block_sweep.json) with the bench.py f=1 majority.
NUM_ACCEPTORS = 3
BLOCK = 1 << 15
ARMS_FULL = (("window_1m", 1 << 20), ("window_8m", 1 << 23))
ARMS_SMOKE = (("window_16k", 1 << 14),)
SMOKE_BLOCK = 1 << 10

#: Alternating-chunk A/B knobs (measure_overhead_block's shape).
FULL_CHUNKS = dict(warmup=2, chunks=8, iters=64, blocks=3)
SMOKE_CHUNKS = dict(warmup=1, chunks=4, iters=8, blocks=2)

#: Per-shard latency pass: host-timed dispatches of LAT_ITERS fused
#: drains, per-shard completion via each device shard's
#: block_until_ready (an UPPER bound: a shard's wait includes any
#: cross-shard collective it participates in).
LAT_ITERS = 8
LAT_SAMPLES_FULL = 48
LAT_SAMPLES_SMOKE = 12


def _force_host_mesh() -> None:
    """Force an 8-device host-platform mesh BEFORE jax's backend
    initializes (the __graft_entry__ dryrun pattern)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _spec_arrays():
    from frankenpaxos_tpu.quorums import SimpleMajority

    spec = SimpleMajority(range(NUM_ACCEPTORS)).write_spec()
    masks, thresholds, combine_any = spec.as_arrays()
    return masks, thresholds, combine_any


def _null_rtt_us(jax, jnp) -> float:
    noop = jax.jit(lambda x: x + 1)
    x = jnp.int32(0)
    for _ in range(3):
        x = noop(x)
        _ = int(x)
    null = []
    for _ in range(20):
        t0 = time.perf_counter()
        x = noop(x)
        _ = int(x)
        null.append(time.perf_counter() - t0)
    import numpy as np

    return float(np.percentile(null, 50) * 1e6)


def measure_ab_block(mesh, window: int, block_size: int, *,
                     warmup: int, chunks: int, iters: int) -> dict:
    """One chunk-interleaved A/B block: persistent 1-chip and mesh
    states over the same GLOBAL window, driven alternately in
    ``iters``-drain chunks (order flipped each chunk) with GC off;
    returns summed per-arm times + the cross-arm equality check."""
    import jax
    import numpy as np

    from frankenpaxos_tpu.bench import pipeline as pl

    masks, thresholds, combine_any = _spec_arrays()
    masks_t = tuple(tuple(int(x) for x in row) for row in masks)
    thresholds_t = tuple(int(t) for t in thresholds)

    # Arm A: one chip, the unsharded pipeline, chunked with a traced
    # start so the ring continues across chunks.
    one = pl.make_state(window, NUM_ACCEPTORS)

    def run_one(state, start):
        return pl.run_steps_from(state, start, iters, block_size,
                                 masks_t, thresholds_t, combine_any)

    # Arm B: the mesh, same global window (padded iff non-divisible --
    # the headline block divides, so w_padded == window here).
    msh, _, w_padded = pl.make_sharded_state(mesh, window, block_size,
                                             NUM_ACCEPTORS)
    runner, _ = pl.make_sharded_runner(
        mesh, block_size=block_size, masks=masks, thresholds=thresholds,
        combine_any=combine_any, iters=iters)

    import jax.numpy as jnp

    # Warm both executables at the exact timed shapes.
    start = jnp.int32(0)
    one = run_one(one, start)
    msh = runner(msh, start)
    assert int(one.committed) == int(msh.committed), (
        int(one.committed), int(msh.committed))
    at = iters

    total = {"one": 0.0, "mesh": 0.0}
    gc.collect()
    gc.disable()
    try:
        for k in range(warmup + chunks):
            order = ("one", "mesh") if k % 2 else ("mesh", "one")
            start = jnp.int32(at)
            for arm in order:
                t0 = time.perf_counter()
                if arm == "one":
                    one = run_one(one, start)
                    _ = int(one.committed)  # value fetch: full sync
                else:
                    msh = runner(msh, start)
                    _ = int(msh.committed)
                if k >= warmup:
                    total[arm] += time.perf_counter() - t0
            at += iters
    finally:
        gc.enable()
    committed_one = int(one.committed)
    committed_mesh = int(msh.committed)
    sm_one, sm_mesh = int(one.sm_state), int(msh.sm_state)
    drains = chunks * iters
    cmds = drains * block_size
    return {
        "one_s": total["one"],
        "mesh_s": total["mesh"],
        "onechip_cmds_per_sec": cmds / total["one"],
        "mesh_cmds_per_sec": cmds / total["mesh"],
        "speedup": total["one"] / total["mesh"],
        "arms_agree": (committed_one == committed_mesh
                       and sm_one == sm_mesh),
        "committed": committed_mesh,
        "padded_window": w_padded,
    }


def measure_arm(mesh, window: int, block_size: int, knobs: dict) -> dict:
    """MEDIAN-of-blocks A/B for one window arm (fresh states per block
    so one GC-debt-laden or cold block cannot swing the ratio)."""
    rows = [measure_ab_block(mesh, window, block_size,
                             warmup=knobs["warmup"],
                             chunks=knobs["chunks"],
                             iters=knobs["iters"])
            for _ in range(knobs["blocks"])]
    ratios = sorted(r["speedup"] for r in rows)
    mid = rows[[r["speedup"] for r in rows].index(
        ratios[len(ratios) // 2])]
    return {
        "window_slots": window,
        "block_slots": block_size,
        "padded_window_slots": mid["padded_window"],
        "chunks": knobs["chunks"],
        "iters_per_chunk": knobs["iters"],
        "blocks": knobs["blocks"],
        "onechip_cmds_per_sec": round(mid["onechip_cmds_per_sec"], 1),
        "mesh_cmds_per_sec": round(mid["mesh_cmds_per_sec"], 1),
        "speedup": round(mid["speedup"], 3),
        "speedup_range": [round(r, 3) for r in ratios],
        "arms_agree": all(r["arms_agree"] for r in rows),
        "committed_per_block": mid["committed"],
    }


def per_shard_latency(mesh, window: int, block_size: int,
                      samples: int) -> dict:
    """Per-shard p50/p99 drain latency: host-timed dispatches of
    LAT_ITERS fused drains; each device shard's completion observed via
    ``block_until_ready`` on ITS piece of the chosen window, in
    rotating shard order so no one shard always pays the full wait.
    Upper bounds (collectives serialize shards), minus the null RTT."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from frankenpaxos_tpu.bench import pipeline as pl

    masks, thresholds, combine_any = _spec_arrays()
    state, _, _ = pl.make_sharded_state(mesh, window, block_size,
                                        NUM_ACCEPTORS)
    runner, _ = pl.make_sharded_runner(
        mesh, block_size=block_size, masks=masks, thresholds=thresholds,
        combine_any=combine_any, iters=LAT_ITERS)
    null_us = _null_rtt_us(jax, jnp)
    state = runner(state, jnp.int32(0))
    _ = int(state.committed)
    at = LAT_ITERS
    n_shards = len(state.chosen.sharding.device_set)
    times: dict = {}
    for s in range(samples):
        t0 = time.perf_counter()
        state = runner(state, jnp.int32(at))
        at += LAT_ITERS
        shards = list(state.chosen.addressable_shards)
        for off in range(len(shards)):
            shard = shards[(s + off) % len(shards)]
            shard.data.block_until_ready()
            dev = repr(shard.device)
            times.setdefault(dev, []).append(time.perf_counter() - t0)
    out = {}
    for dev in sorted(times):
        us = np.maximum(np.asarray(times[dev]) * 1e6 - null_us, 0.0) \
            / LAT_ITERS
        out[dev] = {"p50_us": round(float(np.percentile(us, 50)), 2),
                    "p99_us": round(float(np.percentile(us, 99)), 2)}
    worst = max(v["p50_us"] for v in out.values())
    return {
        "per_shard": out,
        "worst_shard_p50_us": worst,
        "num_shards": n_shards,
        "samples": samples,
        "drains_per_sample": LAT_ITERS,
        "null_rtt_p50_us": round(null_us, 1),
        "method": ("host-timed dispatches of drains_per_sample fused "
                   "drains; per-shard completion via each device "
                   "shard's block_until_ready in rotating order; "
                   "per-drain = (t_shard - null_rtt_p50) / "
                   "drains_per_sample (upper bound: collectives tie "
                   "shards together)"),
    }


def oracle_gate(group_dim: int, slot_dim: int, block_size: int,
                window: int, drains: int = 7) -> dict:
    """Replay ``drains`` steps sharded at (group, slot) vs the
    unsharded host oracle; compare EVERY state leaf bit-for-bit
    through ``gathered_layout``. n=8 acceptors so every group split
    divides."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from frankenpaxos_tpu.bench import pipeline as pl
    from frankenpaxos_tpu.quorums import SimpleMajority

    n = 8
    spec = SimpleMajority(range(n)).write_spec()
    masks, thresholds, combine_any = spec.as_arrays()
    devices = jax.devices()
    if group_dim * slot_dim > len(devices):
        return {"mesh": f"{group_dim}x{slot_dim}", "skipped":
                f"needs {group_dim * slot_dim} devices, "
                f"have {len(devices)}"}
    from jax.sharding import Mesh

    mesh = Mesh(np.array(devices[:group_dim * slot_dim]).reshape(
        group_dim, slot_dim), ("group", "slot"))

    host = pl.make_state(window, n)
    for it in range(drains):
        host = pl.steady_state_step(
            host, jnp.int32(it), block_size=block_size, masks=masks,
            thresholds=thresholds, combine_any=combine_any)

    state, _, w_padded = pl.make_sharded_state(mesh, window, block_size,
                                               n)
    step, _ = pl.make_sharded_step(mesh, block_size=block_size,
                                   masks=masks, thresholds=thresholds,
                                   combine_any=combine_any)
    for it in range(drains):
        state = step(state, jnp.int32(it))

    b_local, pad = pl.local_block(block_size, slot_dim)
    w_local = w_padded // slot_dim
    logical, valid = pl.gathered_layout(slot_dim, w_local, b_local,
                                        block_size)

    def gathered(x):
        x = np.asarray(x)
        if x.ndim == 1:
            out = np.zeros(window, x.dtype)
            out[logical[valid]] = x[valid]
            return out
        out = np.zeros((x.shape[0], window), x.dtype)
        out[:, logical[valid]] = x[:, valid]
        return out

    ok = (int(state.committed) == int(host.committed)
          and int(state.sm_state) == int(host.sm_state)
          and int(state.exec_wm) == int(host.exec_wm))
    for field in ("votes", "chosen", "commands", "results"):
        ok = ok and bool(np.array_equal(
            gathered(getattr(state, field)),
            np.asarray(getattr(host, field))))
    # Pad columns (non-divisible splits only) must stay all-zero.
    if pad:
        ok = ok and not np.asarray(state.votes)[:, ~valid].any() \
            and not np.asarray(state.commands)[~valid].any()
    return {
        "mesh": f"{group_dim}x{slot_dim}",
        "block": block_size,
        "window": window,
        "padded_window": w_padded,
        "non_divisible": pad > 0,
        "drains": drains,
        "bit_identical": bool(ok),
    }


def ingest_gate(mesh, block_size: int) -> dict:
    """Round-trip a drain block through the per-shard ingest routing:
    one placed ``device_put`` per mesh slice, gathered back in lane
    order."""
    import numpy as np

    from frankenpaxos_tpu.bench.pipeline import (
        gathered_layout,
        local_block,
    )
    from frankenpaxos_tpu.ingest.shard import place_block

    slot_dim = mesh.shape["slot"]
    ids = (np.arange(block_size, dtype=np.int32) * 7 + 1)
    placed = place_block(mesh, ids, block_size)
    b_local, _ = local_block(block_size, slot_dim)
    logical, valid = gathered_layout(slot_dim, b_local, b_local,
                                     block_size)
    flat = np.asarray(placed)
    out = np.zeros(block_size, np.int32)
    out[logical[valid]] = flat[valid]
    ok = bool(np.array_equal(out, ids)) and not flat[~valid].any()
    n_puts = len(placed.sharding.addressable_devices_indices_map(
        placed.shape))
    return {
        "round_trip_ok": ok,
        "device_puts_per_drain": n_puts,
        "block": block_size,
        "note": ("one explicitly placed device_put per mesh slice "
                 "(ingest.shard.place_block); lanes land on their "
                 "owning slot shard"),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="bench_results/multichip_lt.json")
    parser.add_argument("--smoke", action="store_true",
                        help="reduced CI shape: small window, few "
                             "chunks, same gates")
    args = parser.parse_args(argv)

    probe = mesh_probe()
    accelerator = probe.platform in _ACCELERATOR_PLATFORMS
    if accelerator and probe.device_count >= 2 \
            and not probe.collective_ok:
        # A mesh that attaches but cannot psum is a PARTIAL MESH:
        # refuse to bench it (the r05 wedged-link class, loud).
        artifact = {
            "kind": "multichip_lt",
            "degraded": True,
            "probe_note": probe.note,
            "probe": probe._asdict(),
        }
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=2)
            f.write("\n")
        print(json.dumps(artifact))
        return 1
    host_mesh = not accelerator
    if host_mesh:
        _force_host_mesh()

    import jax
    import numpy as np

    if host_mesh:
        jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh

    devices = jax.devices()
    # Acceptors stay whole per shard for the f=1 majority headline
    # (group=1); all devices shard the slot window.
    mesh = Mesh(np.array(devices).reshape(1, len(devices)),
                ("group", "slot"))

    block = SMOKE_BLOCK if args.smoke else BLOCK
    knobs = SMOKE_CHUNKS if args.smoke else FULL_CHUNKS
    arms = ARMS_SMOKE if args.smoke else ARMS_FULL
    lat_samples = LAT_SAMPLES_SMOKE if args.smoke else LAT_SAMPLES_FULL

    arm_rows = {}
    for name, window in arms:
        arm_rows[name] = measure_arm(mesh, window, block, knobs)
        print(f"# {name}: mesh "
              f"{arm_rows[name]['mesh_cmds_per_sec']:.3g} cmds/s, "
              f"1-chip {arm_rows[name]['onechip_cmds_per_sec']:.3g}, "
              f"speedup {arm_rows[name]['speedup']}x",
              file=sys.stderr)

    lat = per_shard_latency(mesh, arms[0][1], block, lat_samples)

    # Bit-identity gates: 1x1 (the degenerate control), 2x4 and 8x1
    # (the ISSUE shapes), and 2x3 with a 100-slot block -- the
    # NON-DIVISIBLE slot split (100 % 3 != 0) through the round-up +
    # pad-mask path.
    gates = [
        oracle_gate(1, 1, 128, 512),
        oracle_gate(2, 4, 128, 512),
        oracle_gate(8, 1, 128, 512),
        oracle_gate(2, 3, 100, 400),
    ]
    ing = ingest_gate(mesh, block)

    ran = [g for g in gates if "bit_identical" in g]
    gates_pass = (len(ran) >= 3
                  and all(g["bit_identical"] for g in ran)
                  and any(g["non_divisible"] for g in ran)
                  and ing["round_trip_ok"]
                  and all(r["arms_agree"] for r in arm_rows.values()))

    artifact = {
        "kind": "multichip_lt",
        "mode": "smoke" if args.smoke else "full",
        "degraded": False,
        "host_mesh": host_mesh,
        "probe": probe._asdict(),
        "mesh_shape": {"group": 1, "slot": len(devices)},
        "num_acceptors": NUM_ACCEPTORS,
        "arms": arm_rows,
        "per_shard_latency": lat,
        "oracle_gates": gates,
        "ingest_gate": ing,
        "gates_pass": gates_pass,
        "methodology": (
            "alternating-chunk paired A/B (overload_lt shape): both "
            "arms persistent over the SAME global window, driven in "
            "equal iters_per_chunk-drain chunks with order flipped "
            "each chunk, GC disabled in the timed region, warmup "
            "chunks discarded, speedup = summed 1-chip time / summed "
            "mesh time, median over independent blocks"),
        "host_mesh_note": (
            "no accelerator mesh: A/B ran on a FORCED 8-device "
            "host-platform (CPU XLA) mesh -- methodology and "
            "bit-identity are real, the speedup is NOT a hardware "
            "claim (8 virtual devices share this host's cores)"
            if host_mesh else ""),
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(json.dumps({k: artifact[k] for k in
                      ("kind", "mode", "host_mesh", "gates_pass")}
                     | {"arms": {k: v["speedup"]
                                 for k, v in arm_rows.items()}}))
    return 0 if gates_pass else 1


if __name__ == "__main__":
    sys.exit(main())
