"""Live interactive protocol driving in the browser.

The reference's Scala.js pages let a user step messages, fire timers,
and partition actors mid-run (JsTransport.scala:60-299; partitioned
actors at :77), across 23 demo pages (index.html:12-36) including the
election and heartbeat components. This is the analog without a
browser-side runtime: the protocol runs over a SimTransport inside a
small stdlib HTTP server, and the page (``live_viewer.html``) drives it
through a JSON API --

  * ``GET  /api/state``               -- actors (+ state snapshots,
    partition flags), in-flight messages, running timers, reply count
  * ``POST /api/deliver {"id": n}``   -- deliver one buffered message
  * ``POST /api/drop {"id": n}``      -- drop it (loss injection)
  * ``POST /api/timer {"id": n}``     -- fire a running timer
  * ``POST /api/partition {"actor"}`` / ``/api/heal`` -- JsTransport:77
  * ``POST /api/command``             -- issue a client command
  * ``POST /api/step {"n": k}``       -- k random scheduler steps

Every protocol in the deployment registry is drivable, plus the
``election`` and ``heartbeat`` component demos (the reference's
dedicated pages for them).

Usage::

    python -m frankenpaxos_tpu.live --protocol multipaxos --port 8123
"""

from __future__ import annotations

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
import json
import random
import threading

from frankenpaxos_tpu.runtime import FakeLogger, LogLevel, SimTransport
from frankenpaxos_tpu.viz import snapshot_actor

#: Component demos served alongside the registry protocols
#: (reference index.html lists election/heartbeat pages).
COMPONENT_DEMOS = ("election", "heartbeat")


def _build_component(name: str, seed: int) -> dict:
    logger = FakeLogger(LogLevel.FATAL)
    transport = SimTransport(logger)
    labels: dict = {}
    if name == "election":
        from frankenpaxos_tpu.election.basic import ElectionParticipant

        addresses = [f"participant-{i}" for i in range(3)]
        actors = [ElectionParticipant(a, transport, logger, addresses,
                                      seed=seed + i)
                  for i, a in enumerate(addresses)]
        for actor in actors:
            actor.ping_timer.start() if actor.index == 0 else \
                actor.no_ping_timer.start()
    else:
        from frankenpaxos_tpu.heartbeat import HeartbeatParticipant

        addresses = [f"participant-{i}" for i in range(3)]
        actors = [HeartbeatParticipant(a, transport, logger, addresses)
                  for a in addresses]
    labels.update({a: a for a in addresses})
    return dict(protocol=name, transport=transport, labels=labels,
                client=None, drive=None, replies=[])


def build_system(protocol_name: str, *, f: int = 1, seed: int = 0) -> dict:
    """Wire ``protocol_name`` over a SimTransport (same registry path as
    viz.record_scenario) and return the pieces the server drives."""
    if protocol_name in COMPONENT_DEMOS:
        return _build_component(protocol_name, seed)

    from frankenpaxos_tpu.deploy import DeployCtx, get_protocol

    protocol = get_protocol(protocol_name)
    counter = {"next": 0}

    def fake_port():
        counter["next"] += 1
        return ["sim", counter["next"]]

    raw = protocol.cluster(f, fake_port)
    config = protocol.load_config(raw)
    labels: dict = {}
    counts: dict = {}

    def walk(key, node):
        if (isinstance(node, list) and len(node) == 2
                and not isinstance(node[0], list)):
            prefix = key.rstrip("s")
            index = counts.get(prefix, 0)
            counts[prefix] = index + 1
            labels[(node[0], int(node[1]))] = f"{prefix}_{index}"
        elif isinstance(node, list):
            for item in node:
                walk(key, item)

    for key, node in raw.items():
        if isinstance(node, list):
            walk(key, node)

    logger = FakeLogger(LogLevel.FATAL)
    transport = SimTransport(logger)
    ctx = DeployCtx(config=config, transport=transport, logger=logger,
                    overrides={}, seed=seed, state_machine="AppendLog")
    for role_name, role in protocol.roles.items():
        for index, address in enumerate(role.addresses(config)):
            ctx.seed = seed + index
            role.make(ctx, address, index)
    client_ctx = DeployCtx(config=config, transport=transport,
                           logger=logger, overrides={}, seed=seed + 100)
    client_address = ("sim", "client-0")
    labels[client_address] = "client_0"
    client = protocol.make_client(client_ctx, client_address)
    return dict(protocol=protocol_name, transport=transport,
                labels=labels, client=client, drive=protocol.drive,
                replies=[])


class LiveSession:
    """One drivable system + the lock serializing browser actions onto
    its single-threaded actors."""

    def __init__(self, protocol_name: str, *, f: int = 1, seed: int = 0):
        self.lock = threading.Lock()
        self.rng = random.Random(seed)
        self.protocol_name = protocol_name
        self.f = f
        self.seed = seed
        self.system = build_system(protocol_name, f=f, seed=seed)
        self.issued = 0

    def _label(self, address) -> str:
        labels = self.system["labels"]
        if isinstance(address, list):
            address = (address[0], address[1])
        return labels.get(address, str(address))

    # --- API actions (all under the lock) ---------------------------------
    def state(self) -> dict:
        with self.lock:
            transport = self.system["transport"]
            actors = []
            for address, actor in transport.actors.items():
                actors.append({
                    "label": self._label(address),
                    "partitioned": address in transport.partitioned,
                    "state": snapshot_actor(actor),
                })
            actors.sort(key=lambda a: a["label"])
            messages = [{
                "id": m.id,
                "src": self._label(m.src),
                "dst": self._label(m.dst),
                "label": type(self.system["transport"].actors[m.dst]
                              .serializer.from_bytes(m.data)).__name__
                if m.dst in transport.actors else "?",
            } for m in transport.messages[:200]]
            timers = [{
                "id": t.id,
                "actor": self._label(t.address),
                "name": t.name,
            } for t in transport.running_timers()]
            return {
                "protocol": self.protocol_name,
                "has_client": self.system["client"] is not None,
                "actors": actors,
                "messages": messages,
                "timers": timers,
                "history_len": len(transport.history),
                "issued": self.issued,
                "completed": len(self.system["replies"]),
            }

    def command(self) -> None:
        with self.lock:
            client, drive = self.system["client"], self.system["drive"]
            if client is None:
                raise ValueError(
                    f"{self.protocol_name} has no client to drive")
            replies = self.system["replies"]
            drive(client, self.issued, lambda *_: replies.append(True))
            self.issued += 1

    def deliver(self, message_id: int) -> None:
        with self.lock:
            transport = self.system["transport"]
            for message in transport.messages:
                if message.id == message_id:
                    transport.deliver_message(message)
                    return
            raise ValueError(f"no buffered message {message_id}")

    def drop(self, message_id: int) -> None:
        with self.lock:
            transport = self.system["transport"]
            for message in transport.messages:
                if message.id == message_id:
                    transport.messages.remove(message)
                    return
            raise ValueError(f"no buffered message {message_id}")

    def timer(self, timer_id: int) -> None:
        with self.lock:
            self.system["transport"].trigger_timer(timer_id)

    def partition(self, label: str, heal: bool = False) -> None:
        with self.lock:
            transport = self.system["transport"]
            for address in transport.actors:
                if self._label(address) == label:
                    (transport.heal if heal
                     else transport.partition)(address)
                    return
            raise ValueError(f"no actor {label!r}")

    def step(self, n: int = 1) -> None:
        with self.lock:
            transport = self.system["transport"]
            for _ in range(n):
                command = transport.generate_command(self.rng)
                if command is None:
                    break
                transport.run_command(command)

    def reset(self) -> None:
        with self.lock:
            self.system = build_system(self.protocol_name, f=self.f,
                                       seed=self.seed)
            self.issued = 0


def make_handler(session: LiveSession):
    import os

    page = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "live_viewer.html")

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _json(self, payload, status=200):
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/", "/index.html"):
                with open(page, "rb") as f:
                    body = f.read()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/api/state":
                self._json(session.state())
            else:
                self._json({"error": "not found"}, 404)

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            try:
                if self.path == "/api/command":
                    session.command()
                elif self.path == "/api/deliver":
                    session.deliver(int(body["id"]))
                elif self.path == "/api/drop":
                    session.drop(int(body["id"]))
                elif self.path == "/api/timer":
                    session.timer(int(body["id"]))
                elif self.path == "/api/partition":
                    session.partition(body["actor"])
                elif self.path == "/api/heal":
                    session.partition(body["actor"], heal=True)
                elif self.path == "/api/step":
                    session.step(int(body.get("n", 1)))
                elif self.path == "/api/reset":
                    session.reset()
                else:
                    self._json({"error": "not found"}, 404)
                    return
                self._json(session.state())
            except (ValueError, KeyError) as e:
                self._json({"error": str(e)}, 400)

    return Handler


def serve(protocol_name: str, port: int = 8123, *, f: int = 1,
          seed: int = 0) -> ThreadingHTTPServer:
    """Start the live server (non-blocking; returns the server)."""
    session = LiveSession(protocol_name, f=f, seed=seed)
    server = ThreadingHTTPServer(("127.0.0.1", port),
                                 make_handler(session))
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server


def main(argv=None) -> None:
    import argparse

    from frankenpaxos_tpu.deploy import PROTOCOL_NAMES

    parser = argparse.ArgumentParser()
    parser.add_argument("--protocol", default="multipaxos",
                        choices=[*PROTOCOL_NAMES, *COMPONENT_DEMOS])
    parser.add_argument("--port", type=int, default=8123)
    parser.add_argument("--f", type=int, default=1)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    server = serve(args.protocol, args.port, f=args.f, seed=args.seed)
    print(f"live {args.protocol} at http://127.0.0.1:{args.port}/ "
          f"(ctrl-c to stop)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    main()
