"""Deployment CLI: one process per role over TcpTransport, any protocol.

The analog of the reference's 105 ``<Role>Main`` objects
(jvm/src/main/scala/frankenpaxos/<proto>/<Role>Main.scala): parse flags
(``--protocol``, ``--role``, ``--index``, ``--config``, ``--log_level``,
``--prometheus_port``, ``--state_machine``; LeaderMain.scala:19-103),
read a cluster config file (the prototext analog is JSON here;
ConfigUtil.scala:7-43), construct the role actor over TcpTransport via
the deployment registry (frankenpaxos_tpu/deploy.py), and optionally
expose Prometheus metrics (PrometheusUtil.scala:6-15).

Per-role tunables use ``--options.<name> <value>`` (or ``=``-joined),
matching the reference's scopt ``--options.*`` flags
(LeaderMain.scala:52-80); they apply to both constructor keyword
parameters and options-dataclass fields, coerced by declared type.

Usage::

    python -m frankenpaxos_tpu.cli --protocol multipaxos --role acceptor \
        --index 2 --config cluster.json --options.flush_every_n 10
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from frankenpaxos_tpu.deploy import DeployCtx, get_protocol, PROTOCOL_NAMES
from frankenpaxos_tpu.runtime import LogLevel, PrintLogger
from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport

_TPU_BACKEND_KEYS = ("quorum_backend", "dep_backend", "phase1_backend")


def parse_option_overrides(extra: list) -> dict:
    """``--options.name value`` / ``--options.name=value`` pairs."""
    overrides: dict = {}
    i = 0
    while i < len(extra):
        arg = extra[i]
        if not arg.startswith("--options."):
            raise SystemExit(f"unrecognized argument: {arg}")
        key = arg[len("--options."):]
        if "=" in key:
            key, _, value = key.partition("=")
        else:
            i += 1
            if i >= len(extra) or extra[i].startswith("--options."):
                raise SystemExit(f"missing value for {arg}")
            value = extra[i]
        overrides[key] = value
        i += 1
    return overrides


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="frankenpaxos_tpu")
    parser.add_argument("--protocol", required=True,
                        choices=PROTOCOL_NAMES)
    parser.add_argument("--role", required=True)
    parser.add_argument("--index", type=int, default=0)
    parser.add_argument("--config", required=True,
                        help="cluster config JSON")
    parser.add_argument("--log_level", default="info",
                        choices=["debug", "info", "warn", "error", "fatal"])
    parser.add_argument("--state_machine", default="AppendLog")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--prometheus_port", type=int, default=0,
                        help="0 disables the metrics endpoint")
    parser.add_argument("--wal_dir", default=None,
                        help="durability root (wal/): WAL-capable roles "
                             "write a per-role write-ahead log under "
                             "<wal_dir>/<role>_<index> and recover from "
                             "it on startup, so a SIGKILL'd role "
                             "relaunched with the same wal_dir rejoins "
                             "with its state intact")
    parser.add_argument("--fault_fsync", default=None,
                        metavar="P:PERIOD:WINDOW|C:EVERY:STALL_S:SEED",
                        help="paxchaos storage-fault arm (faults/): "
                             "wrap this role's WAL storage in a "
                             "BLOCKING FsyncStallStorage -- "
                             "P:<period_s>:<window_s> sleeps through "
                             "the first <window_s> of every "
                             "<period_s> on the host wall clock "
                             "(aligned across role processes); "
                             "C:<every>:<stall_s>:<seed> stalls after "
                             "every EVERY-th group commit. The "
                             "deployed twin of the scenario matrix's "
                             "fsync-stall schedule")
    parser.add_argument("--fault_link", default=None,
                        metavar="zone:H:P=Z;drop:ZA-ZB;lat:ZA-ZB=S",
                        help="paxchaos link-fault arm (faults/): inject "
                             "partitions/latency at THIS role's "
                             "TcpTransport send path, mirroring "
                             "--fault_fsync's launch-time arming -- the "
                             "deployed twin of the scenario matrix's "
                             "partition rows (before this flag only the "
                             "in-process client transport armed "
                             "LinkFaults; role->role links ran clean). "
                             "Clauses: zone:HOST:PORT=NAME endpoint "
                             "mapping, drop:ZA-ZB partition (both "
                             "ways), lat:ZA-ZB=SECONDS extra latency")
    parser.add_argument("--ready_addr", default=None,
                        help="host:port the launcher listens on for the "
                             "wait-for-listen handshake: once this role "
                             "is fully constructed and listening, it "
                             "connects there and reports its label")
    parser.add_argument("--trace", default=None, metavar="DIR",
                        help="paxtrace root (obs/): emit receive/timer/"
                             "drain spans with drain-stage sub-spans to "
                             "DIR/<role>_<index>.trace.jsonl, keep the "
                             "crash flight recorder ring in "
                             "DIR/<role>_<index>.flight (mmap'd: "
                             "survives kill -9), and propagate trace "
                             "contexts on outbound frames")
    parser.add_argument("--trace_sample", type=float, default=1.0,
                        help="trace sampling rate at trace roots "
                             "(1.0 = every command, 0.01 = 1 in 100); "
                             "propagated contexts keep the root's "
                             "decision")
    # Back-compat shorthands (now spelled --options.*):
    parser.add_argument("--quorum_backend", default=None,
                        choices=[None, "dict", "tpu"])
    parser.add_argument("--batch_size", type=int, default=None)
    args, extra = parser.parse_known_args(argv)

    overrides = parse_option_overrides(extra)
    if args.quorum_backend is not None:
        overrides.setdefault("quorum_backend", args.quorum_backend)
    if args.batch_size is not None:
        overrides.setdefault("batch_size", str(args.batch_size))

    if not any(overrides.get(k) == "tpu" for k in _TPU_BACKEND_KEYS):
        # Only TPU backends need an accelerator; everything else pins to
        # CPU so role processes never contend for the chip. If the
        # environment already pins it (the TPU plugin's sitecustomize is
        # what overrides the env var), skip the jax import entirely --
        # it costs ~2s of role startup.
        import os
        import sys

        site_mod = sys.modules.get("sitecustomize")
        plugin_loaded = site_mod is not None and ".axon_site" in (
            getattr(site_mod, "__file__", "") or "")
        if plugin_loaded or os.environ.get("JAX_PLATFORMS") != "cpu":
            import jax

            jax.config.update("jax_platforms", "cpu")

    logger = PrintLogger(LogLevel[args.log_level.upper()])
    protocol = get_protocol(args.protocol)

    with open(args.config) as f:
        config = protocol.load_config(json.load(f))

    collectors = None
    if args.prometheus_port > 0:
        from frankenpaxos_tpu.runtime.monitoring import PrometheusCollectors

        collectors = PrometheusCollectors()

    if args.role == "supernode":
        listen_address = None
    else:
        try:
            role = protocol.roles[args.role]
        except KeyError:
            raise SystemExit(
                f"unknown role {args.role!r} for {args.protocol}; "
                f"known: {sorted(protocol.roles)} or 'supernode'")
        addresses = role.addresses(config)
        if not 0 <= args.index < len(addresses):
            raise SystemExit(
                f"--index {args.index} out of range for {args.protocol} "
                f"{args.role}: valid range 0..{len(addresses) - 1}")
        listen_address = addresses[args.index]

    transport = TcpTransport(listen_address, logger)
    if args.fault_link:
        from frankenpaxos_tpu.faults.deployed_backend import (
            parse_link_fault_spec,
        )

        transport.link_faults = parse_link_fault_spec(
            args.fault_link).check
    label = f"{args.role}_{args.index}"
    if collectors is not None:
        from frankenpaxos_tpu.obs import RuntimeMetrics

        transport.runtime_metrics = RuntimeMetrics(collectors, label)
    if args.trace:
        import atexit
        import os

        from frankenpaxos_tpu.obs import FlightRecorder, Tracer

        os.makedirs(args.trace, exist_ok=True)
        tracer = Tracer(
            role=label, sample_rate=args.trace_sample,
            flight=FlightRecorder(
                os.path.join(args.trace, f"{label}.flight")),
            runtime_metrics=transport.runtime_metrics,
            sink_path=os.path.join(args.trace,
                                   f"{label}.trace.jsonl"),
            # Incarnation salt: a crash-relaunched role appends to the
            # same trace.jsonl and must not reuse the dead life's ids.
            instance=os.getpid())
        transport.tracer = tracer
        # SIGTERM exits via sys.exit (below), so a clean kill flushes
        # the span sink; a SIGKILL leaves the mmap'd flight ring.
        atexit.register(tracer.flush)
    transport.start()
    ctx = DeployCtx(config=config, transport=transport, logger=logger,
                    overrides=overrides, seed=args.seed,
                    state_machine=args.state_machine,
                    collectors=collectors, wal_dir=args.wal_dir,
                    wal_fault=args.fault_fsync)

    def make_instrumented(role, role_name, role_address, index):
        """Construct the role actor and, when metrics are on, wrap its
        receive with the uniform per-role request metrics."""
        actor = role.make(ctx, role_address, index)
        if collectors is not None and actor is not None:
            from frankenpaxos_tpu.runtime.monitoring import (
                instrument_actor,
            )

            instrument_actor(actor, collectors, args.protocol, role_name)

    if args.role == "supernode":
        # Coupled baseline: every role of the protocol colocated in one
        # process on one event loop (the reference's SuperNode mains,
        # jvm/.../multipaxos/SuperNode.scala:22+). Bind every role
        # address FIRST so construction-time sends (a leader's Phase1a)
        # always find their targets listening, then construct in
        # declaration order with a distinct seed per actor (matching the
        # per-process --seed diversity of compartmentalized mode --
        # identical seeds would sync the elections' randomized
        # timeouts).
        count = 0
        for role_name, role in protocol.roles.items():
            for role_address in role.addresses(config):
                transport.listen_on(role_address)
        for role_name, role in protocol.roles.items():
            for index, role_address in enumerate(role.addresses(config)):
                ctx.seed = args.seed + count
                make_instrumented(role, role_name, role_address, index)
                count += 1
        address = f"supernode ({count} roles)"
    else:
        address = listen_address
        make_instrumented(role, args.role, address, args.index)
    unmatched = ctx.unmatched_overrides()
    if unmatched:
        # Overrides are shared across a deployment's roles, so an option
        # aimed at another role lands here too -- note, don't fail.
        logger.info(f"options not used by this role: {unmatched}")

    if args.prometheus_port > 0:
        import prometheus_client

        prometheus_client.start_http_server(args.prometheus_port)

    logger.info(f"{args.protocol} {args.role} {args.index} "
                f"listening on {address}")
    if args.ready_addr:
        # Explicit readiness handshake (deploy_suite.launch_roles): by
        # this point every listener is bound, every actor constructed,
        # and the metrics endpoint (if any) serving -- so connecting
        # back and reporting our label is a true end-to-end "ready",
        # unlike grepping logs (which races log flushing and says
        # nothing about whether the process can actually be reached).
        import socket

        ready_host, _, ready_port = args.ready_addr.rpartition(":")
        try:
            with socket.create_connection(
                    (ready_host, int(ready_port)), timeout=10) as sock:
                sock.sendall(f"{args.role}_{args.index}\n".encode())
        except OSError as e:
            # The launcher may have timed out and gone away; the role
            # itself is healthy, so keep serving.
            logger.warn(f"ready handshake to {args.ready_addr} "
                        f"failed: {e}")
    # Exit cleanly on SIGTERM so wrappers that dump state at interpreter
    # exit (cProfile's -m runner, the perf_util.py:37 analog) get to
    # write their output when the harness kills the role.
    import signal

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        transport.stop()


if __name__ == "__main__":
    main()
