"""Deployment CLI: one process per role over TcpTransport.

The analog of the reference's 105 ``<Role>Main`` objects
(jvm/src/main/scala/frankenpaxos/<proto>/<Role>Main.scala): parse flags
(``--protocol``, ``--role``, ``--index``, ``--config``, ``--log_level``,
``--prometheus_port``, ``--state_machine``; LeaderMain.scala:19-103),
read a cluster config file (the prototext analog is JSON here;
ConfigUtil.scala:7-43), construct the role actor over TcpTransport, and
optionally expose Prometheus metrics (PrometheusUtil.scala:6-15).

Usage::

    python -m frankenpaxos_tpu.cli --protocol multipaxos --role acceptor \
        --index 2 --config cluster.json
"""

from __future__ import annotations

import argparse
import json
import time

from frankenpaxos_tpu.runtime import LogLevel, PrintLogger
from frankenpaxos_tpu.runtime.tcp_transport import TcpTransport
from frankenpaxos_tpu.statemachine import state_machine_by_name


def _addr(x) -> tuple:
    return (x[0], int(x[1]))


def load_multipaxos_config(path: str):
    from frankenpaxos_tpu.protocols.multipaxos import (
        DistributionScheme,
        MultiPaxosConfig,
    )

    with open(path) as f:
        raw = json.load(f)
    config = MultiPaxosConfig(
        f=raw["f"],
        batcher_addresses=[_addr(a) for a in raw.get("batchers", [])],
        read_batcher_addresses=[_addr(a)
                                for a in raw.get("read_batchers", [])],
        leader_addresses=[_addr(a) for a in raw["leaders"]],
        leader_election_addresses=[_addr(a)
                                   for a in raw["leader_elections"]],
        proxy_leader_addresses=[_addr(a) for a in raw["proxy_leaders"]],
        acceptor_addresses=[[_addr(a) for a in group]
                            for group in raw["acceptors"]],
        replica_addresses=[_addr(a) for a in raw["replicas"]],
        proxy_replica_addresses=[_addr(a)
                                 for a in raw.get("proxy_replicas", [])],
        flexible=raw.get("flexible", False),
        distribution_scheme=DistributionScheme(
            raw.get("distribution_scheme", "hash")),
    )
    config.check_valid()
    return config


def make_multipaxos_role(role: str, index: int, config, transport, logger,
                         args):
    from frankenpaxos_tpu.protocols import multipaxos as mp

    if role == "batcher":
        return mp.Batcher(config.batcher_addresses[index], transport,
                          logger, config,
                          mp.BatcherOptions(batch_size=args.batch_size))
    if role == "read_batcher":
        return mp.ReadBatcher(config.read_batcher_addresses[index],
                              transport, logger, config,
                              mp.ReadBatchingScheme(
                                  kind=args.read_batching_scheme,
                                  batch_size=args.batch_size),
                              seed=args.seed)
    if role == "leader":
        return mp.Leader(config.leader_addresses[index], transport, logger,
                         config, mp.LeaderOptions(), seed=args.seed)
    if role == "proxy_leader":
        return mp.ProxyLeader(
            config.proxy_leader_addresses[index], transport, logger, config,
            mp.ProxyLeaderOptions(quorum_backend=args.quorum_backend),
            seed=args.seed)
    if role == "acceptor":
        flat = [a for group in config.acceptor_addresses for a in group]
        return mp.Acceptor(flat[index], transport, logger, config)
    if role == "replica":
        return mp.Replica(config.replica_addresses[index], transport,
                          logger, state_machine_by_name(args.state_machine),
                          config, mp.ReplicaOptions(), seed=args.seed)
    if role == "proxy_replica":
        return mp.ProxyReplica(config.proxy_replica_addresses[index],
                               transport, logger, config)
    raise ValueError(f"unknown multipaxos role {role!r}")


def role_address(protocol: str, role: str, index: int, config):
    if protocol == "multipaxos":
        table = {
            "batcher": config.batcher_addresses,
            "read_batcher": config.read_batcher_addresses,
            "leader": config.leader_addresses,
            "proxy_leader": config.proxy_leader_addresses,
            "acceptor": [a for group in config.acceptor_addresses
                         for a in group],
            "replica": config.replica_addresses,
            "proxy_replica": config.proxy_replica_addresses,
        }
        return table[role][index]
    if protocol in ("unreplicated", "echo"):
        return _addr(config["server"])
    raise ValueError(f"unknown protocol {protocol!r}")


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="frankenpaxos_tpu")
    parser.add_argument("--protocol", required=True,
                        choices=["multipaxos", "unreplicated", "echo"])
    parser.add_argument("--role", required=True)
    parser.add_argument("--index", type=int, default=0)
    parser.add_argument("--config", required=True,
                        help="cluster config JSON")
    parser.add_argument("--log_level", default="info",
                        choices=["debug", "info", "warn", "error", "fatal"])
    parser.add_argument("--state_machine", default="KeyValueStore")
    parser.add_argument("--batch_size", type=int, default=1)
    parser.add_argument("--read_batching_scheme", default="size")
    parser.add_argument("--quorum_backend", default="dict",
                        choices=["dict", "tpu"])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--prometheus_port", type=int, default=0,
                        help="0 disables the metrics endpoint")
    args = parser.parse_args(argv)

    if args.quorum_backend != "tpu":
        # Only the TPU quorum path needs an accelerator; everything else
        # pins to CPU so role processes never contend for the chip.
        import jax

        jax.config.update("jax_platforms", "cpu")

    logger = PrintLogger(LogLevel[args.log_level.upper()])

    if args.protocol == "multipaxos":
        config = load_multipaxos_config(args.config)
    else:
        with open(args.config) as f:
            config = json.load(f)

    address = role_address(args.protocol, args.role, args.index, config)
    transport = TcpTransport(address, logger)
    transport.start()

    if args.protocol == "multipaxos":
        actor = make_multipaxos_role(args.role, args.index, config,
                                     transport, logger, args)
    elif args.protocol == "unreplicated":
        from frankenpaxos_tpu.protocols.unreplicated import (
            UnreplicatedServer,
        )

        actor = UnreplicatedServer(address, transport, logger,
                                   state_machine_by_name(args.state_machine))
    else:
        from frankenpaxos_tpu.protocols.echo import EchoServer

        actor = EchoServer(address, transport, logger)

    if args.prometheus_port > 0:
        import prometheus_client

        prometheus_client.start_http_server(args.prometheus_port)

    logger.info(f"{args.protocol} {args.role} {args.index} "
                f"listening on {address}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        transport.stop()


if __name__ == "__main__":
    main()
