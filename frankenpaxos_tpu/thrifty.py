"""Thrifty node selection: message only ``min`` nodes when only ``min``
replies are needed.

Reference behavior: thrifty/ThriftySystem.scala:28-77 -- NotThrifty (all
nodes), Random (a random min-subset), Closest (the min closest by the
heartbeat delay estimate).
"""

from __future__ import annotations

import abc
import random
from typing import Mapping

from frankenpaxos_tpu.runtime.transport import Address


class ThriftySystem(abc.ABC):
    @abc.abstractmethod
    def choose(self, delays: Mapping[Address, float], min_size: int,
               rng: random.Random) -> set[Address]:
        """Pick the subset of ``delays``' keys to actually message."""


class NotThrifty(ThriftySystem):
    def choose(self, delays, min_size, rng) -> set[Address]:
        return set(delays.keys())


class RandomThrifty(ThriftySystem):
    def choose(self, delays, min_size, rng) -> set[Address]:
        return set(rng.sample(sorted(delays.keys(), key=str), min_size))


class ClosestThrifty(ThriftySystem):
    def choose(self, delays, min_size, rng) -> set[Address]:
        ranked = sorted(delays.items(), key=lambda kv: (kv[1], str(kv[0])))
        return {a for a, _ in ranked[:min_size]}


def thrifty_system_by_name(name: str) -> ThriftySystem:
    systems = {
        "NotThrifty": NotThrifty,
        "Random": RandomThrifty,
        "Closest": ClosestThrifty,
    }
    if name not in systems:
        raise ValueError(f"{name} is not one of {', '.join(sorted(systems))}")
    return systems[name]()
