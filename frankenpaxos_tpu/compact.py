"""Compact add-only sets: watermark + sparse overflow.

Reference behavior: compact/CompactSet.scala:24-80 (the API contract:
add/contains/union/diff/materialized_diff/add_all/subtract_all/
subtract_one/size/uncompacted_size/subset/materialize) and
compact/IntPrefixSet.scala:206+ (the integer implementation: a watermark
``w`` meaning "0..w-1 all present" plus a sparse set of values >= w).

An IntPrefixSet is the host twin of a device (watermark scalar, tail
bitmask) pair: the chosen-slot sets, executed-command id sets, and
EPaxos/BPaxos dependency sets all compact this way.
"""

from __future__ import annotations

import abc
from typing import Generic, Iterable, Iterator, Optional, TypeVar

T = TypeVar("T")


class CompactSet(abc.ABC, Generic[T]):
    """Add-only set that best-effort compacts to O(1) space
    (CompactSet.scala:24-80)."""

    @abc.abstractmethod
    def add(self, x: T) -> bool:
        """Add x; returns whether x was already present."""

    @abc.abstractmethod
    def contains(self, x: T) -> bool:
        ...

    @abc.abstractmethod
    def union(self, other) -> "CompactSet[T]":
        ...

    @abc.abstractmethod
    def diff(self, other) -> "CompactSet[T]":
        ...

    @abc.abstractmethod
    def materialized_diff(self, other) -> Iterable[T]:
        ...

    @abc.abstractmethod
    def add_all(self, other) -> "CompactSet[T]":
        ...

    @abc.abstractmethod
    def subtract_all(self, other) -> "CompactSet[T]":
        ...

    @abc.abstractmethod
    def subtract_one(self, x: T) -> "CompactSet[T]":
        ...

    @property
    @abc.abstractmethod
    def size(self) -> int:
        ...

    @property
    @abc.abstractmethod
    def uncompacted_size(self) -> int:
        ...

    @abc.abstractmethod
    def subset(self) -> "CompactSet[T]":
        """A monotone, especially-compact subset of self."""

    @abc.abstractmethod
    def materialize(self) -> set[T]:
        ...


class IntPrefixSet(CompactSet[int]):
    """{0..watermark-1} union values, with values >= watermark sparse.

    Reference: compact/IntPrefixSet.scala:206+ (construction, compaction on
    add, union/diff over (watermark, values) pairs, proto ser/de).
    """

    __slots__ = ("watermark", "values")

    def __init__(self, watermark: int = 0,
                 values: Optional[Iterable[int]] = None):
        self.watermark = watermark
        self.values: set[int] = set(values) if values else set()
        self._compact()

    @classmethod
    def from_watermark(cls, watermark: int) -> "IntPrefixSet":
        return cls(watermark)

    @classmethod
    def from_set(cls, values: Iterable[int]) -> "IntPrefixSet":
        return cls(0, values)

    def __repr__(self):
        return f"IntPrefixSet({self.watermark}, {sorted(self.values)})"

    def __eq__(self, other):
        return (isinstance(other, IntPrefixSet)
                and self.watermark == other.watermark
                and self.values == other.values)

    def __hash__(self):
        return hash((self.watermark, frozenset(self.values)))

    def _absorb(self) -> None:
        # Absorb the contiguous run at the watermark into the watermark.
        # Values strictly below the old watermark cannot appear here
        # (add() refuses them; only construction/bulk ops introduce
        # them, and those run the full _compact), so no filter pass is
        # needed -- a rebuild per add() would make scattered adds
        # quadratic (libbench caught exactly that).
        while self.watermark in self.values:
            self.values.discard(self.watermark)
            self.watermark += 1

    def _compact(self) -> None:
        # Drop values below the watermark, then absorb the run at it.
        self.values = {x for x in self.values if x >= self.watermark}
        self._absorb()

    def add(self, x: int) -> bool:
        if self.contains(x):
            return True
        self.values.add(x)
        self._absorb()
        return False

    def contains(self, x: int) -> bool:
        return x < self.watermark or x in self.values

    def union(self, other: "IntPrefixSet") -> "IntPrefixSet":
        return IntPrefixSet(max(self.watermark, other.watermark),
                            self.values | other.values)

    def diff(self, other: "IntPrefixSet") -> "IntPrefixSet":
        return IntPrefixSet.from_set(set(self.materialized_diff(other)))

    def materialized_diff(self, other: "IntPrefixSet") -> Iterator[int]:
        """Lazily yield elements of self not in other
        (IntPrefixSet.DiffIterator)."""
        for x in range(min(self.watermark, other.watermark), self.watermark):
            if not other.contains(x):
                yield x
        for x in self.values:
            if not other.contains(x):
                yield x

    def add_all(self, other: "IntPrefixSet") -> "IntPrefixSet":
        self.watermark = max(self.watermark, other.watermark)
        self.values |= other.values
        self._compact()
        return self

    def subtract_all(self, other: "IntPrefixSet") -> "IntPrefixSet":
        remaining = set(self.materialized_diff(other))
        self.watermark = 0
        self.values = remaining
        self._compact()
        return self

    def subtract_one(self, x: int) -> "IntPrefixSet":
        # Subtracting below the watermark un-compacts the prefix.
        if x < self.watermark:
            self.values |= set(range(self.watermark))
            self.watermark = 0
        self.values.discard(x)
        self._absorb()
        return self

    @property
    def size(self) -> int:
        return self.watermark + len(self.values)

    @property
    def uncompacted_size(self) -> int:
        return len(self.values)

    def subset(self) -> "IntPrefixSet":
        """The watermark-only part; monotone (IntPrefixSet `subset`)."""
        return IntPrefixSet.from_watermark(self.watermark)

    def materialize(self) -> set[int]:
        return set(range(self.watermark)) | self.values

    def to_dict(self) -> dict:
        """Wire form (IntPrefixSetProto)."""
        return {"watermark": self.watermark, "values": sorted(self.values)}

    @classmethod
    def from_dict(cls, d: dict) -> "IntPrefixSet":
        return cls(d["watermark"], d["values"])


class FakeCompactSet(CompactSet[T]):
    """An uncompacted CompactSet for tests (compact/FakeCompactSet.scala)."""

    def __init__(self, values: Optional[Iterable[T]] = None):
        self._values: set[T] = set(values) if values else set()

    def __repr__(self):
        return f"FakeCompactSet({self._values!r})"

    def __eq__(self, other):
        return (isinstance(other, FakeCompactSet)
                and self._values == other._values)

    def add(self, x: T) -> bool:
        existed = x in self._values
        self._values.add(x)
        return existed

    def contains(self, x: T) -> bool:
        return x in self._values

    def union(self, other: "FakeCompactSet[T]") -> "FakeCompactSet[T]":
        return FakeCompactSet(self._values | other._values)

    def diff(self, other: "FakeCompactSet[T]") -> "FakeCompactSet[T]":
        return FakeCompactSet(self._values - other._values)

    def materialized_diff(self, other: "FakeCompactSet[T]") -> Iterable[T]:
        return self._values - other._values

    def add_all(self, other: "FakeCompactSet[T]") -> "FakeCompactSet[T]":
        self._values |= other._values
        return self

    def subtract_all(self, other: "FakeCompactSet[T]") -> "FakeCompactSet[T]":
        self._values -= other._values
        return self

    def subtract_one(self, x: T) -> "FakeCompactSet[T]":
        self._values.discard(x)
        return self

    @property
    def size(self) -> int:
        return len(self._values)

    @property
    def uncompacted_size(self) -> int:
        return len(self._values)

    def subset(self) -> "FakeCompactSet[T]":
        return FakeCompactSet(self._values)

    def materialize(self) -> set[T]:
        return set(self._values)
