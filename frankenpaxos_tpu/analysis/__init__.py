"""paxlint: AST-based contract checking for the actor runtime, TPU hot
paths, and wire codecs.

FrankenPaxos's value proposition is that every protocol is written once
against a single-threaded actor/transport contract and runs unchanged in
production, simulation, and visualization -- and the TPU-first rules
behind the ``TpuQuorumChecker`` north star (no host syncs or retrace
hazards inside the drain path) are what keep the run pipeline's
multi-x win from silently regressing. Neither contract is expressible
in the type system, so this package makes them machine-checked:

  * ``actor_rules``  -- PAX1xx: the single-threaded actor contract
    (no threads/locks/sleeps in handlers, transport-owned timers, no
    shared module state, no sends from off-loop code).
  * ``hotpath_rules`` -- TPU2xx: no host synchronization or retrace
    hazards in code reachable from ``on_drain``, the run-pipeline
    handlers (``Phase2aRun``/``Phase2bRange``/``ChosenRun``), or the
    ``ops/`` kernels.
  * ``codec_rules``  -- COD3xx: every wire-sent message has a
    registered codec (or a recorded grandfathering), and each codec's
    encode/decode cover the same field set.

Run it with ``python -m frankenpaxos_tpu.analysis``; see
``docs/ANALYSIS.md`` for rule IDs, suppression pragmas
(``# paxlint: disable=<rule>``), and baseline management.
"""

from frankenpaxos_tpu.analysis.core import Finding, Project, RULES, run_rules

__all__ = ["Finding", "Project", "RULES", "run_rules"]
