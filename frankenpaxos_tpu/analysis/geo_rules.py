"""paxgeo determinism-contract rules (GEO8xx).

  * GEO801 -- a wall-clock read or unseeded randomness inside the geo
    simulation layer (``geo/``). The whole wide-area suite rests on
    one invariant: same seed => byte-identical event sequence (the
    committed golden test, the sharp virtual-latency gates in
    bench/geo_lt.py, minimizer-replayable chaos traces). One
    ``time.time()`` in a delay computation or one module-level
    ``random.random()`` silently breaks all three. Virtual time comes
    from ``GeoSimTransport.now``; randomness comes from a
    ``random.Random`` seeded with a STRING key (sha512 seeding --
    stable across processes, unlike ``hash()`` under
    PYTHONHASHSEED).

Seeded generators (``random.Random(...)`` instances) and reading the
virtual clock are of course fine; only the module-level conveniences
and OS entropy/clock sources are flagged.
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    Project,
    register_rules,
)

RULES = {
    "GEO801": "wall-clock read or unseeded randomness in the geo "
              "simulation layer (breaks same-seed determinism)",
}

#: Dotted call names that introduce nondeterminism. ``random.Random``
#: (the seeded constructor) is explicitly NOT here.
_FORBIDDEN_CALLS = frozenset({
    "time.time", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.time_ns",
    "datetime.now", "datetime.datetime.now", "datetime.utcnow",
    "os.urandom", "uuid.uuid4", "uuid.uuid1", "secrets.token_bytes",
    "random.random", "random.randint", "random.randrange",
    "random.uniform", "random.choice", "random.choices",
    "random.shuffle", "random.sample", "random.getrandbits",
    "numpy.random.random", "numpy.random.rand", "numpy.random.randn",
    "numpy.random.randint", "np.random.random", "np.random.rand",
    "np.random.randn", "np.random.randint",
})


def check(project: Project):
    findings: list = []
    base = f"{project.package}/geo/"
    for mod in project:
        if not mod.path.startswith(base):
            continue
        if not focused(project, mod.path):
            continue
        for node in cached_walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted(node.func)
            if callee not in _FORBIDDEN_CALLS:
                continue
            findings.append(Finding(
                rule="GEO801", file=mod.path, line=node.lineno,
                scope=callee, detail=callee,
                message=f"{callee}() in the geo simulation layer "
                        "breaks the same-seed determinism contract "
                        "(golden delivery order, virtual-latency "
                        "gates, minimizer replays) -- take the "
                        "virtual clock from the transport and draw "
                        "jitter from a string-seeded random.Random "
                        "(docs/GEO.md)"))
    return findings


register_rules(RULES, check)
