"""SAFE9xx: Paxos safety disciplines as dataflow over role-state writes.

Every safety bug this repo has shipped-then-caught was found
dynamically by chaos soaks (the PR 3 next_slot-unclamped double-choose,
the PR 5 adopted-epoch double-choose, the PR 9 restart-ballot reuse) --
and soaks cover 4 of the 20 protocols. These rules enforce the
disciplines the Flexible Paxos / WPaxos safety arguments rest on
MECHANICALLY, over every protocol unit:

  * SAFE901 -- ballot/round adoption without a monotonicity guard: a
    handler stores an incoming round into role state
    (``self.round = msg.round``) with no comparison against the stored
    round anywhere on the handler path. An unguarded adoption lets a
    stale leader roll the promise backwards, breaking the quorum
    intersection argument.
  * SAFE902 -- vote-store writes that are not write-once-per
    (slot, ballot): overwriting a vote record without a round compare
    or an existing-entry check lets one acceptor report two different
    values for the same (slot, ballot) -- two choosable values.
  * SAFE903 -- ``next_slot`` derived from the Phase1 voted max without
    a chosen-watermark clamp (the PR 3 double-choose class): Phase1bs
    report nothing below the watermark, so ``voted_max + 1`` can land
    INSIDE already-chosen slots and re-propose fresh commands there.
  * SAFE904 -- watermark fields updated non-monotonically: a plain
    assignment (no ``max()``, no guard) lets a stale/duplicate message
    rewind GC or execution watermarks, un-protecting state the role
    already discarded.
  * SAFE905 -- promise state mutated after the corresponding
    Phase1b/WPhase1b send in the same handler: the promise must be
    complete BEFORE it is announced -- post-send mutation diverges
    between SimTransport (by-reference: receiver sees the final state)
    and TcpTransport (serialized at send: receiver sees the stale one),
    and under durability the WAL record order inverts.

Scope: Actor subclasses under ``protocols/``, ``reconfig/`` and
``geo/`` (the protocol units), over the PAX1xx handler closure
(``receive``/``on_drain`` + self-call/timer-callback closure).
Guards resolve INTERPROCEDURALLY through that closure: a round compare
in the dispatching handler clears the adoption inside the helper it
calls (the ``_handle_phase2a_run`` -> ``_store_run`` shape).
Justified exceptions carry ``# paxlint: disable=SAFE90x`` with the
safety argument in the comment.
"""

from __future__ import annotations

import ast
import re

from frankenpaxos_tpu.analysis.actor_rules import (
    _actor_classes,
    _handler_closure,
)
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    Project,
    register_rules,
)

RULES = {
    "SAFE901": "ballot/round adoption without a monotonicity guard on "
               "the stored round",
    "SAFE902": "vote-store write that is not write-once-per-"
               "(slot, ballot)",
    "SAFE903": "next_slot derived from the Phase1 voted max without a "
               "chosen-watermark clamp",
    "SAFE904": "watermark field updated non-monotonically (assignment "
               "without max()/guard)",
    "SAFE905": "promise state mutated after the Phase1b send in the "
               "same handler",
}

#: Module-path segments that mark protocol units (matched like the
#: PAX111 scopes, so fixture projects scope identically).
_ROLE_SCOPES = ("/protocols/", "/reconfig/", "/geo/")

#: Round/ballot-valued state: ``round``, ``ballot``, ``vote_round``,
#: ``ballots[group]``... but NOT ``round_system``/``round_type``
#: (machinery, not state).
_ROUND_RE = re.compile(r"(^|_)(round|ballot)s?($|_)")
_ROUND_DENY = frozenset({"round_system", "roundsystem", "round_type",
                         "round_robin"})

#: Vote-store state (SAFE902): per-slot vote records. Deliberately
#: name-based on ``vote``/``accepted`` only -- leader-side ``states``
#: maps are per-instance STATE MACHINES, not vote stores.
_VOTE_RE = re.compile(r"(^|_)(vote|voted|votes|accepted)s?($|_)")
_VOTE_EXACT = frozenset()

_WATERMARK_RE = re.compile(r"watermark")
_WATERMARK_EXACT = frozenset({"max_voted_slot", "max_slot"})

_SEND_NAMES = frozenset({"send", "send_no_flush", "_wal_send",
                         "broadcast", "send_batch"})


def _in_scope(path: str) -> bool:
    return any(seg in path for seg in _ROLE_SCOPES)


def _is_round_field(name: str) -> bool:
    low = name.lower().lstrip("_")
    return low not in _ROUND_DENY and bool(_ROUND_RE.search(low))


def _is_vote_field(name: str) -> bool:
    low = name.lower().lstrip("_")
    return low in _VOTE_EXACT or bool(_VOTE_RE.search(low))


def _is_watermark_field(name: str) -> bool:
    low = name.lower().lstrip("_")
    return low in _WATERMARK_EXACT or bool(_WATERMARK_RE.search(low))


def _self_field(node: ast.AST) -> str | None:
    """``self.X`` / ``self.X[...]`` / ``self.X[...][...]`` -> ``X``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _field_writes(func: ast.AST):
    """Yield ``(stmt_node, field, target, value_or_None, augmented)``
    for every write to ``self.X`` / ``self.X[...]`` in ``func``,
    skipping nested function/class bodies (other scopes)."""
    yield from _field_writes_of(func, roots=list(
        ast.iter_child_nodes(func)))


def _field_writes_of(stmt: ast.AST, roots: list | None = None):
    """Like :func:`_field_writes` but over one statement subtree
    (the statement itself included)."""
    stack = roots if roots is not None else [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
        targets, value, augmented = [], None, False
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AugAssign):
            targets, value, augmented = [node.target], node.value, True
        for target, tvalue in _unpacked(targets, value):
            field = _self_field(target)
            if field is not None:
                yield node, field, target, tvalue, augmented


def _unpacked(targets: list, value):
    """Flatten tuple/list assignment targets, pairing each element with
    its RHS component when the RHS is a matching display
    (``self.a, self.b = m.x, m.y``) and with the whole RHS otherwise --
    a tuple-written round adoption must not be invisible."""
    for target in targets:
        if isinstance(target, (ast.Tuple, ast.List)):
            elements = target.elts
            if isinstance(value, (ast.Tuple, ast.List)) \
                    and len(value.elts) == len(elements):
                yield from zip(elements, value.elts)
            else:
                for element in elements:
                    yield element, value
        else:
            yield target, value


def _mentions(tree: ast.AST, pred) -> bool:
    """Any Name/Attribute leaf in ``tree`` whose name satisfies
    ``pred``."""
    for node in cached_walk(tree):
        if isinstance(node, ast.Attribute) and pred(node.attr):
            return True
        if isinstance(node, ast.Name) and pred(node.id):
            return True
    return False


def _has_guard_compare(func: ast.AST, pred) -> bool:
    """A Compare whose leaves mention a name satisfying ``pred`` --
    the shape of every monotonicity/write-once guard
    (``if msg.round < self.round``, ``while w in self.log``...)."""
    for node in cached_walk(func):
        if isinstance(node, ast.Compare):
            if _mentions(node, pred):
                return True
    return False


def _reads_self_field(tree: ast.AST, field: str) -> bool:
    for node in cached_walk(tree):
        if isinstance(node, ast.Attribute) and node.attr == field \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and isinstance(node.ctx, ast.Load):
            return True
    return False


def _calls_get_on(func: ast.AST, field: str) -> bool:
    """``self.<field>.get(...)`` / ``self.<field>[...].get(...)`` --
    the read-before-write shape of a write-once check."""
    for node in cached_walk(func):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "setdefault") \
                and _self_field(node.func.value) == field:
            return True
    return False


def _is_constant(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.operand,
                                                    ast.Constant):
        return True
    return False


def _closure_callers(closure: dict) -> dict:
    """method name -> set of DIRECT caller method names, within the
    handler closure."""
    callers: dict = {name: set() for name in closure}
    for name, func in closure.items():
        for node in cached_walk(func):
            if isinstance(node, ast.Call):
                called = dotted(node.func)
                if called.startswith("self.") and called.count(".") == 1:
                    callee = called.split(".", 1)[1]
                    if callee in callers:
                        callers[callee].add(name)
    return callers


def _guard_contexts(name: str, closure: dict, callers: dict) -> list:
    """The function plus every transitive caller inside the closure:
    a guard anywhere on the call-in path clears the write."""
    seen = {name}
    frontier = [name]
    while frontier:
        cur = frontier.pop()
        for caller in callers.get(cur, ()):
            if caller not in seen:
                seen.add(caller)
                frontier.append(caller)
    return [closure[n] for n in seen]


def _is_phase1b_ctor(name: str) -> bool:
    """Promise announcements: ``Phase1b``, ``WPhase1b``,
    ``MatchPhase1b``... -- but never the ``*Nack`` refusals."""
    return "Phase1b" in name and "Nack" not in name


def _phase1b_sends(func: ast.AST) -> list:
    """The send CALL NODES whose message is (or aliases a local
    assigned from) a ``*Phase1b*`` construction."""
    locals_p1b: set = set()
    out = []
    for node in cached_walk(func):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _is_phase1b_ctor(dotted(node.value.func)
                                     .split(".")[-1]):
            locals_p1b.add(node.targets[0].id)
    for node in cached_walk(func):
        if not (isinstance(node, ast.Call)
                and dotted(node.func).split(".")[-1] in _SEND_NAMES):
            continue
        for arg in node.args[1:] + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in locals_p1b:
                out.append(node)
                break
            if any(isinstance(sub, ast.Call)
                   and _is_phase1b_ctor(dotted(sub.func).split(".")[-1])
                   for sub in cached_walk(arg)):
                out.append(node)
                break
    return out


def _post_send_statements(func: ast.AST, send_call: ast.Call) -> list:
    """The statements CONTROL-FLOW-AFTER ``send_call`` inside ``func``:
    for each block on the send's ancestor chain, the statements
    following the ancestor -- stopping outward once a block's tail
    guarantees termination (return/raise), and never crossing into a
    sibling branch of the same ``if`` (line numbers alone would)."""
    # Parent map over the statement tree (cheap: one walk per call).
    parents: dict = {}
    for node in cached_walk(func):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    # The statement containing the send.
    stmt = send_call
    while id(stmt) in parents and not isinstance(stmt, ast.stmt):
        stmt = parents[id(stmt)]
    out: list = []
    while isinstance(stmt, ast.stmt):
        parent = parents.get(id(stmt))
        if parent is None:
            break
        for field in ("body", "orelse", "finalbody", "handlers"):
            block = getattr(parent, field, None)
            if isinstance(block, list) and stmt in block:
                i = block.index(stmt)
                tail = block[i + 1:]
                out.extend(tail)
                if any(isinstance(s, (ast.Return, ast.Raise,
                                      ast.Continue, ast.Break))
                       for s in tail):
                    return out
                break
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda, ast.ClassDef)):
            # A send inside a nested def (the resend-timer idiom) has
            # no post-send region in the ENCLOSING handler: the outer
            # statements run before the timer ever fires.
            break
        if not isinstance(parent, ast.stmt):
            break
        stmt = parent
    return out


def _is_slot_cursor(field: str) -> bool:
    low = field.lower().lstrip("_")
    return ("next" in low and "slot" in low) \
        or low in ("delegate_start", "start_slot")


def _watermark_leaf(name: str) -> bool:
    low = name.lower()
    return "watermark" in low or "chosen" in low


def _local_env(func: ast.AST) -> dict:
    """name -> [RHS exprs] for every bare-Name assignment in ``func``
    (all of them: provenance is merged conservatively toward
    cleanliness)."""
    env: dict = {}
    for node in cached_walk(func):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    env.setdefault(target.id, []).append(node.value)
    return env


#: Names that LOOK slot/round-valued but are machinery, never state.
_MACHINERY = frozenset({"slot_system", "round_system", "roundsystem"})


def _slot_leaves(expr: ast.AST, func: ast.AST,
                 env: dict | None = None,
                 exclude: str | None = None) -> tuple:
    """(watermark, voted_max, params) over ``expr`` with ONE level of
    local-name expansion. ``watermark`` counts any leaf (message
    fields included); ``voted_max`` counts only bare locals and
    ``self.*`` reads whose name says max/slot (a ``msg.start_slot``
    field was clamped by its producer -- the producer's own write is
    where the rule bites). ``exclude`` drops the field being written
    (reading yourself is not a voted max)."""
    if env is None:
        env = _local_env(func)
    params = {a.arg for a in getattr(func, "args").args[1:]} \
        if hasattr(func, "args") else set()
    watermark = False
    voted_max = False
    params_used: set = set()
    seen_locals: set = set()

    def slotish(name: str) -> bool:
        low = name.lower()
        return name != exclude and low not in _MACHINERY \
            and ("max" in low or "slot" in low)

    def scan(node: ast.AST, expand: bool) -> None:
        nonlocal watermark, voted_max
        for sub in cached_walk(node):
            if isinstance(sub, ast.Attribute):
                if _watermark_leaf(sub.attr):
                    watermark = True
                elif isinstance(sub.value, ast.Name) \
                        and sub.value.id == "self" \
                        and slotish(sub.attr):
                    voted_max = True
            elif isinstance(sub, ast.Name):
                low = sub.id.lower()
                if _watermark_leaf(low):
                    watermark = True
                elif slotish(sub.id):
                    voted_max = True
                if sub.id in params:
                    # Only DIRECT param reads defer to call sites; a
                    # param buried one expansion deep (a comprehension
                    # over message fields) is this site's own value.
                    if expand:
                        params_used.add(sub.id)
                elif expand and sub.id in env \
                        and sub.id not in seen_locals:
                    seen_locals.add(sub.id)
                    for rhs in env[sub.id]:
                        scan(rhs, False)

    scan(expr, True)
    return watermark, voted_max, params_used


def _check_next_slot(mod, cls, closure: dict) -> list:
    """SAFE903 proper: see the family docstring."""
    findings: list = []
    #: callee -> {param: [fields it writes into]} for deferred
    #: call-site checks.
    deferred: dict = {}
    for name, func in closure.items():
        scope = f"{cls.name}.{name}"
        env = _local_env(func)
        for node, field, target, value, augmented in _field_writes(func):
            if not _is_slot_cursor(field) or augmented \
                    or value is None or _is_constant(value):
                continue
            watermark, voted_max, params_used = _slot_leaves(
                value, func, env, exclude=field)
            if watermark:
                continue
            if _has_guard_compare(
                    func, lambda n, f=field: n == f
                    or "next_slot" in n.lower()):
                continue  # a monotone guard on the cursor itself
            if params_used:
                for p in params_used:
                    deferred.setdefault(name, {}).setdefault(
                        p, []).append(field)
                continue
            if voted_max:
                findings.append(Finding(
                    rule="SAFE903", file=mod.path, line=node.lineno,
                    scope=scope, detail=f"self.{field}",
                    message=f"self.{field} derived from a voted max "
                            f"with no chosen-watermark clamp: Phase1bs "
                            f"report nothing below the watermark, so "
                            f"voted_max+1 can re-propose into "
                            f"already-chosen slots (clamp with "
                            f"max(..., chosen_watermark))"))
    if not deferred:
        return findings
    # Call sites of the deferred helpers: the clamp must exist where
    # the slot value is COMPUTED.
    for name, func in closure.items():
        scope = f"{cls.name}.{name}"
        env = _local_env(func)
        for node in cached_walk(func):
            if not isinstance(node, ast.Call):
                continue
            called = dotted(node.func)
            if not (called.startswith("self.")
                    and called.count(".") == 1):
                continue
            callee = called.split(".", 1)[1]
            if callee not in deferred:
                continue
            callee_params = [a.arg for a in
                             closure[callee].args.args[1:]]
            bindings = list(zip(callee_params, node.args)) + [
                (kw.arg, kw.value) for kw in node.keywords]
            for pname, arg in bindings:
                if pname not in deferred[callee]:
                    continue
                watermark, voted_max, _ = _slot_leaves(arg, func, env)
                if watermark or not voted_max:
                    continue
                # A clamp expressed as a guard must compare THE VALUE
                # BEING PASSED against the watermark -- an unrelated
                # watermark compare elsewhere in the function is not a
                # clamp.
                if isinstance(arg, ast.Name) and any(
                        isinstance(cmp, ast.Compare)
                        and _mentions(cmp, _watermark_leaf)
                        and _mentions(cmp, lambda n, a=arg.id: n == a)
                        for cmp in cached_walk(func)):
                    continue
                fields = sorted(set(deferred[callee][pname]))
                findings.append(Finding(
                    rule="SAFE903", file=mod.path, line=node.lineno,
                    scope=scope, detail=f"{callee}:{pname}",
                    message=f"slot cursor(s) "
                            f"{', '.join('self.' + f for f in fields)} "
                            f"set via self.{callee}({pname}=...) from "
                            f"a voted max with no chosen-watermark "
                            f"clamp: Phase1bs report nothing below "
                            f"the watermark, so voted_max+1 can "
                            f"re-propose into already-chosen slots "
                            f"(clamp with "
                            f"max(..., chosen_watermark))"))
    return findings


def check(project: Project):
    findings: list = []
    for mod, cls in _actor_classes(project):
        if not _in_scope(mod.path):
            continue
        if not focused(project, mod.path):
            continue
        closure = _handler_closure(cls)
        if not closure:
            continue
        callers = _closure_callers(closure)
        for name, func in closure.items():
            scope = f"{cls.name}.{name}"
            contexts = None  # computed lazily, shared by every rule

            def guards():
                nonlocal contexts
                if contexts is None:
                    contexts = _guard_contexts(name, closure, callers)
                return contexts

            for node, field, target, value, augmented in \
                    _field_writes(func):
                # --- SAFE901: round adoption needs a monotonicity
                # guard somewhere on the handler path.
                if _is_round_field(field) and not field.startswith(
                        ("vote", "_vote")):
                    if augmented or value is None \
                            or _is_constant(value) \
                            or _reads_self_field(value, field):
                        pass  # bump / reset / self-derived: monotone
                    elif not any(_has_guard_compare(ctx, _is_round_field)
                                 for ctx in guards()):
                        findings.append(Finding(
                            rule="SAFE901", file=mod.path,
                            line=node.lineno, scope=scope,
                            detail=f"self.{field}",
                            message=f"handler adopts a round into "
                                    f"self.{field} with no comparison "
                                    f"against the stored round on the "
                                    f"handler path: a stale message "
                                    f"can roll the promise backwards "
                                    f"(compare msg round to "
                                    f"self.{field}, or use max())"))
                # --- SAFE902: vote-store writes must be write-once
                # per (slot, ballot).
                if _is_vote_field(field) and not augmented \
                        and value is not None and not _is_constant(value):
                    ok = any(
                        _has_guard_compare(ctx, _is_round_field)
                        or _calls_get_on(ctx, field)
                        for ctx in guards())
                    if not ok:
                        findings.append(Finding(
                            rule="SAFE902", file=mod.path,
                            line=node.lineno, scope=scope,
                            detail=f"self.{field}",
                            message=f"vote-store write to self.{field} "
                                    f"with neither a round compare nor "
                                    f"an existing-entry check on the "
                                    f"handler path: votes must be "
                                    f"write-once per (slot, ballot) or "
                                    f"one acceptor can report two "
                                    f"values for one (slot, ballot)"))
                # --- SAFE904: watermark updates must be monotone.
                if _is_watermark_field(field) and not augmented \
                        and value is not None and not _is_constant(value):
                    is_max = isinstance(value, ast.Call) \
                        and dotted(value.func) == "max" \
                        and any(_self_field(a) == field
                                for a in value.args)
                    # A Load of the field anywhere in the function
                    # counts as a guard: the wm = self.W; while ...:
                    # wm += 1; self.W = wm walk is monotone by
                    # construction.
                    guarded = is_max \
                        or _reads_self_field(func, field) \
                        or any(_has_guard_compare(
                            ctx, _is_watermark_field)
                            for ctx in guards())
                    if not guarded:
                        findings.append(Finding(
                            rule="SAFE904", file=mod.path,
                            line=node.lineno, scope=scope,
                            detail=f"self.{field}",
                            message=f"non-monotone watermark update to "
                                    f"self.{field}: a stale/duplicate "
                                    f"message can rewind it and "
                                    f"un-protect discarded state (use "
                                    f"max(self.{field}, ...) or guard "
                                    f"the assignment)"))
            # --- SAFE905: no promise mutation after the Phase1b send
            # (control-flow-after, not merely line-after: a sibling
            # elif branch is NOT post-send).
            for send_call in _phase1b_sends(func):
                post = _post_send_statements(func, send_call)
                for stmt in post:
                    for node, field, target, value, augmented in \
                            _field_writes_of(stmt):
                        if _is_round_field(field):
                            findings.append(Finding(
                                rule="SAFE905", file=mod.path,
                                line=node.lineno, scope=scope,
                                detail=f"self.{field}",
                                message=f"self.{field} mutated after "
                                        f"the Phase1b send at line "
                                        f"{send_call.lineno}: the "
                                        f"promise must be complete "
                                        f"before it is announced (sim "
                                        f"delivers by reference, TCP "
                                        f"serializes at send -- the "
                                        f"two diverge)"))
        # --- SAFE903: slot cursors derived from the Phase1 voted max
        # must clamp to the chosen watermark (the PR 3 double-choose
        # class), tracked through one level of local provenance and
        # through sender-helper parameters.
        findings.extend(_check_next_slot(mod, cls, closure))
    return findings


register_rules(RULES, check)
