"""ALIAS10xx: sim-vs-deployed mutable-aliasing divergence races.

SimTransport delivers message OBJECTS by reference; TcpTransport
serializes at send time. The two agree only when messages are
effectively immutable: a handler that embeds a live mutable container
in an outgoing message -- or mutates a message it received -- behaves
differently in simulation than deployed, which is exactly the class of
bug the chaos soaks can never catch (the sim IS the oracle).

  * ALIAS1001 -- a send whose message embeds an alias of mutable self
    state: a ``list``/``dict``/``set``/``deque`` field passed into a
    message construction without ``tuple()``/``copy()``/freezing,
    where some handler later mutates that field. In the sim the
    receiver observes the mutation (time travel); deployed it does
    not.
  * ALIAS1002 -- a handler mutates a message object it received
    (``message.field = x``, ``message.values.append(...)``): visible
    to the sender and to other recipients in sim only.

Scope: Actor subclasses under ``protocols/``, ``reconfig/`` and
``geo/``, over the PAX1xx handler closure. Sends resolve through the
closure's helpers (``_wal_send``, ``send_batch``, and class-local
sender helpers whose parameter flows into the message construction);
received-message taint propagates through ``receive``'s dispatch calls
into ``_handle_*`` helpers. Justified exceptions carry
``# paxlint: disable=ALIAS100x`` with the argument for why the alias
cannot race (e.g. the field is never mutated after the send by
construction).
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.actor_rules import (
    _actor_classes,
    _handler_closure,
)
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    Project,
    register_rules,
)
from frankenpaxos_tpu.analysis.safety_rules import _in_scope, _self_field

RULES = {
    "ALIAS1001": "message embeds an alias of mutable self state "
                 "(sim delivers by reference; TCP serializes)",
    "ALIAS1002": "handler mutates a received message object (visible "
                 "to the sender in sim only)",
}

#: Constructors whose result is mutable (a field initialized to one is
#: aliasing-hazardous when embedded in a message).
_MUTABLE_CTORS = frozenset({
    "dict", "list", "set", "defaultdict", "deque", "OrderedDict",
    "Counter", "SortedDict", "SortedSet", "bytearray",
})

#: Calls whose RESULT is a fresh object: wrapping the field in one
#: breaks the alias.
_SANITIZERS = frozenset({
    "tuple", "list", "dict", "set", "frozenset", "sorted", "bytes",
    "copy", "deepcopy", "min", "max", "len", "sum", "str", "repr",
    "enumerate",
})

#: Method names that mutate their receiver in place.
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "add", "insert",
    "update", "setdefault", "pop", "popitem", "popleft", "remove",
    "discard", "clear", "sort", "reverse",
})

_SEND_NAMES = frozenset({"send", "send_no_flush", "_wal_send",
                         "broadcast", "send_batch"})


def _mutable_fields(cls: ast.ClassDef) -> set:
    """Fields initialized to a mutable container anywhere in the class
    (``__init__``, recovery helpers, handlers)."""
    out: set = set()
    for node in cached_walk(cls):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        for target in targets:
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            v = node.value
            mutable = isinstance(v, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp)) \
                or (isinstance(v, ast.Call)
                    and dotted(v.func).split(".")[-1] in _MUTABLE_CTORS)
            if mutable:
                out.add(target.attr)
    return out


def _mutated_fields(closure: dict) -> set:
    """Fields some handler-closure method mutates in place."""
    out: set = set()
    for func in closure.values():
        for node in cached_walk(func):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                field = _self_field(node.func.value)
                if field is not None:
                    out.add(field)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for target in targets:
                    if isinstance(target, ast.Subscript):
                        field = _self_field(target)
                        if field is not None:
                            out.add(field)
            elif isinstance(node, ast.Delete):
                for target in node.targets:
                    if isinstance(target, ast.Subscript):
                        field = _self_field(target)
                        if field is not None:
                            out.add(field)
    return out


def _alias_leaks(expr: ast.AST, fields: set, names: set) -> list:
    """``(kind, name, node)`` for every UNSANITIZED embedding of
    ``self.<field in fields>`` (kind "self") or a bare ``Name in
    names`` (kind "name") inside ``expr``. A wrapping call to a
    sanitizer -- or any method call / subscript, whose result is a
    different object -- breaks the alias."""
    out: list = []

    def visit(node: ast.AST, sanitized: bool) -> None:
        if isinstance(node, ast.Call):
            leaf = dotted(node.func).split(".")[-1]
            arg_sanitized = sanitized or leaf in _SANITIZERS
            # The callee expression itself never embeds its owner.
            visit(node.func, True)
            for arg in node.args:
                visit(arg, arg_sanitized)
            for kw in node.keywords:
                visit(kw.value, arg_sanitized)
            return
        if isinstance(node, ast.Subscript):
            # Element reads are a different (possibly still mutable)
            # object; out of scope for this rule.
            visit(node.value, True)
            if isinstance(node.slice, ast.AST):
                visit(node.slice, True)
            return
        if isinstance(node, ast.Attribute):
            field = _self_field(node)
            if field is not None:
                if not sanitized and field in fields \
                        and isinstance(node.ctx, ast.Load):
                    out.append(("self", field, node))
                return
            visit(node.value, True)  # obj.attr: a different object
            return
        if isinstance(node, ast.Name):
            if not sanitized and node.id in names \
                    and isinstance(node.ctx, ast.Load):
                out.append(("name", node.id, node))
            return
        for child in ast.iter_child_nodes(node):
            visit(child, sanitized)

    visit(expr, False)
    return out


def _methods(cls: ast.ClassDef) -> dict:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _message_exprs(func: ast.AST):
    """``(send_call, expr)`` for every message expression handed to a
    send-like call in ``func``: every arg past the destination, with
    local names resolved to the construction they alias."""
    local_ctors: dict = {}
    for node in cached_walk(func):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            local_ctors[node.targets[0].id] = node.value
    for node in cached_walk(func):
        if not (isinstance(node, ast.Call)
                and dotted(node.func).split(".")[-1] in _SEND_NAMES):
            continue
        for arg in node.args[1:] + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name) and arg.id in local_ctors:
                yield node, local_ctors[arg.id]
            else:
                yield node, arg


def _sender_param_sinks(cls: ast.ClassDef) -> dict:
    """method name -> set of parameter names that flow UNSANITIZED into
    a message expression of a send inside that method (the sender-
    helper shape: ``def _reply(self, dst, values): self.send(dst,
    Msg(values=values))``)."""
    out: dict = {}
    for name, func in _methods(cls).items():
        params = {a.arg for a in func.args.args[1:]}
        if not params:
            continue
        sinks: set = set()
        for _, expr in _message_exprs(func):
            for kind, leak, _node in _alias_leaks(expr, set(), params):
                if kind == "name":
                    sinks.add(leak)
        if sinks:
            out[name] = sinks
    return out


def _check_alias1001(mod, cls, closure, findings: list) -> None:
    mutable = _mutable_fields(cls)
    if not mutable:
        return
    hazardous = mutable & _mutated_fields(closure)
    if not hazardous:
        return
    sinks = _sender_param_sinks(cls)
    methods = _methods(cls)
    for name, func in closure.items():
        scope = f"{cls.name}.{name}"
        # Direct sends (and sends of locally-constructed messages).
        for send, expr in _message_exprs(func):
            for kind, field, node in _alias_leaks(expr, hazardous,
                                                  set()):
                findings.append(Finding(
                    rule="ALIAS1001", file=mod.path, line=node.lineno,
                    scope=scope, detail=f"self.{field}",
                    message=f"message embeds live mutable self.{field} "
                            f"(a handler later mutates it): sim "
                            f"delivers the alias, TCP serializes a "
                            f"snapshot -- freeze it (tuple()/copy()) "
                            f"at the send"))
        # Sender helpers: the alias leaks at the CALL SITE.
        for node in cached_walk(func):
            if not isinstance(node, ast.Call):
                continue
            called = dotted(node.func)
            if not (called.startswith("self.")
                    and called.count(".") == 1):
                continue
            helper = called.split(".", 1)[1]
            if helper not in sinks or helper in _SEND_NAMES:
                continue
            helper_func = methods.get(helper)
            if helper_func is None:
                continue
            params = [a.arg for a in helper_func.args.args[1:]]
            bindings = list(zip(params, node.args)) + [
                (kw.arg, kw.value) for kw in node.keywords]
            for pname, arg in bindings:
                if pname not in sinks[helper]:
                    continue
                for kind, field, leak_node in _alias_leaks(
                        arg, hazardous, set()):
                    findings.append(Finding(
                        rule="ALIAS1001", file=mod.path,
                        line=leak_node.lineno, scope=scope,
                        detail=f"self.{field}",
                        message=f"live mutable self.{field} flows "
                                f"through self.{helper}() into a sent "
                                f"message: freeze it (tuple()/copy()) "
                                f"before handing it to the sender "
                                f"helper"))


def _tainted_params(cls: ast.ClassDef, closure: dict) -> dict:
    """method name -> set of parameter names bound to a RECEIVED
    message: ``receive``'s message param, ``_handle_*`` params past
    ``src``, plus class-local propagation through calls that pass a
    tainted name along."""
    taint: dict = {name: set() for name in closure}
    for name, func in closure.items():
        args = [a.arg for a in func.args.args]
        if name == "receive" and len(args) >= 3:
            taint[name].update(args[2:])
        elif name.startswith("_handle") and len(args) >= 3:
            taint[name].update(args[2:])
    changed = True
    while changed:
        changed = False
        for name, func in closure.items():
            if not taint[name]:
                continue
            for node in cached_walk(func):
                if not isinstance(node, ast.Call):
                    continue
                called = dotted(node.func)
                if not (called.startswith("self.")
                        and called.count(".") == 1):
                    continue
                callee = called.split(".", 1)[1]
                if callee not in closure:
                    continue
                callee_args = [a.arg for a in
                               closure[callee].args.args][1:]
                for i, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name) \
                            and arg.id in taint[name] \
                            and i < len(callee_args) \
                            and callee_args[i] not in taint[callee]:
                        taint[callee].add(callee_args[i])
                        changed = True
                for kw in node.keywords:
                    if isinstance(kw.value, ast.Name) \
                            and kw.value.id in taint[name] \
                            and kw.arg in callee_args \
                            and kw.arg not in taint[callee]:
                        taint[callee].add(kw.arg)
                        changed = True
    return taint


def _root_name(node: ast.AST) -> str | None:
    """The base Name of an attribute/subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _check_alias1002(mod, cls, closure, findings: list) -> None:
    taint = _tainted_params(cls, closure)
    for name, func in closure.items():
        tainted = set(taint.get(name, ()))
        if not tainted:
            continue
        scope = f"{cls.name}.{name}"

        def flag(node, what: str) -> None:
            findings.append(Finding(
                rule="ALIAS1002", file=mod.path, line=node.lineno,
                scope=scope, detail=what,
                message=f"handler mutates received message state "
                        f"({what}): the sender (and every other "
                        f"recipient) observes it in sim but not over "
                        f"TCP -- copy before mutating"))

        for node in cached_walk(func):
            # Track locals aliasing message internals
            # (``deps = msg.deps`` then ``deps.add(...)``).
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value,
                                   (ast.Attribute, ast.Subscript)):
                root = _root_name(node.value)
                if root in tainted:
                    tainted.add(node.targets[0].id)
        for node in cached_walk(func):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for target in targets:
                    if isinstance(target, (ast.Attribute,
                                           ast.Subscript)):
                        root = _root_name(target)
                        if root in tainted:
                            flag(node, dotted(target)
                                 or f"{root}[...]")
            elif isinstance(node, ast.Delete):
                for target in node.targets:
                    if isinstance(target, (ast.Attribute,
                                           ast.Subscript)):
                        root = _root_name(target)
                        if root in tainted:
                            flag(node, dotted(target)
                                 or f"{root}[...]")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                # The owner is message state whether it is an attribute
                # chain (``message.values.append``) or a local aliasing
                # one (``vals = message.values; vals.append``) -- taint
                # covers both, and copies (``list(...)``) never taint.
                root = _root_name(node.func.value)
                if root in tainted:
                    flag(node, dotted(node.func))


def check(project: Project):
    findings: list = []
    for mod, cls in _actor_classes(project):
        if not _in_scope(mod.path):
            continue
        if not focused(project, mod.path):
            continue
        closure = _handler_closure(cls)
        if not closure:
            continue
        _check_alias1001(mod, cls, closure, findings)
        _check_alias1002(mod, cls, closure, findings)
    return findings


register_rules(RULES, check)
