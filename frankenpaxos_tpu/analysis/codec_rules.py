"""COD3xx: wire-codec exhaustiveness and encode/decode symmetry.

The runtime's ``HybridSerializer`` encodes registered message types with
fixed-layout binary codecs and silently pickles everything else
(runtime/serializer.py). That fallback is deliberate for cold-path
messages -- but it means a NEW hot-path message (or a codec whose
encode and decode drift apart) fails soft: the wire still works, just
slower or subtly wrong. These rules make the codec surface explicit:

  * COD301 -- every message dataclass a protocol actually SENDS (it
    appears in a ``send``/``send_no_flush``/``broadcast`` call) in a
    package that registers codecs must have a registered codec.
    Intentionally-pickled cold-path messages are grandfathered in the
    checked-in baseline with this rule ID (see docs/ANALYSIS.md).
  * COD302 -- for each codec class: the field set ``encode`` reads off
    the message, the field set ``decode`` passes to the constructor,
    and the dataclass's declared fields must all agree. A field added
    to a message but not to its codec -- or encoded but dropped on
    decode -- is caught here instead of in production.
"""

from __future__ import annotations

import ast
import os

from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focus_touches,
    Project,
    register_rules,
)

RULES = {
    "COD301": "protocol-sent message dataclass has no registered codec",
    "COD302": "codec encode/decode field sets disagree with the message",
}

#: ``_wal_send`` is the durable roles' deferred-send alias (held for
#: the drain's group commit, then sent): messages routed through it
#: still cross the wire, so COD301 exhaustiveness must see them.
_SEND_NAMES = frozenset({"send", "send_no_flush", "broadcast",
                         "_wal_send"})

#: Where COD3xx findings anchor: codec modules and the message-class
#: modules next to them. Diff-aware runs skip the registry scan when
#: the focus closure cannot hold a finding (core.focus_touches).
_FINDING_SURFACE = ("/election/", "/ingest/", "/protocols/",
                    "/reconfig/", "/runtime/", "/serve/", "/wal/",
                    "heartbeat.py")


def _is_dataclass(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        d = dotted(dec)
        if d.split(".")[-1] == "dataclass":
            return True
    return False


def _fields(cls: ast.ClassDef) -> list:
    """Declared dataclass field names, in order."""
    out = []
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            ann = dotted(node.annotation)
            if ann.split(".")[-1] == "ClassVar":
                continue
            out.append(node.target.id)
    return out


def _codec_classes(project: Project) -> list:
    """Every codec class: (Module, ClassDef, message_type dotted name).

    The dotted name is as written at the assignment (``Phase2b``,
    ``ur.ClientReply``); :func:`_resolve_message_class` resolves it
    through the codec module's imports -- several protocols define
    same-named message classes (Phase2a, ClientReply), so a global
    name index would check codecs against the wrong dataclass."""
    cached = getattr(project, "_codec_classes_cache", None)
    if cached is not None:
        return cached
    out = []
    for mod in project:
        for node in cached_walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            assigns = {stmt.targets[0].id: stmt.value
                       for stmt in node.body
                       if isinstance(stmt, ast.Assign)
                       and len(stmt.targets) == 1
                       and isinstance(stmt.targets[0], ast.Name)}
            # A concrete codec declares BOTH message_type and tag;
            # encode/decode may live in a shared base class
            # (epaxos _PhaseCodec-style layouts).
            if "message_type" not in assigns or "tag" not in assigns:
                continue
            msg = dotted(assigns["message_type"])
            if msg:
                out.append((mod, node, msg))
    project._codec_classes_cache = out
    return out


#: Per-module {class name: ClassDef} maps for :func:`_find_method`,
#: keyed by tree identity (the core._ALIAS_CACHE pinning contract) --
#: it runs per (codec class, method) and must not re-walk the module.
_MODULE_CLASSES_CACHE: dict = {}


def _find_method(mod, cls: ast.ClassDef, name: str):
    """``name`` method on ``cls`` or a same-module base (one level of
    the shared-layout pattern)."""
    classes = _MODULE_CLASSES_CACHE.get(id(mod.tree))
    if classes is None:
        classes = _MODULE_CLASSES_CACHE[id(mod.tree)] = {
            n.name: n for n in cached_walk(mod.tree)
            if isinstance(n, ast.ClassDef)}
    seen: set = set()
    stack = [cls.name]
    while stack:
        cur = stack.pop(0)
        if cur in seen or cur not in classes:
            continue
        seen.add(cur)
        node = classes[cur]
        for n in node.body:
            if isinstance(n, ast.FunctionDef) and n.name == name:
                return n
        stack.extend(dotted(b).split(".")[-1] for b in node.bases)
    return None


def _class_in_module(project: Project, mod, name: str,
                     follow: int = 2) -> tuple | None:
    """A dataclass ``name`` defined in ``mod``, following re-exports
    (``from x import name``) up to ``follow`` hops. Memoized on the
    project: the flow/codec global passes resolve the same
    (module, name) pairs repeatedly and trees never change."""
    cache = getattr(project, "_class_in_module_cache", None)
    if cache is None:
        cache = project._class_in_module_cache = {}
    key = (mod.path, name, follow)
    if key in cache:
        return cache[key]
    cache[key] = found = _class_in_module_uncached(project, mod, name,
                                                   follow)
    return found


def _class_in_module_uncached(project: Project, mod, name: str,
                              follow: int) -> tuple | None:
    for node in cached_walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == name \
                and _is_dataclass(node):
            return (mod, node)
    if follow > 0:
        for node in mod.tree.body:
            if isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if (a.asname or a.name) == name:
                        src = project.by_name.get(node.module)
                        if src is not None:
                            return _class_in_module(
                                project, src, a.name, follow - 1)
    return None


def _resolve_message_class(project: Project, codec_mod,
                           name: str) -> tuple | None:
    """(Module, ClassDef) for a codec's ``message_type`` expression,
    resolved through the codec module's imports; None when statically
    unresolvable (e.g. a namespace passed as a function parameter)."""
    from frankenpaxos_tpu.analysis.core import import_aliases

    parts = name.split(".")
    aliases = import_aliases(codec_mod.tree, codec_mod.name)
    if len(parts) == 1:
        found = _class_in_module(project, codec_mod, parts[0])
        if found:
            return found
        target = aliases.get(parts[0])
        if target:
            parts = target.split(".")
        else:
            return None
    else:
        target = aliases.get(parts[0])
        if target is None:
            return None  # e.g. `ns.Msg` where ns is a runtime value
        parts = target.split(".") + parts[1:]
    # parts is now fully qualified: find the longest module prefix.
    for split in range(len(parts) - 1, 0, -1):
        mod = project.by_name.get(".".join(parts[:split]))
        if mod is not None and split == len(parts) - 1:
            return _class_in_module(project, mod, parts[-1])
    return None


def _module_funcs(mod) -> dict:
    return {n.name: n for n in mod.tree.body
            if isinstance(n, ast.FunctionDef)}


def _attr_reads(func: ast.AST, param: str) -> set:
    return {node.attr for node in cached_walk(func)
            if isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == param}


def _encode_reads(mod, cls: ast.ClassDef) -> set | None:
    """Top-level message fields the codec's encode method reads --
    directly, or via a module-level helper the whole message is passed
    to (``_put_reply(out, message)``). None when no encode is found."""
    encode = _find_method(mod, cls, "encode")
    if encode is None:
        return None
    args = [a.arg for a in encode.args.args if a.arg != "self"]
    if len(args) < 2:
        return set()
    msg = args[1]  # encode(self, out, message)
    reads = _attr_reads(encode, msg)
    helpers = _module_funcs(mod)
    for node in cached_walk(encode):
        if not isinstance(node, ast.Call):
            continue
        helper = helpers.get(dotted(node.func))
        if helper is None:
            continue
        for pos, arg in enumerate(node.args):
            if isinstance(arg, ast.Name) and arg.id == msg \
                    and pos < len(helper.args.args):
                reads |= _attr_reads(helper,
                                     helper.args.args[pos].arg)
    return reads


def _decode_fields(mod, cls: ast.ClassDef, message: str,
                   declared: list) -> list | None:
    """Field sets the decode method passes to the message constructor
    (one per constructor call site -- a shared-layout decode may build
    the message differently per branch). Construction is matched by the
    message's own name or via ``self.message_type(...)``; a one-hop
    module-level helper call is followed. None when no construction is
    statically visible."""
    decode = _find_method(mod, cls, "decode")
    if decode is None:
        return None
    helpers = _module_funcs(mod)
    scopes = [decode] + [helpers[dotted(n.func)]
                         for n in cached_walk(decode)
                         if isinstance(n, ast.Call)
                         and dotted(n.func) in helpers]
    for scope in scopes:
        sets = []
        for node in cached_walk(scope):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name.split(".")[-1] != message \
                    and name not in ("self.message_type",
                                     "cls.message_type"):
                continue
            fields = {kw.arg for kw in node.keywords if kw.arg}
            fields.update(declared[i] for i in range(len(node.args))
                          if i < len(declared))
            sets.append(fields)
        if sets:
            return sets
    return None


def _package_dataclasses(project: Project, pkg_dir: str) -> dict:
    out: dict = {}
    for mod in project:
        if os.path.dirname(mod.path) == pkg_dir:
            for node in cached_walk(mod.tree):
                if isinstance(node, ast.ClassDef) \
                        and _is_dataclass(node):
                    out.setdefault(node.name, (mod, node))
    return out


def _sent_types(project: Project, pkg_dir: str, classes: dict) -> set:
    """Message class names that appear in send/broadcast calls within
    the package (directly constructed, or via a one-hop local alias).
    Memoized per package dir on the project (one scan per protocol,
    not one per rule that asks)."""
    cache = getattr(project, "_codec_sent_types_cache", None)
    if cache is None:
        cache = project._codec_sent_types_cache = {}
    if pkg_dir in cache:
        return cache[pkg_dir]
    cache[pkg_dir] = sent = _sent_types_uncached(project, pkg_dir,
                                                 classes)
    return sent


def _sent_types_uncached(project: Project, pkg_dir: str,
                         classes: dict) -> set:
    sent: set = set()
    for mod in project:
        if os.path.dirname(mod.path) != pkg_dir:
            continue
        for func in cached_walk(mod.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            local_types: dict = {}
            for node in cached_walk(func):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    name = dotted(node.value.func).split(".")[-1]
                    if name in classes:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                local_types[t.id] = name
            for node in cached_walk(func):
                if not isinstance(node, ast.Call):
                    continue
                if dotted(node.func).split(".")[-1] not in _SEND_NAMES:
                    continue
                for arg in node.args:
                    if isinstance(arg, ast.Call):
                        name = dotted(arg.func).split(".")[-1]
                        if name in classes:
                            sent.add(name)
                    elif isinstance(arg, ast.Name) \
                            and arg.id in local_types:
                        sent.add(local_types[arg.id])
    return sent


def check(project: Project):
    if not focus_touches(project, _FINDING_SURFACE):
        return []
    findings: list = []
    codecs = _codec_classes(project)

    # COD302: encode/decode/declared field symmetry.
    for mod, cls, message_dotted in codecs:
        message = message_dotted.split(".")[-1]
        entry = _resolve_message_class(project, mod, message_dotted)
        if entry is None:
            continue
        msg_mod, msg_cls = entry
        declared = _fields(msg_cls)
        if not declared:
            continue
        reads = _encode_reads(mod, cls)
        decode_sets = _decode_fields(mod, cls, message, declared)
        if reads is not None:
            missing_enc = [f for f in declared if f not in reads]
            if missing_enc:
                findings.append(Finding(
                    rule="COD302", file=mod.path, line=cls.lineno,
                    scope=cls.name, detail=f"encode:{message}",
                    message=f"encode never reads {message} field(s) "
                            f"{missing_enc}: encoded frames silently "
                            f"drop them"))
        if decode_sets is not None:
            union = set().union(*decode_sets)
            common = set.intersection(*decode_sets)
            missing_dec = [f for f in declared if f not in union]
            extra_dec = sorted(common - set(declared))
            if missing_dec:
                findings.append(Finding(
                    rule="COD302", file=mod.path, line=cls.lineno,
                    scope=cls.name, detail=f"decode:{message}",
                    message=f"decode never sets {message} field(s) "
                            f"{missing_dec}"))
            if extra_dec:
                findings.append(Finding(
                    rule="COD302", file=mod.path, line=cls.lineno,
                    scope=cls.name, detail=f"decode-extra:{message}",
                    message=f"decode passes unknown field(s) "
                            f"{extra_dec} to {message}"))

    # COD301: exhaustiveness per codec-registering package.
    pkg_dirs = sorted({os.path.dirname(mod.path)
                       for mod, _, _ in codecs})
    for pkg_dir in pkg_dirs:
        registered = {message.split(".")[-1]
                      for mod, _, message in codecs
                      if os.path.dirname(mod.path) == pkg_dir}
        classes = _package_dataclasses(project, pkg_dir)
        for name in sorted(_sent_types(project, pkg_dir, classes)
                           - registered):
            msg_mod, msg_cls = classes[name]
            findings.append(Finding(
                rule="COD301", file=msg_mod.path, line=msg_cls.lineno,
                scope=name, detail=name,
                message=f"{name} is sent by this protocol but has no "
                        f"registered codec: it rides the pickle "
                        f"fallback (slower, and refused under "
                        f"set_pickle_fallback(False))"))
    return findings


register_rules(RULES, check)
