"""A lightweight, name-based call graph over one Project.

Purpose-built for the TPU hot-path rules: given entry points (every
``on_drain``, the run-pipeline message handlers, the ``ops/`` kernels),
compute the over-approximate set of package functions reachable from
them. Resolution is intentionally duck-typed -- ``self.f()`` resolves
within the class (and name-matched base classes), ``mod.f()`` through
the import table, and ``obj.f()`` to every package method named ``f``
-- because a checker would rather over-flag (the pragma/baseline
machinery curates) than silently miss a host sync behind a strategy
interface like ``QuorumTracker``.
"""

from __future__ import annotations

import ast
import dataclasses

from frankenpaxos_tpu.analysis.core import (
    dotted,
    import_aliases,
    Module,
    Project,
    qualname_index,
)

#: Method names never duck-resolved: builtin-collection noise that would
#: wire the graph to unrelated classes. Package functions with these
#: names are still reachable via self./module-qualified calls.
_DUCK_STOPLIST = frozenset({
    "append", "extend", "pop", "popleft", "add", "discard", "clear",
    "keys", "values", "items", "get", "set", "setdefault", "update",
    "sort", "tolist", "join", "split", "read", "write", "close", "wait",
    "put", "inc", "observe", "labels", "time", "info", "debug", "warn",
    "error", "copy", "count", "index", "format", "strip", "encode",
    "decode", "to_bytes", "from_bytes", "any", "all", "max", "min",
})


@dataclasses.dataclass
class FuncInfo:
    """One function/method definition in the project."""

    module: Module
    node: ast.AST            # FunctionDef / AsyncFunctionDef
    qualname: str            # "Class.method" or "func"
    cls: str | None          # enclosing class name, if a method

    @property
    def ref(self) -> str:
        return f"{self.module.path}::{self.qualname}"

    @property
    def name(self) -> str:
        return self.node.name


class CallGraph:
    def __init__(self, project: Project):
        self.project = project
        # function ref -> FuncInfo
        self.funcs: dict[str, FuncInfo] = {}
        # method name -> [refs] (for duck resolution)
        self.by_method: dict[str, list] = {}
        # (module path, bare name) -> ref (module-level functions)
        self.module_level: dict[tuple, str] = {}
        # class name -> {method name -> ref} (name-keyed; collisions
        # keep every definition via by_method)
        self.class_methods: dict[str, dict] = {}
        # class name -> [base class names] (package-wide, name-keyed)
        self.bases: dict[str, list] = {}
        self._aliases: dict[str, dict] = {}
        for mod in project:
            self._index_module(mod)

    # --- indexing ---------------------------------------------------------
    def _index_module(self, mod: Module) -> None:
        self._aliases[mod.path] = import_aliases(mod.tree, mod.name)
        quals = qualname_index(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual = quals[id(node)]
            parts = qual.split(".")
            cls = parts[-2] if len(parts) >= 2 else None
            info = FuncInfo(module=mod, node=node, qualname=qual, cls=cls)
            self.funcs[info.ref] = info
            self.by_method.setdefault(node.name, []).append(info.ref)
            if cls is None and len(parts) == 1:
                self.module_level[(mod.path, node.name)] = info.ref
            if cls is not None and len(parts) == 2:
                self.class_methods.setdefault(cls, {})[node.name] = \
                    info.ref
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                self.bases[node.name] = [
                    dotted(b).split(".")[-1] for b in node.bases]

    # --- resolution -------------------------------------------------------
    def _method_in_hierarchy(self, cls: str, name: str,
                             seen: set | None = None) -> str | None:
        seen = seen or set()
        while cls and cls not in seen:
            seen.add(cls)
            ref = self.class_methods.get(cls, {}).get(name)
            if ref is not None:
                return ref
            parents = self.bases.get(cls, [])
            for p in parents[1:]:
                ref = self._method_in_hierarchy(p, name, seen)
                if ref is not None:
                    return ref
            cls = parents[0] if parents else ""
        return None

    def resolve_call(self, caller: FuncInfo, call: ast.Call) -> list:
        """Possible callee refs for ``call`` made inside ``caller``."""
        name = dotted(call.func)
        if not name:
            return []
        parts = name.split(".")
        aliases = self._aliases.get(caller.module.path, {})

        # self.f() / cls.f(): resolve within the class hierarchy.
        if parts[0] in ("self", "cls") and len(parts) == 2 and caller.cls:
            ref = self._method_in_hierarchy(caller.cls, parts[1])
            return [ref] if ref else self._duck(parts[1])
        if parts[0] in ("self", "cls"):
            # self.obj.f(): duck-resolve the trailing method.
            return self._duck(parts[-1]) if len(parts) > 2 else []

        # Bare f(): module-level function here, or an import alias.
        if len(parts) == 1:
            ref = self.module_level.get((caller.module.path, parts[0]))
            if ref is not None:
                return [ref]
            target = aliases.get(parts[0])
            if target:
                return self._resolve_qualified(target)
            # A locally-defined nested function.
            prefix = caller.qualname + "." + parts[0]
            ref = f"{caller.module.path}::{prefix}"
            return [ref] if ref in self.funcs else []

        # mod.f() / pkg.mod.f() through the import table.
        target = aliases.get(parts[0])
        if target:
            return self._resolve_qualified(
                ".".join([target] + parts[1:]))

        # ClassName.f() on a class defined in this project.
        if parts[0] in self.class_methods and len(parts) == 2:
            ref = self._method_in_hierarchy(parts[0], parts[1])
            return [ref] if ref else []

        # obj.f(): duck typing on the method name.
        return self._duck(parts[-1])

    def _resolve_qualified(self, qualified: str) -> list:
        """Resolve a fully-qualified dotted name against project
        modules: ``pkg.mod.func`` or ``pkg.mod.Class.method``."""
        parts = qualified.split(".")
        for split in range(len(parts) - 1, 0, -1):
            mod = self.project.by_name.get(".".join(parts[:split]))
            if mod is None:
                continue
            rest = parts[split:]
            ref = f"{mod.path}::{'.'.join(rest)}"
            if ref in self.funcs:
                return [ref]
            if len(rest) == 1:
                # A symbol re-exported through __init__: duck on name.
                return [r for r in self.by_method.get(rest[0], ())]
        return []

    def _duck(self, method: str) -> list:
        if method in _DUCK_STOPLIST or method.startswith("__"):
            return []
        return list(self.by_method.get(method, ()))

    # --- reachability -----------------------------------------------------
    def reachable(self, roots: list) -> dict:
        """BFS from ``roots`` (function refs); returns
        ``{ref: root_ref}`` -- which root first reached each function."""
        out: dict = {}
        frontier = [(r, r) for r in roots if r in self.funcs]
        while frontier:
            nxt = []
            for ref, root in frontier:
                if ref in out:
                    continue
                out[ref] = root
                info = self.funcs[ref]
                for node in ast.walk(info.node):
                    if isinstance(node, ast.Call):
                        for callee in self.resolve_call(info, node):
                            if callee not in out:
                                nxt.append((callee, root))
            frontier = nxt
        return out
