"""A lightweight, name-based call graph over one Project.

Purpose-built for the TPU hot-path rules: given entry points (every
``on_drain``, the run-pipeline message handlers, the ``ops/`` kernels),
compute the over-approximate set of package functions reachable from
them. Resolution is intentionally duck-typed -- ``self.f()`` resolves
within the class (and name-matched base classes), ``mod.f()`` through
the import table, and ``obj.f()`` to every package method named ``f``
-- because a checker would rather over-flag (the pragma/baseline
machinery curates) than silently miss a host sync behind a strategy
interface like ``QuorumTracker``.
"""

from __future__ import annotations

import ast
import dataclasses

from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    import_aliases,
    is_sanitizer_call,
    Module,
    Project,
    qualname_index,
)

#: Method names never duck-resolved: builtin-collection noise that would
#: wire the graph to unrelated classes. Package functions with these
#: names are still reachable via self./module-qualified calls.
#: Callees through which a param does NOT escape for the ownership
#: fixpoint: deployed sends serialize their message argument at the
#: send boundary (a copy), so buffer obligations end there. The
#: queued-payload mutation window that remains is OWN1102's job.
_ESCAPE_SKIP_CALLEES = frozenset({
    "send", "send_no_flush", "_wal_send", "broadcast", "send_batch",
})

_DUCK_STOPLIST = frozenset({
    "append", "extend", "pop", "popleft", "add", "discard", "clear",
    "keys", "values", "items", "get", "set", "setdefault", "update",
    "sort", "tolist", "join", "split", "read", "write", "close", "wait",
    "put", "inc", "observe", "labels", "time", "info", "debug", "warn",
    "error", "copy", "count", "index", "format", "strip", "encode",
    "decode", "to_bytes", "from_bytes", "any", "all", "max", "min",
})


@dataclasses.dataclass
class FuncInfo:
    """One function/method definition in the project."""

    module: Module
    node: ast.AST            # FunctionDef / AsyncFunctionDef
    qualname: str            # "Class.method" or "func"
    cls: str | None          # enclosing class name, if a method

    @property
    def ref(self) -> str:
        return f"{self.module.path}::{self.qualname}"

    @property
    def name(self) -> str:
        return self.node.name


class CallGraph:
    def __init__(self, project: Project):
        self.project = project
        # function ref -> FuncInfo
        self.funcs: dict[str, FuncInfo] = {}
        # method name -> [refs] (for duck resolution)
        self.by_method: dict[str, list] = {}
        # (module path, bare name) -> ref (module-level functions)
        self.module_level: dict[tuple, str] = {}
        # class name -> {method name -> ref} (name-keyed; collisions
        # keep every definition via by_method)
        self.class_methods: dict[str, dict] = {}
        # class name -> [base class names] (package-wide, name-keyed)
        self.bases: dict[str, list] = {}
        self._aliases: dict[str, dict] = {}
        for mod in project:
            self._index_module(mod)

    # --- indexing ---------------------------------------------------------
    def _index_module(self, mod: Module) -> None:
        self._aliases[mod.path] = import_aliases(mod.tree, mod.name)
        quals = qualname_index(mod.tree)
        for node in cached_walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            qual = quals[id(node)]
            parts = qual.split(".")
            cls = parts[-2] if len(parts) >= 2 else None
            info = FuncInfo(module=mod, node=node, qualname=qual, cls=cls)
            self.funcs[info.ref] = info
            self.by_method.setdefault(node.name, []).append(info.ref)
            if cls is None and len(parts) == 1:
                self.module_level[(mod.path, node.name)] = info.ref
            if cls is not None and len(parts) == 2:
                self.class_methods.setdefault(cls, {})[node.name] = \
                    info.ref
        for node in cached_walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                self.bases[node.name] = [
                    dotted(b).split(".")[-1] for b in node.bases]

    # --- resolution -------------------------------------------------------
    def _method_in_hierarchy(self, cls: str, name: str,
                             seen: set | None = None) -> str | None:
        seen = seen or set()
        while cls and cls not in seen:
            seen.add(cls)
            ref = self.class_methods.get(cls, {}).get(name)
            if ref is not None:
                return ref
            parents = self.bases.get(cls, [])
            for p in parents[1:]:
                ref = self._method_in_hierarchy(p, name, seen)
                if ref is not None:
                    return ref
            cls = parents[0] if parents else ""
        return None

    def resolve_call(self, caller: FuncInfo, call: ast.Call) -> list:
        """Possible callee refs for ``call`` made inside ``caller``."""
        name = dotted(call.func)
        if not name:
            return []
        parts = name.split(".")
        aliases = self._aliases.get(caller.module.path, {})

        # self.f() / cls.f(): resolve within the class hierarchy.
        if parts[0] in ("self", "cls") and len(parts) == 2 and caller.cls:
            ref = self._method_in_hierarchy(caller.cls, parts[1])
            return [ref] if ref else self._duck(parts[1])
        if parts[0] in ("self", "cls"):
            # self.obj.f(): duck-resolve the trailing method.
            return self._duck(parts[-1]) if len(parts) > 2 else []

        # Bare f(): module-level function here, or an import alias.
        if len(parts) == 1:
            ref = self.module_level.get((caller.module.path, parts[0]))
            if ref is not None:
                return [ref]
            target = aliases.get(parts[0])
            if target:
                return self._resolve_qualified(target)
            # A locally-defined nested function.
            prefix = caller.qualname + "." + parts[0]
            ref = f"{caller.module.path}::{prefix}"
            return [ref] if ref in self.funcs else []

        # mod.f() / pkg.mod.f() through the import table.
        target = aliases.get(parts[0])
        if target:
            return self._resolve_qualified(
                ".".join([target] + parts[1:]))

        # ClassName.f() on a class defined in this project.
        if parts[0] in self.class_methods and len(parts) == 2:
            ref = self._method_in_hierarchy(parts[0], parts[1])
            return [ref] if ref else []

        # obj.f(): duck typing on the method name.
        return self._duck(parts[-1])

    def _resolve_qualified(self, qualified: str) -> list:
        """Resolve a fully-qualified dotted name against project
        modules: ``pkg.mod.func`` or ``pkg.mod.Class.method``."""
        parts = qualified.split(".")
        for split in range(len(parts) - 1, 0, -1):
            mod = self.project.by_name.get(".".join(parts[:split]))
            if mod is None:
                continue
            rest = parts[split:]
            ref = f"{mod.path}::{'.'.join(rest)}"
            if ref in self.funcs:
                return [ref]
            if len(rest) == 1:
                # A symbol re-exported through __init__: duck on name.
                return [r for r in self.by_method.get(rest[0], ())]
        return []

    def _duck(self, method: str) -> list:
        if method in _DUCK_STOPLIST or method.startswith("__"):
            return []
        return list(self.by_method.get(method, ()))

    # --- escape analysis (paxown) -----------------------------------------
    def escaping_params(self) -> dict:
        """``{func ref: set of param names that escape}`` -- a param
        escapes when the function stores it (or a container holding
        it) into ``self`` state, captures it in a nested def/lambda
        closure, or passes it to a callee whose own param escapes
        (computed to a fixpoint over the whole graph). A mention
        wrapped in an ownership sanitizer (``bytes(p)``,
        ``p.tobytes()``, ...) does not count, and neither does passing
        to a send (``send``/``_wal_send``/...): the deployed transport
        serializes at the send boundary, so ownership obligations end
        there (OWN1102 guards the queued-payload window separately).
        Memoized on the graph: the OWN11xx rules query it per call
        site."""
        cached = getattr(self, "_escaping_params", None)
        if cached is not None:
            return cached
        out: dict = {ref: self._direct_escapes(info)
                     for ref, info in self.funcs.items()}
        # Resolve every plain param-passing call ONCE into an edge
        # list, then fixpoint over the edges (resolution dominates the
        # cost; the fixpoint itself is cheap).
        edges: list = []  # (caller ref, caller param, callee ref, callee param)
        for ref, info in self.funcs.items():
            params = set(_param_names(info.node))
            if not params:
                continue
            for call in cached_walk(info.node):
                if not isinstance(call, ast.Call):
                    continue
                passed = _passed_params(call, params)
                if not passed:
                    continue
                for callee in self.resolve_call(info, call):
                    if self.funcs[callee].name in _ESCAPE_SKIP_CALLEES:
                        continue
                    callee_params = _param_names(self.funcs[callee].node)
                    for pos, kw, name in passed:
                        target = _bound_param(callee_params, pos, kw)
                        if target is not None:
                            edges.append((ref, name, callee, target))
        changed = True
        while changed:
            changed = False
            for ref, name, callee, target in edges:
                if target in out[callee] and name not in out[ref]:
                    out[ref].add(name)
                    changed = True
        self._escaping_params = out
        return out

    def _direct_escapes(self, info: FuncInfo) -> set:
        params = set(_param_names(info.node))
        if not params:
            return set()
        escaped: set = set()
        for node in cached_walk(info.node):
            if isinstance(node, ast.Assign):
                if any(_is_self_store(t) for t in node.targets):
                    escaped |= _unsanitized_names(node.value, params)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("append", "extend", "add",
                                       "appendleft", "setdefault",
                                       "push", "insert") and \
                    _is_self_store(node.func.value):
                for arg in node.args:
                    escaped |= _unsanitized_names(arg, params)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)) \
                    and node is not info.node:
                # Closure capture: a timer/resend callback holding the
                # param alive past this dispatch.
                for inner in cached_walk(node):
                    if isinstance(inner, ast.Name) and \
                            inner.id in params:
                        escaped.add(inner.id)
        return escaped

    # --- reachability -----------------------------------------------------
    def reachable(self, roots: list) -> dict:
        """BFS from ``roots`` (function refs); returns
        ``{ref: root_ref}`` -- which root first reached each function."""
        out: dict = {}
        frontier = [(r, r) for r in roots if r in self.funcs]
        while frontier:
            nxt = []
            for ref, root in frontier:
                if ref in out:
                    continue
                out[ref] = root
                info = self.funcs[ref]
                for node in cached_walk(info.node):
                    if isinstance(node, ast.Call):
                        for callee in self.resolve_call(info, node):
                            if callee not in out:
                                nxt.append((callee, root))
            frontier = nxt
        return out


def project_graph(project: Project) -> CallGraph:
    """One CallGraph per Project, built lazily and shared by every
    rule family that needs interprocedural resolution (the PR 7 cache
    discipline: indexing the whole package once is what keeps the
    full-run budget honest as families grow)."""
    graph = getattr(project, "_callgraph", None)
    if graph is None:
        graph = project._callgraph = CallGraph(project)
    return graph


def _param_names(node: ast.AST) -> list:
    args = node.args
    names = [a.arg for a in args.posonlyargs + args.args]
    names += [a.arg for a in args.kwonlyargs]
    return [n for n in names if n not in ("self", "cls")]


def _is_self_store(node: ast.AST) -> bool:
    """``self.X`` / ``self.X[k]`` / ``self.X.Y`` -- state that
    outlives the call."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in ("self", "cls"):
            return True
        node = node.value
    return False


def _unsanitized_names(expr: ast.AST, names: set) -> set:
    """Which of ``names`` does ``expr`` mention OUTSIDE an ownership
    sanitizer call? ``(p, k)`` mentions p; ``bytes(p)`` does not."""
    found: set = set()

    def visit(node):
        if is_sanitizer_call(node):
            return
        if isinstance(node, ast.Name) and node.id in names:
            found.add(node.id)
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return found


def _passed_params(call: ast.Call, params: set) -> list:
    """Params of the CALLER passed plainly to ``call``: a list of
    ``(positional index or None, keyword or None, param name)``.
    Sanitized mentions (``bytes(p)``) and derived expressions do not
    count -- only a bare name or a container literal holding one."""
    out: list = []
    for i, arg in enumerate(call.args):
        for name in _unsanitized_names(arg, params) \
                if isinstance(arg, (ast.Tuple, ast.List, ast.Name)) \
                else ():
            out.append((i, None, name))
    for kw in call.keywords:
        if kw.arg is None:
            continue
        for name in _unsanitized_names(kw.value, params) \
                if isinstance(kw.value,
                              (ast.Tuple, ast.List, ast.Name)) \
                else ():
            out.append((None, kw.arg, name))
    return out


def _bound_param(callee_params: list, pos, kw):
    """The callee param a call argument binds to. ``callee_params``
    has self/cls stripped, which matches the common bound-call shape
    (``self.helper(p)`` / ``obj.helper(p)``); an unbound
    ``Class.helper(obj, p)`` call may misbind by one slot -- an
    accepted over/under-approximation for a style this codebase does
    not use."""
    if kw is not None:
        return kw if kw in callee_params else None
    if pos is None or pos >= len(callee_params):
        return None
    return callee_params[pos]
