"""DEV12xx: host<->device transfer discipline for the multi-chip path.

The TPU2xx family guards the *blocking* host syncs
(``block_until_ready``, ``device_get``, ``np.asarray`` of an async
dispatch). This family guards the TRANSFER DISCIPLINE the multi-chip
flagship needs on the same hot-path reachable set (every ``on_drain``,
``ops/`` kernels, run-pipeline handlers):

  * DEV1201 -- a device->host scalar fetch in hot-path code outside
    the sanctioned fetch points: ``.item()`` on an array, or
    ``float()``/``int()``/``bool()`` coercion of a jax value. Each one
    is a synchronous device round-trip per call -- per message, that
    is the batching cliff.
  * DEV1202 -- a host->device copy (``jnp.asarray``/``jnp.array``/
    ``device_put``) inside a loop on the drain path: per-message H2D
    transfers instead of building columns once and transferring the
    column. The paxingest column planes exist so this never happens.
  * DEV1203 -- ``jax.device_put`` without an explicit
    device/``NamedSharding`` placement in mesh-aware code
    (``ops/`` + ``bench/pipeline``): an unplaced put lands on the
    default device and silently de-shards a mesh array on the next
    collective.

Sanctioned fetch points (drain-boundary collectors, flush timers)
carry ``# paxlint: disable=DEV1201`` with the reason, exactly like the
TPU20x pragma discipline.
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.callgraph import project_graph
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    import_aliases,
    Project,
    qualname_index,
    register_rules,
)
from frankenpaxos_tpu.analysis.hotpath_rules import _roots

RULES = {
    "DEV1201": "device->host scalar fetch (.item()/float()/bool()) in "
               "hot-path code outside a sanctioned fetch point",
    "DEV1202": "per-message host->device copy inside a drain-path "
               "loop (build columns, transfer once)",
    "DEV1203": "jax.device_put without an explicit device/sharding in "
               "mesh-aware code (ops/, bench/pipeline)",
}

#: Host->device transfer call leaves (DEV1202/1203).
_H2D_LEAVES = frozenset({"device_put", "asarray", "array"})

#: Files that are mesh-aware by contract: every array placement there
#: must say WHERE (DEV1203).
_MESH_SCOPES = ("/ops/", "bench/pipeline")


def _is_jaxish(name: str, aliases: dict) -> bool:
    """Does the dotted call/value name resolve into jax/jnp?"""
    root = name.split(".")[0]
    target = aliases.get(root, root)
    return target in ("jax", "jnp") or target.startswith("jax.")


def _jax_locals(func: ast.AST, aliases: dict) -> set:
    """Locals assigned from a jax/jnp call (device values)."""
    out: set = set()
    for node in cached_walk(func):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            if _is_jaxish(dotted(node.value.func), aliases):
                out.add(node.targets[0].id)
    return out


def _loop_spans(func: ast.AST) -> list:
    """(start, end) line spans of for/while loop bodies in ``func``."""
    return [(n.lineno, getattr(n, "end_lineno", n.lineno))
            for n in cached_walk(func)
            if isinstance(n, (ast.For, ast.AsyncFor, ast.While))]


def check(project: Project):
    findings: list = []
    graph = project_graph(project)
    roots = _roots(project, graph)
    reachable = graph.reachable(list(roots))

    def flag(rule, mod, node, scope, detail, message):
        findings.append(Finding(
            rule=rule, file=mod.path, line=node.lineno, scope=scope,
            detail=detail, message=message))

    for ref, root in reachable.items():
        info = graph.funcs[ref]
        mod = info.module
        if not focused(project, mod.path):
            continue
        root_name = graph.funcs[root].qualname
        via = roots.get(root)
        how = (f"reachable from {root_name} ({via})"
               if ref != root else f"a hot-path root ({via})")
        aliases = import_aliases(mod.tree, mod.name)
        jax_locals = _jax_locals(info.node, aliases)
        loops = _loop_spans(info.node)
        for node in cached_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            leaf = d.split(".")[-1]
            # DEV1201: scalar fetches.
            if leaf == "item" and not node.args and not node.keywords \
                    and isinstance(node.func, ast.Attribute):
                flag("DEV1201", mod, node, info.qualname, d,
                     f".item() is a synchronous device->host scalar "
                     f"fetch in code {how}; fetch once at the drain "
                     f"boundary (or keep the value on device)")
            elif leaf in ("float", "int", "bool") and d == leaf and \
                    len(node.args) == 1:
                arg = node.args[0]
                src = None
                if isinstance(arg, ast.Call) and \
                        _is_jaxish(dotted(arg.func), aliases):
                    src = dotted(arg.func)
                elif isinstance(arg, ast.Name) and arg.id in jax_locals:
                    src = arg.id
                if src is not None:
                    flag("DEV1201", mod, node, info.qualname,
                         f"{leaf}({src})",
                         f"{leaf}() of device value {src} is an "
                         f"implicit device->host fetch in code {how}; "
                         f"fetch once at the drain boundary")
            # DEV1202: per-message H2D copies in a loop.
            elif leaf in _H2D_LEAVES and _is_jaxish(d, aliases) and \
                    any(s <= node.lineno <= e for s, e in loops):
                flag("DEV1202", mod, node, info.qualname, d,
                     f"{d} inside a loop in code {how} is a "
                     f"per-message host->device copy; build the "
                     f"column on host and transfer it once per drain")

    # DEV1203: unplaced device_put in mesh-aware modules (file-scoped,
    # not reachability-scoped: the contract is on the code's home).
    for mod in project:
        if not any(seg in mod.path for seg in _MESH_SCOPES):
            continue
        if not focused(project, mod.path):
            continue
        aliases = import_aliases(mod.tree, mod.name)
        quals = None
        for node in cached_walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d.split(".")[-1] != "device_put" or \
                    not _is_jaxish(d, aliases):
                continue
            placed = len(node.args) >= 2 or any(
                kw.arg in ("device", "sharding", "dst_sharding")
                for kw in node.keywords)
            if placed:
                continue
            if quals is None:
                quals = qualname_index(mod.tree)
            scope = "<module>"
            for d_node in cached_walk(mod.tree):
                if isinstance(d_node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)) and \
                        d_node.lineno <= node.lineno <= \
                        getattr(d_node, "end_lineno", d_node.lineno):
                    scope = quals[id(d_node)]
            flag("DEV1203", mod, node, scope, d,
                 f"{d} without an explicit device/NamedSharding in "
                 f"mesh-aware code; an unplaced put lands on the "
                 f"default device and de-shards the array on the "
                 f"next collective")
    return findings


register_rules(RULES, check)
