"""Diff-aware paxlint (``--changed-since REF``).

Findings are module-local only in where they are REPORTED -- computing
them still needs the whole project (the callgraph, the class index,
the codec registry scan). So diff-aware mode parses everything exactly
like a full run and narrows only the per-module rule work plus the
final report, via :attr:`Project.focus`: the transitive closure of
modules that import (directly or through any chain) a changed module.
A focused run is therefore by construction the full run restricted to
the closure -- tests/test_analysis_cli.py proves the equivalence on a
synthetic diff.

Changes outside the analyzed package (tests, docs, CI, and the
analysis package itself -- rule changes can alter ANY module's
findings) conservatively disable focusing: the run degrades to a full
run rather than guessing.
"""

from __future__ import annotations

import ast
import subprocess

from frankenpaxos_tpu.analysis.core import cached_walk


def changed_paths(root: str, ref: str) -> list:
    """Repo-relative paths changed since ``ref`` (committed or not)."""
    out = subprocess.run(
        ["git", "diff", "--name-only", ref, "--"],
        cwd=root, capture_output=True, text=True, check=True)
    return sorted({line.strip() for line in out.stdout.splitlines()
                   if line.strip()})


def _imported_project_modules(project, mod) -> set:
    """Dotted names of project modules ``mod`` imports. ``from pkg.a
    import b`` counts both ``pkg.a`` and ``pkg.a.b`` (either may be
    the module); relative imports resolve against ``mod.name``."""
    names: set = set()

    def note(dotted: str) -> None:
        while dotted:
            if dotted in project.by_name:
                names.add(dotted)
            dotted = dotted.rpartition(".")[0]

    for node in cached_walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                note(alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                parts = mod.name.split(".")
                # level=1 is the module's own package: drop the leaf
                # module name -- except for __init__ modules, whose
                # dotted name (sans __init__) already IS the package.
                drop = node.level - (
                    1 if mod.path.endswith("__init__.py") else 0)
                if drop:
                    parts = parts[:len(parts) - drop]
                base = ".".join(parts + ([node.module]
                                         if node.module else []))
            else:
                base = node.module or ""
            note(base)
            for alias in node.names:
                if base:
                    note(f"{base}.{alias.name}")
    return names


def affected_closure(project, changed: list):
    """The repo-relative path set diff-aware mode should focus on, or
    ``None`` for "run everything" (a change outside the package)."""
    pkg_prefix = f"{project.package}/"
    for path in changed:
        if path.startswith(pkg_prefix) and path not in project.modules:
            # Inside the package but not a parsed module: the analysis
            # package itself, or a non-Python asset rules may read.
            return None
    seeds = {path for path in changed if path in project.modules}
    if not seeds and any(not p.startswith(pkg_prefix) for p in changed):
        # Only out-of-package changes (tests/docs/CI): nothing the
        # rules look at changed, but equivalence with a full run is
        # exactly "no findings can have changed", so report none.
        return set()

    # Reverse import edges: imported module name -> importer paths.
    importers: dict = {}
    for mod in project:
        for name in _imported_project_modules(project, mod):
            importers.setdefault(name, set()).add(mod.path)

    closure = set(seeds)
    frontier = list(seeds)
    while frontier:
        mod = project.modules[frontier.pop()]
        for path in importers.get(mod.name, ()):
            if path not in closure:
                closure.add(path)
                frontier.append(path)
    return closure
