"""Checked-in baseline of grandfathered paxlint findings.

The baseline is a JSON list of finding keys -- ``(rule, file, scope,
detail)`` plus the human message for review -- NOT line numbers, so it
survives unrelated edits. Semantics:

  * a finding whose key is in the baseline is *suppressed* (listed in
    the report as grandfathered, with its rule ID);
  * a finding not in the baseline fails the run (exit 1);
  * a baseline entry that no longer matches any finding is *stale* and
    reported so it can be pruned (``--write-baseline`` regenerates).

Regenerate with ``python -m frankenpaxos_tpu.analysis
--write-baseline`` -- and justify any new entry in the PR; the whole
point is that silent regressions must become loud diffs here.
"""

from __future__ import annotations

import json
import os


def load(path: str) -> list:
    """Baseline entries as a list of dicts (empty when absent)."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, list):
        raise SystemExit(f"paxlint: baseline {path} is not a JSON list")
    return data


def keys(entries: list) -> set:
    return {(e["rule"], e["file"], e["scope"], e["detail"])
            for e in entries}


def write(path: str, findings: list) -> None:
    entries = [
        {"rule": f.rule, "file": f.file, "scope": f.scope,
         "detail": f.detail, "message": f.message}
        for f in findings
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entries, f, indent=1, sort_keys=True)
        f.write("\n")


def split(findings: list, baseline_entries: list) -> tuple:
    """-> (new findings, grandfathered findings, stale baseline keys)."""
    known = keys(baseline_entries)
    new = [f for f in findings if f.key not in known]
    old = [f for f in findings if f.key in known]
    live = {f.key for f in findings}
    stale = sorted(k for k in known if k not in live)
    return new, old, stale
