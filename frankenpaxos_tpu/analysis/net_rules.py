"""paxwire transport-contract rules (NET7xx).

  * NET701 -- a per-message FLUSHING send inside a loop in a
    drain-granular handler (``on_drain`` or a helper it calls): each
    iteration schedules its own flush where a batch path exists.
    ``Actor.send_batch`` (or ``send_no_flush`` + one ``flush``) ships
    the loop's messages as ONE transport batch -- one writev, adjacent
    same-type payloads coalesced into a batch frame
    (runtime/paxwire.py, docs/TRANSPORT.md).

Per-DESTINATION fan-out loops (the destination expression depends on
the loop variable: one reply array per client, one Phase2a per
acceptor group) are not flagged -- those are different connections, so
there is nothing to batch per peer; the transport's per-pass flush
already coalesces them. Only loops that push multiple messages at one
fixed destination with a flushing ``send`` per iteration are the
anti-pattern.
"""

from __future__ import annotations

import ast

from frankenpaxos_tpu.analysis.actor_rules import _actor_classes, _methods
from frankenpaxos_tpu.analysis.core import (
    cached_walk,
    dotted,
    Finding,
    focused,
    Project,
    register_rules,
)

RULES = {
    "NET701": "per-message flushing send in a loop in a drain-granular "
              "handler where a batch path exists",
}

#: Handlers whose loops are drain-granular by construction: the batch
#: boundary the whole run pipeline amortizes over.
_DRAIN_SEEDS = ("on_drain",)


def _drain_closure(cls: ast.ClassDef) -> list:
    """``on_drain`` plus every same-class helper reachable from it
    through ``self.X()`` calls."""
    methods = _methods(cls)
    seen: set = set()
    queue = [s for s in _DRAIN_SEEDS if s in methods]
    out = []
    while queue:
        name = queue.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        func = methods[name]
        out.append(func)
        for node in cached_walk(func):
            if isinstance(node, ast.Call):
                callee = dotted(node.func)
                if callee.startswith("self."):
                    queue.append(callee.split(".", 1)[1])
    return out


def _walk_same_scope(node: ast.AST):
    """Walk ``node`` without descending into nested function/class
    definitions (their bodies run in another scope/time)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield child
        yield from _walk_same_scope(child)


def _target_names(target: ast.AST) -> set:
    return {n.id for n in cached_walk(target) if isinstance(n, ast.Name)}


def _expr_names(expr: ast.AST) -> set:
    return {n.id for n in cached_walk(expr) if isinstance(n, ast.Name)}


def check(project: Project):
    findings: list = []
    for mod, cls in _actor_classes(project):
        if not focused(project, mod.path):
            continue
        for func in _drain_closure(cls):
            for loop in cached_walk(func):
                if not isinstance(loop, ast.For):
                    continue
                loop_names = _target_names(loop.target)
                for node in _walk_same_scope(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = dotted(node.func)
                    if callee == "self.send":
                        if not node.args:
                            continue
                        dst = node.args[0]
                    elif callee.endswith(".send") \
                            and isinstance(node.func, ast.Attribute) \
                            and isinstance(node.func.value, ast.Name) \
                            and node.func.value.id != "self":
                        # chan.send(...) on a channel bound outside
                        # the loop: the destination is the channel.
                        if node.func.value.id in loop_names:
                            continue
                        dst = node.func.value
                    else:
                        continue
                    if _expr_names(dst) & loop_names:
                        continue  # per-destination fan-out: fine
                    findings.append(Finding(
                        rule="NET701", file=mod.path, line=node.lineno,
                        scope=f"{cls.name}.{func.name}",
                        detail=callee,
                        message="per-message flushing send to a fixed "
                                "destination inside a drain-granular "
                                "loop: every iteration schedules its "
                                "own flush -- stage the loop's "
                                "messages and ship them with "
                                "Actor.send_batch (or send_no_flush + "
                                "one flush) so paxwire coalesces them "
                                "into one batch frame and one writev"))
    return findings


register_rules(RULES, check)
